"""Distillation layer tests.

Mirrors the reference's suite (SURVEY §4): the multi-epoch NOP-teacher
pipeline test (≙ distill_reader_test.py — ordering/epoch protocol with
ragged batches, no GPU or network model), a serving roundtrip, balance-cap
units, and a full-stack store+discovery+teacher test with churn
(≙ test_distill_reader.sh).
"""

import threading
import time

import numpy as np
import pytest

from edl_tpu.distill import (
    DistillReader,
    EchoPredictBackend,
    NopPredictBackend,
    PredictClient,
    PredictServer,
)
from edl_tpu.distill.discovery import (
    BalanceTable,
    DiscoveryClient,
    DiscoveryService,
    TeacherRegister,
)
from edl_tpu.distill.worker import ServerPool
from edl_tpu.store.server import StoreServer


@pytest.fixture()
def echo_server():
    server = PredictServer(EchoPredictBackend()).start()
    yield server
    server.stop()


class TestServing:
    def test_echo_roundtrip(self, echo_server):
        client = PredictClient(echo_server.endpoint)
        feeds = {"img": np.arange(12, dtype=np.float32).reshape(3, 4)}
        out = client.predict(feeds)
        np.testing.assert_allclose(out["echo_img"], feeds["img"].sum(axis=1))
        assert client.ping()
        client.close()

    def test_nop_backend(self):
        server = PredictServer(NopPredictBackend()).start()
        try:
            client = PredictClient(server.endpoint)
            assert client.predict({"x": np.zeros((2, 2))}) == {}
            client.close()
        finally:
            server.stop()

    def test_jax_backend_bucketing(self):
        from edl_tpu.distill.serving import JaxPredictBackend

        backend = JaxPredictBackend(
            lambda feeds: {"double": feeds["x"] * 2.0}, max_batch=8
        )
        for n in (1, 3, 8, 11):  # ragged sizes share pow2 bucket programs
            x = np.random.randn(n, 4).astype(np.float32)
            out = backend({"x": x})
            assert out["double"].shape == (n, 4)
            np.testing.assert_allclose(out["double"], x * 2.0, rtol=1e-6)


def _ragged_batches(num_batches=24, batch=8, tail=2):
    """24 full batches + 1 ragged tail — the reference's test shape
    (distill_reader_test.py: 24x8 + 1x2 samples)."""

    def gen():
        rng = np.random.RandomState(0)
        for i in range(num_batches):
            x = rng.randn(batch, 4).astype(np.float32)
            y = np.full((batch,), i, np.int64)
            yield (x, y)
        x = rng.randn(tail, 4).astype(np.float32)
        yield (x, np.full((tail,), num_batches, np.int64))

    return gen


class TestPipeline:
    def test_batch_mode_ordering_many_epochs(self, echo_server):
        reader = DistillReader(
            feeds=("img",), teacher_batch_size=3, require_num=4
        )
        reader.set_fixed_teacher(echo_server.endpoint)
        reader.set_batch_generator(_ragged_batches())
        try:
            for _epoch in range(30):
                batches = list(reader())
                assert len(batches) == 25
                for i, (img, label, echo) in enumerate(batches):
                    expect = 8 if i < 24 else 2
                    assert img.shape[0] == expect
                    assert (label == i).all()
                    # pairing survives concurrency: echo == row sums
                    np.testing.assert_allclose(
                        echo, img.astype(np.float64).sum(axis=1), rtol=1e-5
                    )
        finally:
            reader.stop()

    def test_sample_mode(self, echo_server):
        def gen():
            for i in range(10):
                yield (np.full((4,), i, np.float32), i)

        reader = DistillReader(feeds=("img",), teacher_batch_size=4)
        reader.set_fixed_teacher(echo_server.endpoint)
        reader.set_sample_generator(gen)
        try:
            out = list(reader())
            assert len(out) == 10
            for i, (img, label, echo) in enumerate(out):
                assert label == i
                np.testing.assert_allclose(echo, img.sum())
        finally:
            reader.stop()

    def test_sample_list_mode(self, echo_server):
        def gen():
            for i in range(6):
                yield [(np.full((2,), i + j, np.float32), j) for j in range(5)]

        reader = DistillReader(feeds=("img",), teacher_batch_size=2)
        reader.set_fixed_teacher(echo_server.endpoint)
        reader.set_sample_list_generator(gen)
        try:
            units = list(reader())
            assert len(units) == 6
            for i, unit in enumerate(units):
                assert len(unit) == 5
                for j, (img, label, echo) in enumerate(unit):
                    assert label == j
                    np.testing.assert_allclose(echo, img.sum())
        finally:
            reader.stop()

    def test_nop_teacher_pipeline(self):
        """The reference's NOP test: full concurrency, no predictions."""
        server = PredictServer(NopPredictBackend()).start()
        reader = DistillReader(feeds=("img",), teacher_batch_size=3)
        reader.set_fixed_teacher(server.endpoint)
        reader.set_batch_generator(_ragged_batches(num_batches=5))
        try:
            for _ in range(5):
                batches = list(reader())
                assert len(batches) == 6
                assert all(len(b) == 2 for b in batches)  # no fetchs appended
        finally:
            reader.stop()
            server.stop()

    def test_teacher_failover_midstream(self):
        """Kill one of two teachers mid-epoch: failed tasks are re-queued
        and every batch still arrives exactly once, in order."""
        s1 = PredictServer(EchoPredictBackend()).start()
        s2 = PredictServer(EchoPredictBackend()).start()
        reader = DistillReader(
            feeds=("img",), teacher_batch_size=2, require_num=3
        )
        reader.set_fixed_teacher(s1.endpoint, s2.endpoint)
        reader.set_batch_generator(_ragged_batches(num_batches=40))
        killer = threading.Timer(0.05, s2.stop)
        killer.start()
        try:
            batches = list(reader())
            assert len(batches) == 41
            for i, (img, label, echo) in enumerate(batches):
                assert (label == i).all()
                np.testing.assert_allclose(
                    echo, img.astype(np.float64).sum(axis=1), rtol=1e-5
                )
        finally:
            killer.cancel()
            reader.stop()
            s1.stop()
            s2.stop()


class TestHungTeacher:
    TEACHER_SRC = (
        "from edl_tpu.distill import EchoPredictBackend, PredictServer\n"
        "import time\n"
        "srv = PredictServer(EchoPredictBackend()).start()\n"
        "print(srv.endpoint, flush=True)\n"
        "time.sleep(3600)\n"
    )

    def test_hung_teacher_rpc_timeout_failover(self):
        """SIGSTOP (hang, don't kill) a subprocess teacher mid-stream: the
        predict RPC must time out, the teacher goes to cooldown, its
        in-flight task is re-delivered, and every batch still arrives
        exactly once, in order — the hung-peer drill the dead-teacher
        failover test can't cover (a dead socket fails fast; a hung one
        only fails by timeout)."""
        import os
        import signal
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-c", self.TEACHER_SRC],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        try:
            # bounded endpoint wait: a wedged child must fail the test,
            # not hang the suite with the finally never reached
            got = [None]

            def read_ep():
                got[0] = proc.stdout.readline().strip()

            t = threading.Thread(target=read_ep, daemon=True)
            t.start()
            t.join(timeout=30)
            hung_ep = got[0]
            assert hung_ep, "teacher subprocess printed no endpoint"
            healthy = PredictServer(EchoPredictBackend()).start()
            reader = DistillReader(
                feeds=("img",), teacher_batch_size=2, require_num=3,
                rpc_timeout=1.0,
            )
            reader.set_fixed_teacher(hung_ep, healthy.endpoint)
            reader.set_batch_generator(_ragged_batches(num_batches=40))
            # freeze the subprocess teacher BEFORE consumption: the tasks
            # routed to it MUST take the rpc-timeout path (a timer racing
            # a fast CPU stream would usually fire after completion)
            os.kill(proc.pid, signal.SIGSTOP)
            try:
                t0 = time.time()
                batches = list(reader())
                elapsed = time.time() - t0
                assert len(batches) == 41
                for i, (img, label, echo) in enumerate(batches):
                    assert (label == i).all()
                    np.testing.assert_allclose(
                        echo, img.astype(np.float64).sum(axis=1), rtol=1e-5
                    )
                # the hung teacher was dealt tasks, so the stream must have
                # paid at least one rpc timeout — and recovered bounded
                assert 1.0 <= elapsed < 30, elapsed
            finally:
                reader.stop()
                healthy.stop()
        finally:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
            proc.kill()
            proc.wait()


class TestBalance:
    def test_assign_caps(self):
        # 4 teachers, 2 clients -> 2 each, disjoint
        a = BalanceTable.assign(["t1", "t2", "t3", "t4"], ["c1", "c2"])
        assert sorted(a["c1"] + a["c2"]) == ["t1", "t2", "t3", "t4"]
        # 2 teachers, 5 clients -> 1 each, <= ceil(5/2)=3 per teacher
        a = BalanceTable.assign(["t1", "t2"], ["c%d" % i for i in range(5)])
        loads = {}
        for servers in a.values():
            assert len(servers) == 1
            loads[servers[0]] = loads.get(servers[0], 0) + 1
        assert max(loads.values()) <= 3
        # degenerate cases
        assert BalanceTable.assign([], ["c"]) == {"c": []}
        assert BalanceTable.assign(["t"], []) == {}

    def test_assign_properties_under_churn(self):
        """Property test over seeded join/leave/drain/sick churn: at
        every step the assignment (a) routes only to eligible teachers
        — never a drained or breaker-ejected one, except the all-sick
        fallback, (b) honors both caps, (c) covers every client, and
        (d) is deterministic — an unchanged eligible set reassigns
        NOTHING, so churn is driven by membership alone."""
        import random

        rng = random.Random(7)
        teachers = ["t%02d" % i for i in range(4)]
        clients = ["c%d" % i for i in range(3)]
        next_t = len(teachers)
        drained, sick = set(), set()
        prev_key, prev_assignment = None, None
        for _step in range(300):
            op = rng.random()
            if op < 0.2 and len(teachers) < 12:
                teachers.append("t%02d" % next_t)
                next_t += 1
            elif op < 0.4 and teachers:
                gone = rng.choice(teachers)
                teachers.remove(gone)
                drained.discard(gone)
                sick.discard(gone)
            elif op < 0.55 and teachers:
                drained.add(rng.choice(teachers))
            elif op < 0.65 and drained:
                drained.discard(rng.choice(sorted(drained)))
            elif op < 0.85 and teachers:
                sick.add(rng.choice(teachers))
            elif sick:
                sick.discard(rng.choice(sorted(sick)))
            # the balancer's own eligibility pipeline: drained teachers
            # left the watch set entirely; sick ones are ejected with
            # the all-sick fallback
            registered = sorted(t for t in teachers if t not in drained)
            eligible = [t for t in registered if t not in sick]
            if not eligible and registered:
                eligible = list(registered)
            assignment = BalanceTable.assign(eligible, clients)
            assert sorted(assignment) == sorted(clients)  # coverage
            if eligible:
                per_client = max(1, len(eligible) // len(clients))
                cap = -(-len(clients) * per_client // len(eligible))
                load = {}
                for c, servers in assignment.items():
                    assert len(servers) == per_client
                    assert len(set(servers)) == len(servers)
                    for t in servers:
                        assert t in eligible  # validity
                        load[t] = load.get(t, 0) + 1
                assert max(load.values()) <= cap
            key = tuple(eligible)
            if key == prev_key:
                # no gratuitous churn: same world, same assignment
                assert assignment == prev_assignment
            prev_key, prev_assignment = key, assignment

    def test_sick_reports_eject_and_all_sick_falls_back(self):
        """A client's breaker-driven sick report ejects the teacher from
        its assignment; when EVERY teacher is reported sick the balancer
        falls back to the raw set (all-sick means overload, not death);
        clearing the report restores the teacher."""
        store = StoreServer(port=0).start()
        job = "distill-sick"
        t1 = PredictServer(EchoPredictBackend()).start()
        t2 = PredictServer(EchoPredictBackend()).start()
        svc = DiscoveryService(store.endpoint, job, ["teacher"])
        reg1 = TeacherRegister(store.endpoint, job, "teacher", t1.endpoint)
        reg2 = TeacherRegister(store.endpoint, job, "teacher", t2.endpoint)
        client = DiscoveryClient(
            store.endpoint, job, "teacher", client_id="student-1"
        )

        def wait_view(want, note):
            deadline = time.time() + 10.0
            while time.time() < deadline:
                _, servers = client.get_servers()
                if sorted(servers) == sorted(want):
                    return
                time.sleep(0.05)
            raise AssertionError(
                "%s: wanted %s, have %s" % (note, want, servers)
            )

        try:
            client.wait_servers(timeout=10.0)
            wait_view([t1.endpoint, t2.endpoint], "initial")
            client.report_sick(t1.endpoint)
            wait_view([t2.endpoint], "sick teacher ejected")
            client.report_sick(t2.endpoint)  # ALL sick -> fallback
            wait_view([t1.endpoint, t2.endpoint], "all-sick fallback")
            client.clear_sick(t1.endpoint)
            wait_view([t1.endpoint], "t2 still sick after t1 cleared")
            client.clear_sick(t2.endpoint)
            wait_view([t1.endpoint, t2.endpoint], "all cleared")
        finally:
            client.stop()
            reg1.stop()
            reg2.stop()
            svc.stop()
            t1.stop()
            t2.stop()
            store.stop()

    def test_server_pool(self):
        pool = ServerPool()
        pool.update(["a:1", "b:2"])
        got = pool.acquire(timeout=1.0)
        assert got in ("a:1", "b:2")
        pool.mark_bad(got)
        other = pool.acquire(timeout=1.0)
        assert other != got
        pool.close()
        assert pool.acquire(timeout=0.2) is None

    def test_server_pool_cooldown_recovery_without_membership_change(self):
        """All teachers in cooldown + stable membership: acquire(None) must
        wake up on its own when the cooldown lapses (the advisor's hang:
        cooldown expiry never notifies the condition)."""
        pool = ServerPool(cooldown=0.4)
        pool.update(["a:1", "b:2"])
        pool.mark_bad("a:1")
        pool.mark_bad("b:2")
        assert not pool.has("a:1") and not pool.has("b:2")

        got = []
        t = threading.Thread(target=lambda: got.append(pool.acquire()))
        t.start()
        t.join(timeout=5.0)
        assert not t.is_alive(), "acquire(timeout=None) hung past cooldown"
        assert got and got[0] in ("a:1", "b:2")
        # cooled-down members are full members again
        assert pool.has(got[0])

    def test_server_pool_cooldown_blocks_then_admits_bounded(self):
        pool = ServerPool(cooldown=0.3)
        pool.update(["only:1"])
        pool.mark_bad("only:1")
        t0 = time.time()
        assert pool.acquire(timeout=0.05) is None  # still cooling
        assert pool.acquire(timeout=2.0) == "only:1"
        assert 0.1 <= time.time() - t0 < 1.5


class TestFullStack:
    def test_discovery_balance_and_reader(self):
        """Store + balancer + registered teachers + dynamic reader; then a
        teacher joins late and a rebalance reaches the client."""
        store = StoreServer(port=0).start()
        job = "distill-test"
        t1 = PredictServer(EchoPredictBackend()).start()
        svc = DiscoveryService(store.endpoint, job, ["teacher"])
        reg1 = TeacherRegister(store.endpoint, job, "teacher", t1.endpoint)
        client = DiscoveryClient(
            store.endpoint, job, "teacher", client_id="student-1"
        )
        try:
            servers = client.wait_servers(timeout=10.0)
            assert servers == [t1.endpoint]

            reader = DistillReader(feeds=("img",), teacher_batch_size=4)
            reader.set_dynamic_teacher(store.endpoint, job, "teacher")
            reader.set_batch_generator(_ragged_batches(num_batches=6))
            batches = list(reader())
            assert len(batches) == 7
            np.testing.assert_allclose(
                batches[0][2],
                batches[0][0].astype(np.float64).sum(axis=1),
                rtol=1e-5,
            )
            reader.stop()

            # late-joining teacher triggers a rebalance
            t2 = PredictServer(EchoPredictBackend()).start()
            reg2 = TeacherRegister(store.endpoint, job, "teacher", t2.endpoint)
            deadline = time.time() + 10.0
            while time.time() < deadline:
                _, servers = client.get_servers()
                if len(servers) == 2:
                    break
                time.sleep(0.05)
            assert sorted(servers) == sorted([t1.endpoint, t2.endpoint])
            reg2.stop()
            t2.stop()
        finally:
            client.stop()
            reg1.stop()
            svc.stop()
            t1.stop()
            store.stop()


class TestDynamicDiscovery:
    """Regression for the ISSUE-14 blocking-under-lock fix: the lazy
    DiscoveryClient dial happens OUTSIDE ``_DynamicDiscovery._lock``
    (double-checked publish), so ``stop()`` never waits behind a slow
    store connect — and a stop racing the dial closes the fresh client
    instead of leaking it."""

    def test_stop_does_not_wait_behind_dial(self, monkeypatch):
        from edl_tpu.distill import discovery as discovery_mod
        from edl_tpu.distill.reader import _DynamicDiscovery

        dial_started = threading.Event()
        release_dial = threading.Event()
        stopped = []

        class SlowClient:
            def __init__(self, *a, **k):
                dial_started.set()
                assert release_dial.wait(5.0), "dial never released"

            def get_servers(self):
                return 0, ["teacher:1"]

            def stop(self):
                stopped.append(True)

        monkeypatch.setattr(discovery_mod, "DiscoveryClient", SlowClient)
        dyn = _DynamicDiscovery("127.0.0.1:1", "job", "svc", 4)
        got = []
        t = threading.Thread(target=lambda: got.append(dyn()), daemon=True)
        t.start()
        assert dial_started.wait(5.0)
        # the old code held _lock across the dial: this stop() would
        # have blocked until release_dial fired
        t0 = time.monotonic()
        dyn.stop()
        assert time.monotonic() - t0 < 1.0
        release_dial.set()
        t.join(5.0)
        assert not t.is_alive()
        assert got == [[]]  # stopped mid-dial: no servers published
        assert stopped      # ...and the orphaned fresh client was closed

    def test_dial_publishes_once(self, monkeypatch):
        from edl_tpu.distill import discovery as discovery_mod
        from edl_tpu.distill.reader import _DynamicDiscovery

        made = []

        class Client:
            def __init__(self, *a, **k):
                made.append(self)

            def get_servers(self):
                return 0, ["teacher:1"]

            def stop(self):
                pass

        monkeypatch.setattr(discovery_mod, "DiscoveryClient", Client)
        dyn = _DynamicDiscovery("127.0.0.1:1", "job", "svc", 4)
        assert dyn() == ["teacher:1"]
        assert dyn() == ["teacher:1"]
        assert len(made) == 1  # second call reuses the published client


class TestCoalescingBackend:
    """Server-side megabatching (SURVEY §7 hard part: teacher throughput
    via per-core megabatching): concurrent requests merge into one
    backend call; results split back per caller."""

    class _CountingEcho(EchoPredictBackend):
        def __init__(self):
            self.calls = 0
            self.batch_rows = []

        def __call__(self, feeds):
            self.calls += 1
            self.batch_rows.append(next(iter(feeds.values())).shape[0])
            return super().__call__(feeds)

    def test_concurrent_requests_coalesce_and_split_correctly(self):
        from edl_tpu.distill import CoalescingBackend

        inner = self._CountingEcho()
        be = CoalescingBackend(inner, max_rows=1024, max_wait_ms=60.0)
        n_threads, rows = 8, 4
        results = [None] * n_threads
        start = threading.Barrier(n_threads)

        def worker(i):
            start.wait()
            feeds = {"x": np.full((rows, 3), float(i), np.float32)}
            results[i] = be({"x": feeds["x"]})

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        # every caller got ITS rows back (echo = row sum = 3*i)
        for i, out in enumerate(results):
            assert out is not None
            np.testing.assert_allclose(out["echo_x"], np.full((rows,), 3.0 * i))
        # and the device saw materially fewer, larger batches
        assert inner.calls < n_threads, inner.batch_rows
        assert be.requests_served == n_threads
        assert sum(inner.batch_rows) == n_threads * rows
        be.close()

    def test_key_mismatch_runs_separate_cohorts(self):
        from edl_tpu.distill import CoalescingBackend

        inner = self._CountingEcho()
        be = CoalescingBackend(inner, max_wait_ms=30.0)
        outs = {}

        def worker(name):
            outs[name] = be({name: np.ones((2, 2), np.float32)})

        ts = [threading.Thread(target=worker, args=(n,)) for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        np.testing.assert_allclose(outs["a"]["echo_a"], [2.0, 2.0])
        np.testing.assert_allclose(outs["b"]["echo_b"], [2.0, 2.0])
        assert inner.calls == 2
        be.close()

    def test_error_propagates_to_all_waiters(self):
        from edl_tpu.distill import CoalescingBackend

        def bad(feeds):
            raise ValueError("teacher broke")

        be = CoalescingBackend(bad, max_wait_ms=30.0)
        errs = []

        def worker():
            try:
                be({"x": np.ones((1, 1), np.float32)})
            except ValueError as e:
                errs.append(str(e))

        ts = [threading.Thread(target=worker) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert errs == ["teacher broke"] * 3
        be.close()

    def test_max_rows_splits_cohorts(self):
        from edl_tpu.distill import CoalescingBackend

        inner = self._CountingEcho()
        be = CoalescingBackend(inner, max_rows=8, max_wait_ms=60.0)
        start = threading.Barrier(4)
        results = [None] * 4

        def worker(i):
            start.wait()
            results[i] = be({"x": np.full((4, 2), float(i), np.float32)})

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        for i, out in enumerate(results):
            np.testing.assert_allclose(out["echo_x"], np.full((4,), 2.0 * i))
        # 16 rows at max_rows=8 -> at least 2 device calls, each <= 8 rows
        assert all(r <= 8 for r in inner.batch_rows)
        assert inner.calls >= 2
        be.close()

    def test_through_predict_server(self):
        """End-to-end: two clients against one server; the server lets
        thread-safe backends run concurrently so cohorts can form."""
        from edl_tpu.distill import CoalescingBackend

        inner = self._CountingEcho()
        server = PredictServer(CoalescingBackend(inner, max_wait_ms=40.0)).start()
        try:
            outs = {}

            def worker(i):
                c = PredictClient(server.endpoint)
                outs[i] = c.predict(
                    {"x": np.full((2, 2), float(i), np.float32)}
                )
                c.close()

            ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            for i in range(4):
                np.testing.assert_allclose(
                    outs[i]["echo_x"], np.full((2,), 2.0 * i)
                )
        finally:
            server.stop()


class TestSampleModeBatching:
    """Sample-mode tasks must group teacher_batch_size consecutive samples
    into ONE RPC (reference read_sample accumulates across yields,
    distill_worker.py:531-563) — not one RPC per sample."""

    def test_sample_mode_sends_batched_rpcs(self):
        calls = []

        class Counting(EchoPredictBackend):
            def __call__(self, feeds):
                calls.append(next(iter(feeds.values())).shape[0])
                return super().__call__(feeds)

        server = PredictServer(Counting()).start()
        try:
            def gen():
                for i in range(37):
                    yield (np.full((4,), float(i), np.float32), np.int64(i))

            reader = (
                DistillReader(
                    feeds=["x", "y"], fetchs=["echo_x"], teacher_batch_size=16
                )
                .set_fixed_teacher(server.endpoint)
                .set_sample_generator(gen)
            )
            try:
                got = list(reader())
            finally:
                reader.stop()
            # every sample comes back, in order, correctly paired
            assert len(got) == 37
            for i, sample in enumerate(got):
                x, y, echo = sample
                assert int(y) == i
                np.testing.assert_allclose(echo, 4.0 * i)
            # and the teacher saw ceil(37/16)=3 RPCs, not 37
            assert sorted(calls) == [5, 16, 16], calls
        finally:
            server.stop()

    def test_close_stops_runner_thread(self):
        """server.stop() must stop the cohort-runner thread (it would
        otherwise pin the backend's device buffers forever)."""
        from edl_tpu.distill import CoalescingBackend

        be = CoalescingBackend(EchoPredictBackend(), max_wait_ms=5.0)
        be({"x": np.ones((1, 2), np.float32)})  # spawns the runner
        runner = be._worker
        assert runner is not None and runner.is_alive()
        be.close()
        assert not runner.is_alive()
        with pytest.raises(RuntimeError):
            be({"x": np.ones((1, 2), np.float32)})
