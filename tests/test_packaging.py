"""Packaging validation: k8s manifests + Dockerfile (SURVEY C24/C25).

The environment has no docker daemon, kubectl or cluster (zero egress), so
this validates the artifacts the way `kubectl apply --dry-run=client` and a
Dockerfile lint would: full YAML parse, k8s schema essentials, referential
integrity between Services/Deployments, command modules that actually exist
in the package, COPY sources that exist in the repo, and consistency
between the manifests' env contract and the code's EDL_* contract — the
drift these files historically accumulate. (The reference ships images
built elsewhere, reference README.md:20-24; its manifests are equally
cluster-untested in-tree.)
"""

import os
import re

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
K8S = os.path.join(REPO, "k8s")
DOCKERFILE = os.path.join(REPO, "docker", "Dockerfile")


def _docs():
    out = []
    for name in sorted(os.listdir(K8S)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(K8S, name)) as f:
            for doc in yaml.safe_load_all(f):
                if doc is not None:
                    out.append((name, doc))
    return out


def _module_exists(mod: str) -> bool:
    import importlib.util

    try:
        return importlib.util.find_spec(mod) is not None
    except (ImportError, ValueError):
        return False


class TestK8sManifests:
    def test_all_docs_parse_with_schema_essentials(self):
        docs = _docs()
        assert len(docs) >= 4  # store deploy+svc+pvc, train, distill set
        for name, doc in docs:
            assert doc.get("apiVersion"), (name, doc)
            assert doc.get("kind"), (name, doc)
            assert doc.get("metadata", {}).get("name"), (name, doc)

    def test_deployment_selectors_match_pod_labels(self):
        for name, doc in _docs():
            if doc["kind"] != "Deployment":
                continue
            sel = doc["spec"]["selector"]["matchLabels"]
            labels = doc["spec"]["template"]["metadata"]["labels"]
            for k, v in sel.items():
                assert labels.get(k) == v, (name, doc["metadata"]["name"])

    def test_services_select_an_existing_deployment(self):
        docs = _docs()
        pod_label_sets = [
            doc["spec"]["template"]["metadata"]["labels"]
            for _, doc in docs
            if doc["kind"] == "Deployment"
        ]
        for name, doc in docs:
            if doc["kind"] != "Service":
                continue
            sel = doc["spec"]["selector"]
            assert any(
                all(labels.get(k) == v for k, v in sel.items())
                for labels in pod_label_sets
            ), "service %s selects nothing" % doc["metadata"]["name"]

    def test_container_commands_reference_real_modules(self):
        for name, doc in _docs():
            if doc["kind"] != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                assert c.get("image"), (name, c)
                cmd = c.get("command", [])
                if len(cmd) >= 3 and cmd[:2] == ["python", "-m"]:
                    assert _module_exists(cmd[2]), (name, cmd[2])
                # script args must exist in the repo (they're COPY'd in)
                for arg in cmd[3:]:
                    if isinstance(arg, str) and arg.endswith(".py"):
                        assert os.path.exists(os.path.join(REPO, arg)), (
                            name, arg,
                        )

    def test_env_vars_are_in_the_edl_contract(self):
        from edl_tpu.cluster.job_env import WorkerEnv

        known = set(WorkerEnv.VARS) | {
            "EDL_NODES_RANGE", "EDL_NPROC_PER_NODE", "EDL_LOG_DIR",
            "EDL_DISTILL_STORE", "EDL_DISTILL_JOB_ID",
            "EDL_DISTILL_SERVICE_NAME", "EDL_DISTILL_MAX_TEACHER",
            "EDL_DEVICES_PER_PROC", "EDL_TIMELINE", "EDL_LOG_LEVEL",
            "EDL_STANDBY", "EDL_HOT_RESTAGE",
            # health plane (launch/launcher.py + train/context.py)
            "EDL_DRAIN_BUDGET", "EDL_FAIL_GRACE", "EDL_HEARTBEAT_EVERY",
            "EDL_STALL_DEADLINE", "EDL_STALL_FACTOR", "EDL_STALL_FLOOR",
            "JAX_PLATFORMS", "XLA_FLAGS",
        }
        for name, doc in _docs():
            if doc["kind"] != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                for env in c.get("env", ()):
                    var = env["name"]
                    if var.startswith("EDL_"):
                        assert var in known, (
                            "%s sets %s, not part of the EDL_* contract"
                            % (name, var)
                        )

    @staticmethod
    def _flag_claim(deployment, flag):
        """Resolve a path-valued container flag to the PVC claim backing
        the mount it lives under."""
        spec = deployment["spec"]["template"]["spec"]
        c = spec["containers"][0]
        assert flag in c["command"], "%s not in store command" % flag
        path = c["command"][c["command"].index(flag) + 1]
        mounts = {m["mountPath"]: m["name"] for m in c.get("volumeMounts", ())}
        name = next(
            (mounts[m] for m in mounts
             if path == m or path.startswith(m.rstrip("/") + "/")),
            None,
        )
        assert name, "%s=%s is not under any mount" % (flag, path)
        volumes = {v["name"]: v for v in spec.get("volumes", ())}
        return volumes[name]["persistentVolumeClaim"]["claimName"]

    def test_store_deployment_is_durable(self):
        """The round-3 durability work must be expressed in the manifest:
        --data_dir backed by a PVC, so a rescheduled store pod loses
        nothing."""
        docs = _docs()
        store = next(
            doc for _, doc in docs
            if doc["kind"] == "Deployment"
            and doc["metadata"]["name"] == "edl-store"
        )
        claim = self._flag_claim(store, "--data_dir")
        assert any(
            doc["kind"] == "PersistentVolumeClaim"
            and doc["metadata"]["name"] == claim
            for _, doc in docs
        ), "PVC %s not defined" % claim

    def test_store_replica_rides_the_shared_volume(self):
        """The round-4 store-HOST-loss answer must be expressed in the
        manifest: --replica_dir under a mount whose claim is the SAME
        shared volume the training pods checkpoint to (elastic-job.yaml)
        — an independent volume, so losing the data PVC's node doesn't
        lose the replica too."""
        docs = _docs()
        store = next(
            doc for _, doc in docs
            if doc["kind"] == "Deployment"
            and doc["metadata"]["name"] == "edl-store"
        )
        replica_claim = self._flag_claim(store, "--replica_dir")
        data_claim = self._flag_claim(store, "--data_dir")
        assert replica_claim != data_claim, (
            "replica on the same volume as the data dir protects nothing"
        )
        # ...and it IS the volume the training pods mount for checkpoints
        train = next(
            doc for _, doc in docs
            if doc["kind"] == "Deployment"
            and doc["metadata"]["name"] == "edl-train"
        )
        train_claims = {
            v["persistentVolumeClaim"]["claimName"]
            for v in train["spec"]["template"]["spec"].get("volumes", ())
            if "persistentVolumeClaim" in v
        }
        assert replica_claim in train_claims, (
            "store replica claim %s is not the training ckpt volume"
            % replica_claim
        )

    def test_store_endpoint_ports_are_consistent(self):
        """Every EDL_STORE_ENDPOINT in the manifests must point at a
        Service name+port that exists."""
        docs = _docs()
        service_ports = {
            doc["metadata"]["name"]: {
                p["port"] for p in doc["spec"]["ports"]
            }
            for _, doc in docs
            if doc["kind"] == "Service"
        }
        found = 0
        for name, doc in docs:
            if doc["kind"] != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                for env in c.get("env", ()):
                    if env["name"] in ("EDL_STORE_ENDPOINT", "EDL_DISTILL_STORE"):
                        host, port = env["value"].rsplit(":", 1)
                        assert host in service_ports, (name, env["value"])
                        assert int(port) in service_ports[host], (
                            name, env["value"],
                        )
                        found += 1
        assert found >= 1


class TestDockerfile:
    @pytest.fixture()
    def instructions(self):
        out = []
        with open(DOCKERFILE) as f:
            buf = ""
            for line in f:
                line = line.rstrip("\n")
                if not line.strip() or line.lstrip().startswith("#"):
                    continue
                buf += line
                if line.endswith("\\"):
                    buf = buf[:-1] + " "
                    continue
                out.append(buf.strip())
                buf = ""
        return out

    def test_structure(self, instructions):
        assert instructions[0].startswith("FROM ")
        kinds = {i.split()[0] for i in instructions}
        assert {"FROM", "COPY", "RUN", "CMD"} <= kinds

    def test_copy_sources_exist(self, instructions):
        for ins in instructions:
            if not ins.startswith("COPY"):
                continue
            parts = ins.split()
            if any(p.startswith("--from=") for p in parts):
                continue  # built in an earlier stage, not in the repo
            for src in parts[1:-1]:
                if src.startswith("--"):
                    continue
                path = os.path.join(REPO, src.rstrip("/"))
                assert os.path.exists(path), "COPY source missing: %s" % src

    def test_builder_output_matches_cmake_target(self, instructions):
        froms = [i for i in instructions if "--from=builder" in i]
        assert froms, "runtime stage must take the native master from builder"
        with open(os.path.join(REPO, "native", "CMakeLists.txt")) as f:
            cmake = f.read()
        targets = set(re.findall(r"add_executable\((\w+)", cmake))
        for ins in froms:
            binary = os.path.basename(ins.split()[-2])
            assert binary in targets, (binary, targets)

    def test_cmd_module_exists(self, instructions):
        cmd = next(i for i in instructions if i.startswith("CMD"))
        assert "edl_tpu.store.server" in cmd
        assert _module_exists("edl_tpu.store.server")

    def test_exposed_port_matches_store_default(self, instructions):
        expose = next(i for i in instructions if i.startswith("EXPOSE"))
        assert "2379" in expose  # the store CLI default
