"""Hot-standby worker shells (launch/standby.py).

Covers the pool mechanics (spawn, activate, fallback, replacement,
teardown) with a stub script, and the launcher integration end-to-end:
a real launcher with EDL_STANDBY=1 must run its workers THROUGH the
shells (observable via the marker the stub drops), survive a restage,
and leave no shell behind on exit.
"""

import json
import os
import subprocess
import sys
import time

import psutil
import pytest

from conftest import TOY_WORKER as TOY, incarnations  # noqa: F401
from edl_tpu.launch.standby import StandbyPool, standby_enabled

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a worker script that proves which pid ran it and what env it saw
PROBE = """
import json, os, sys
out = os.environ["PROBE_OUT"]
with open(out, "w") as f:
    json.dump({
        "pid": os.getpid(),
        "rank": os.environ.get("EDL_WORKER_RANK"),
        "argv": sys.argv,
        "numpy_preloaded": "numpy" in sys.modules,
    }, f)
"""


def _spawn_env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    env.update(extra or {})
    return env


class TestPoolMechanics:
    def test_activate_runs_script_in_shell_pid(self, tmp_path):
        script = tmp_path / "probe.py"
        script.write_text(PROBE)
        out = tmp_path / "probe.json"
        pool = StandbyPool(_spawn_env(), count=1)
        try:
            shell_pid = pool._idle[0].pid
            proc = pool.activate(
                _spawn_env({"PROBE_OUT": str(out), "EDL_WORKER_RANK": "3"}),
                str(script), ["--flag", "x"],
            )
            assert proc is not None and proc.pid == shell_pid
            assert proc.wait(timeout=60) == 0
            rec = json.loads(out.read_text())
            # same process: the shell became the worker (no exec)
            assert rec["pid"] == shell_pid
            assert rec["rank"] == "3"
            assert rec["argv"] == [str(script), "--flag", "x"]
            # the pre-payment actually happened before activation
            assert rec["numpy_preloaded"] is True
        finally:
            pool.stop()

    def test_activation_replaces_consumed_shell_via_ensure(self, tmp_path):
        script = tmp_path / "probe.py"
        script.write_text(PROBE)
        pool = StandbyPool(_spawn_env(), count=1)
        try:
            first = pool.activate(
                _spawn_env({"PROBE_OUT": str(tmp_path / "a.json")}),
                str(script), [],
            )
            assert first is not None
            assert not pool._idle  # consumed
            pool.ensure()
            assert len(pool._idle) == 1
            assert pool._idle[0].pid != first.pid
        finally:
            pool.stop()

    def test_jax_env_mismatch_declines(self):
        pool = StandbyPool(_spawn_env(), count=1)
        try:
            env = _spawn_env()
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            assert pool.activate(env, TOY, []) is None
        finally:
            pool.stop()

    def test_dead_shell_falls_back_to_none(self, tmp_path):
        pool = StandbyPool(_spawn_env(), count=1)
        try:
            pool._idle[0].kill()
            pool._idle[0].wait()
            assert pool.activate(_spawn_env(), TOY, []) is None
        finally:
            pool.stop()

    def test_stop_kills_idle_shells(self):
        pool = StandbyPool(_spawn_env(), count=2)
        pids = [p.pid for p in pool._idle]
        pool.stop()
        deadline = time.time() + 5
        while time.time() < deadline:
            if not any(psutil.pid_exists(pid) for pid in pids):
                break
            time.sleep(0.1)
        assert not any(
            psutil.pid_exists(pid)
            and psutil.Process(pid).status() != psutil.STATUS_ZOMBIE
            for pid in pids
        )

    def test_log_path_redirect(self, tmp_path):
        script = tmp_path / "noisy.py"
        script.write_text("print('worker says hi')\n")
        log = tmp_path / "worker.log"
        pool = StandbyPool(_spawn_env(), count=1)
        try:
            proc = pool.activate(_spawn_env(), str(script), [], str(log))
            assert proc is not None and proc.wait(timeout=60) == 0
            assert "worker says hi" in log.read_text()
        finally:
            pool.stop()

    def test_enabled_flag_logic(self, monkeypatch):
        monkeypatch.delenv("EDL_STANDBY", raising=False)
        assert not standby_enabled()
        assert standby_enabled(True)
        monkeypatch.setenv("EDL_STANDBY", "1")
        assert standby_enabled()
        monkeypatch.setenv("EDL_STANDBY", "0")
        assert not standby_enabled(True)  # env force-off beats the flag


class TestLauncherIntegration:
    def _spawn(self, store, job_id, out_dir, exit_after=None):
        env = _spawn_env({
            "TEST_OUT_DIR": out_dir,
            "EDL_DEVICES_PER_PROC": "1",
            "EDL_STANDBY": "1",
        })
        if exit_after is not None:
            env["TEST_EXIT_AFTER"] = str(exit_after)
        return subprocess.Popen(
            [
                sys.executable, "-m", "edl_tpu.launch",
                "--job_id", job_id,
                "--store", store.endpoint,
                "--nodes_range", "1:2",
                "--nproc_per_node", "1",
                "--ttl", "0.8",
                TOY,
            ],
            env=env,
            cwd=REPO,
        )

    def test_single_pod_completes_through_standby(self, store, tmp_path):
        out = str(tmp_path)
        launcher = self._spawn(store, "sb1", out, exit_after=0.5)
        try:
            assert launcher.wait(timeout=60) == 0
        finally:
            if launcher.poll() is None:
                launcher.kill()
        runs = incarnations(out)
        assert len(runs) == 1
        # no stray standby shells after a clean exit
        for p in psutil.Process().children(recursive=True):
            assert "standby" not in " ".join(p.cmdline() or [])

    def test_restage_activates_fresh_standby(self, store, tmp_path):
        """Kill pod B of a 2-pod job: pod A drains and respawns its worker
        through a REPLACEMENT shell (the first was consumed by stage 1)."""
        out = str(tmp_path)
        a = self._spawn(store, "sb2", out)
        b = self._spawn(store, "sb2", out)
        try:
            deadline = time.time() + 45
            while time.time() < deadline:
                if any(w == 2 for runs in incarnations(out).values()
                       for w in runs.values()):
                    break
                time.sleep(0.3)
            runs = incarnations(out)
            assert any(
                w == 2 for r in runs.values() for w in r.values()
            ), "2-pod stage never formed: %r" % runs
            b.kill()
            b.wait()
            deadline = time.time() + 45
            while time.time() < deadline:
                runs = incarnations(out)
                if any(
                    set(r.values()) == {1} for r in runs.values()
                ):
                    break
                time.sleep(0.3)
            assert any(
                set(r.values()) == {1} for r in runs.values()
            ), "post-kill world-1 stage never formed: %r" % runs
        finally:
            for p in (a, b):
                if p.poll() is None:
                    p.kill()
                    p.wait()
