"""Checkpoint/resume tests — incl. resume across a mesh topology change.

Mirrors the reference's elasticity contract (the checkpoint is the only
state crossing a resize, SURVEY §3.4): save under a 4-device mesh, restore
under an 8-device mesh, training continues bit-identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.checkpoint import (
    AdjustRegistry,
    CheckpointManager,
    TrainStatus,
    linear_scaled_lr,
)
from edl_tpu.models import MLP
from edl_tpu.parallel import make_mesh, replicated, shard_params_fsdp
from edl_tpu.train import create_state, make_train_step, mse_loss


def _make_state(rng=0):
    model = MLP(hidden=(16,), features=4)
    x = jnp.zeros((8, 8), jnp.float32)
    return model, create_state(
        model, jax.random.PRNGKey(rng), x, optax.sgd(0.1, momentum=0.9)
    )


def _train(state, steps, seed=0):
    step = make_train_step(mse_loss)
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = jnp.asarray(rng.randn(8, 8), jnp.float32)
        y = jnp.asarray(rng.randn(8, 4), jnp.float32)
        state, _ = step(state, (x, y))
    return state


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        _, state = _make_state()
        state = _train(state, 3)
        with CheckpointManager(str(tmp_path / "ckpt")) as mngr:
            mngr.save(state, TrainStatus(epoch=2, step=3, world_size=1))
            mngr.wait()
            _, template = _make_state(rng=1)  # different init values
            restored, status = mngr.restore(template)
        assert status is not None and status.epoch == 2 and status.step == 3
        jax.tree.map(
            np.testing.assert_array_equal, restored.params, state.params
        )
        jax.tree.map(
            np.testing.assert_array_equal, restored.opt_state, state.opt_state
        )

    def test_empty_dir_restores_template(self, tmp_path):
        _, state = _make_state()
        with CheckpointManager(str(tmp_path / "none")) as mngr:
            restored, status = mngr.restore(state)
        assert status is None
        assert restored is state

    def test_single_tier_restores_count_as_durable(self, tmp_path):
        """Classic single-dir mode: ``path`` IS the durable tier, and
        the tier-labeled restore counter says so (the peer/local tiers
        exist only when EDL_CKPT_LOCAL_DIR arms the ladder)."""
        from edl_tpu.checkpoint.manager import _M_RESTORES

        _, state = _make_state()
        before = _M_RESTORES.value(tier="durable")
        with CheckpointManager(str(tmp_path / "ckpt")) as mngr:
            assert mngr.durable_path is None  # no ladder armed
            mngr.save(state, TrainStatus(epoch=1, step=1))
            mngr.wait()
            mngr.restore(state)
        assert _M_RESTORES.value(tier="durable") == before + 1

    def test_local_tier_without_store_still_mirrors_durable(self, tmp_path):
        """A local tier without the worker env contract (no store, no
        job) cannot push to peers — but the durable mirror is a purely
        LOCAL copy and must still run: a configured durable path that
        silently never fills would be a durability regression."""
        import time

        _, state = _make_state()
        state = _train(state, 2)
        with CheckpointManager(
            str(tmp_path / "durable"), local_dir=str(tmp_path / "local")
        ) as mngr:
            assert mngr._replicator is not None  # mirror-only (k=0)
            assert not mngr._replicator.peers_armed
            mngr.save(state, TrainStatus(epoch=1, step=2))
            mngr.wait()
            # saves land in the LOCAL tier immediately...
            assert (tmp_path / "local" / "2").is_dir()
            # ...and the background mirror lands them in the durable dir
            deadline = time.time() + 15
            while time.time() < deadline and not (
                tmp_path / "durable" / "2"
            ).is_dir():
                time.sleep(0.05)
            assert (tmp_path / "durable" / "2").is_dir()
            assert mngr._replicator.lag() == 0  # mirror-only never lags
            _, template = _make_state(rng=1)
            restored, status = mngr.restore(template)
        assert status is not None and status.step == 2
        jax.tree.map(
            np.testing.assert_array_equal, restored.params, state.params
        )

    def test_retention(self, tmp_path):
        _, state = _make_state()
        with CheckpointManager(str(tmp_path / "keep"), max_to_keep=2) as mngr:
            for s in (1, 2, 3):
                mngr.save(state, TrainStatus(epoch=s, step=s))
            mngr.wait()
            assert mngr.latest_step() == 3
            assert len(mngr.all_steps()) == 2

    def test_resume_across_topology_change(self, tmp_path):
        """Save sharded on a 4-device mesh; restore onto an 8-device mesh."""
        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs 8 virtual devices")
        _, state = _make_state()
        state = _train(state, 2)

        mesh4 = make_mesh({"dp": 2, "fsdp": 2}, devices=devices[:4])
        sharded4 = state.replace(params=shard_params_fsdp(mesh4, state.params))
        path = str(tmp_path / "topo")
        with CheckpointManager(path) as mngr:
            mngr.save(sharded4, TrainStatus(epoch=0, step=2, world_size=4))
            mngr.wait()

        mesh8 = make_mesh({"dp": 2, "fsdp": 4}, devices=devices)
        _, template = _make_state(rng=1)
        template = jax.tree.map(
            lambda x: jax.device_put(x, replicated(mesh8)), template
        )
        template = template.replace(
            params=shard_params_fsdp(mesh8, template.params),
            opt_state=shard_params_fsdp(mesh8, template.opt_state),
        )
        with CheckpointManager(path) as mngr:
            restored, status = mngr.restore(template)
        assert status.world_size == 4

        # values survive the reshard bit-exactly
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            restored.params,
            state.params,
        )
        # and training continues identically vs the unsharded original
        with mesh8:
            cont_a = _train(restored, 2, seed=7)
        cont_b = _train(state, 2, seed=7)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6
            ),
            cont_a.params,
            cont_b.params,
        )


class TestTornWriteRecovery:
    """A corrupted newest version (torn write, bad disk, crashed upload)
    must not take the job down: restore falls back to the previous good
    version with a warning, purges the provably-unreadable one, and only
    raises when EVERY version is gone."""

    @staticmethod
    def _corrupt(path, step):
        # the canonical torn-write simulation, shared with the chaos
        # corrupt-ckpt scenario
        from edl_tpu.chaos.scenario import corrupt_checkpoint_version

        corrupt_checkpoint_version(path, step)

    def test_restore_falls_back_past_corrupt_newest(self, tmp_path):
        import logging

        from edl_tpu.checkpoint.manager import _M_RESTORE_FALLBACKS

        path = str(tmp_path / "torn")
        _, state = _make_state()
        with CheckpointManager(path) as mngr:
            mngr.save(state, TrainStatus(epoch=0, step=1), step=1)
            mngr.save(state, TrainStatus(epoch=1, step=2), step=2)
            mngr.wait()
        self._corrupt(path, 2)

        # the edl_tpu base logger does not propagate to root (caplog),
        # so capture the fallback warning with a direct handler
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        capture = _Capture(level=logging.WARNING)
        edl_log = logging.getLogger("edl_tpu.checkpoint.manager")
        edl_log.addHandler(capture)
        before = _M_RESTORE_FALLBACKS.value()
        try:
            with CheckpointManager(path) as mngr:
                _, template = _make_state(rng=1)
                restored, status = mngr.restore(template)
                # fell back to the good previous version...
                assert status is not None and status.step == 1
                jax.tree.map(
                    np.testing.assert_array_equal, restored.params, state.params
                )
                assert _M_RESTORE_FALLBACKS.value() == before + 1
                assert any(
                    "unreadable" in record.getMessage() for record in records
                )
                # ...and purged the torn one, so latest_step is
                # trustworthy again and a post-resume re-save of step 2
                # cannot collide
                assert mngr.all_steps() == [1]
                mngr.save(restored, TrainStatus(epoch=1, step=2), step=2)
                mngr.wait()
                assert mngr.latest_step() == 2
        finally:
            edl_log.removeHandler(capture)

    def test_read_status_falls_back_too(self, tmp_path):
        path = str(tmp_path / "torn2")
        _, state = _make_state()
        with CheckpointManager(path) as mngr:
            mngr.save(state, TrainStatus(epoch=3, step=1), step=1)
            mngr.save(state, TrainStatus(epoch=4, step=2), step=2)
            mngr.wait()
        self._corrupt(path, 2)
        with CheckpointManager(path) as mngr:
            got = mngr.read_status()
        assert got is not None and got.epoch == 3

    def test_all_versions_corrupt_raises(self, tmp_path):
        path = str(tmp_path / "torn3")
        _, state = _make_state()
        with CheckpointManager(path) as mngr:
            mngr.save(state, TrainStatus(step=1), step=1)
            mngr.wait()
        self._corrupt(path, 1)
        with CheckpointManager(path) as mngr:
            _, template = _make_state(rng=1)
            with pytest.raises(Exception):
                mngr.restore(template)

    def test_explicit_step_does_not_fall_back(self, tmp_path):
        """A caller who PINNED a step asked for that version, not an
        older one — corruption there must surface, not silently swap."""
        path = str(tmp_path / "torn4")
        _, state = _make_state()
        with CheckpointManager(path) as mngr:
            mngr.save(state, TrainStatus(step=1), step=1)
            mngr.save(state, TrainStatus(step=2), step=2)
            mngr.wait()
        self._corrupt(path, 2)
        with CheckpointManager(path) as mngr:
            _, template = _make_state(rng=1)
            with pytest.raises(Exception):
                mngr.restore(template, step=2)
            assert sorted(mngr.all_steps()) == [1, 2]  # nothing purged


class TestAdjust:
    def test_linear_lr_and_merge(self):
        reg = AdjustRegistry()
        reg.register(linear_scaled_lr(0.1, base_world_size=8))
        reg.register(lambda status, world: {"batch_per_worker": 32})
        out = reg.resolve(TrainStatus(epoch=1), world_size=16)
        assert out["lr"] == pytest.approx(0.2)
        assert out["batch_per_worker"] == 32


class TestAsyncCheckpoint:
    """async_save=True: saves overlap training (Orbax async), wait()
    finalizes, restore round-trips — the TPU-native answer to the
    reference's blocking rank-0 HDFS uploads (train_with_fleet.py:563)."""

    def test_async_save_roundtrip_and_status(self, tmp_path):
        model, state = _make_state()
        with CheckpointManager(str(tmp_path), async_save=True) as mngr:
            step = make_train_step(mse_loss, donate=False)
            x = jnp.ones((8, 8)); y = jnp.zeros((8, 4))
            for epoch in range(3):
                state, _ = step(state, (x, y))
                mngr.save(state, TrainStatus(epoch=epoch, step=int(state.step)))
            mngr.wait()
            assert mngr.latest_step() == 3
            assert mngr.read_status().epoch == 2
            _, fresh = _make_state(rng=1)
            restored, status = mngr.restore(fresh)
            assert status.epoch == 2
            for a, b in zip(
                jax.tree.leaves(restored.params), jax.tree.leaves(state.params)
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_read_status_without_state(self, tmp_path):
        model, state = _make_state()
        with CheckpointManager(str(tmp_path)) as mngr:
            assert mngr.read_status() is None
            mngr.save(state, TrainStatus(epoch=7, step=0))
            mngr.wait()
            got = mngr.read_status()
            assert got.epoch == 7
