"""Hot restage: surviving workers adopt new stages in-process.

Drives real launchers in EDL_HOT_RESTAGE=1 mode with the instrumented
hot_churn_worker and asserts the defining property stop-resume cannot
have: the SAME worker process (one pid) trains across multiple cluster
generations, including a grow (world 1 -> 2) and a shrink back after a
peer pod is SIGKILLed, with the job still completing and checkpointed
resume intact.
"""

import os
import subprocess
import sys
import time
from collections import defaultdict

import pytest

# Multi-worker stages make jax.distributed ride Gloo for CPU collectives,
# and on this environment's jax build the Gloo rendezvous times out
# (FAILED_PRECONDITION: Gloo context initialization failed:
# DEADLINE_EXCEEDED: GetKeyValue() timed out) for every world >= 2 stage.
# Skip with the reason on record instead of red noise; opt back in with
# EDL_TEST_GLOO_MP=1 where the Gloo transport works.
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.environ.get("EDL_TEST_GLOO_MP", "0") != "1",
        reason="jax CPU multi-process collectives (Gloo rendezvous) hit "
        "DEADLINE_EXCEEDED here; set EDL_TEST_GLOO_MP=1 to run",
    ),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "hot_churn_worker.py")


def hot_marks(out_dir):
    """{stage: {(rank, world, pid, epoch), ...}} from the worker markers."""
    runs = defaultdict(set)
    for name in os.listdir(out_dir):
        if not name.startswith("ep."):
            continue
        _, stage, rank, world, pid, epoch = name.split(".")
        runs[stage].add((int(rank), int(world), int(pid), int(epoch)))
    return dict(runs)


def spawn(store, job_id, out_dir, ckpt, pause="0.5"):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "TEST_OUT_DIR": out_dir,
            "TEST_EPOCH_PAUSE": pause,
            "EDL_HOT_RESTAGE": "1",
            # generous: under full-suite CPU contention a tight grace
            # makes the worker fall back to a (legitimate) cold respawn,
            # which is exactly what this test must distinguish from
            "EDL_HOT_GRACE": "90",
        }
    )
    return subprocess.Popen(
        [
            sys.executable, "-m", "edl_tpu.launch",
            "--job_id", job_id,
            "--store", store.endpoint,
            "--nodes_range", "1:2",
            "--nproc_per_node", "1",
            "--ttl", "0.8",
            "--ckpt_path", ckpt,
            WORKER,
        ],
        env=env,
        cwd=REPO,
    )


def wait_for(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.3)
    raise AssertionError("timeout: " + msg)


def test_grow_and_shrink_same_pid(store, tmp_path):
    """Pod A trains alone; pod B joins (grow handled in-process by A);
    B is SIGKILLed (shrink handled in-process or via fallback); the job
    completes. Pod A's worker pid must span the world-1 AND world-2
    stages — the surviving process adopted a new generation without a
    respawn."""
    out = str(tmp_path / "out")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(out)
    # slow epochs: under full-suite load pod B's join can take tens of
    # seconds, and the job must still be mid-training when the grow lands
    a = spawn(store, "hot1", out, ckpt, pause="1.5")
    b = None
    try:
        wait_for(
            lambda: any(
                w == 1 for runs in hot_marks(out).values()
                for (_, w, _, _) in runs
            ),
            90, "world-1 stage trained",
        )
        b = spawn(store, "hot1", out, ckpt, pause="1.5")
        wait_for(
            lambda: any(
                w == 2 for runs in hot_marks(out).values()
                for (_, w, _, _) in runs
            ),
            120, "world-2 stage trained",
        )
        # the grow must have been adopted in-process: one pid appears in
        # both a world-1 and a world-2 stage
        marks = hot_marks(out)
        pids_by_world = defaultdict(set)
        for runs in marks.values():
            for rank, world, pid, _ in runs:
                pids_by_world[world].add(pid)
        shared = pids_by_world[1] & pids_by_world[2]
        assert shared, (
            "no pid spans world 1 and 2 (grow was not in-process): %r"
            % pids_by_world
        )
        # kill pod B mid-training: A must carry the job to completion
        b.kill()
        b.wait()
        b = None
        # budget covers a wedged shrink adoption (full EDL_HOT_GRACE=90)
        # plus a cold respawn + remaining 1.5s-paced epochs under load
        assert a.wait(timeout=300) == 0
        done = [f for f in os.listdir(out) if f.startswith("done.")]
        assert done, "no completion marker"
        # every epoch 0..5 ran somewhere (resume contract held)
        epochs = {
            e for runs in hot_marks(out).values() for (_, _, _, e) in runs
        }
        assert epochs == set(range(6)), epochs
    finally:
        for p in (a, b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def test_hot_disabled_respawns(store, tmp_path):
    """Control: without EDL_HOT_RESTAGE the same drill changes pids
    between stages (stop-resume semantics unchanged by this feature)."""
    out = str(tmp_path / "out")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(out)

    def spawn_cold(job_id):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "TEST_OUT_DIR": out,
            # same mid-training-when-B-joins mitigation as the grow test
            "TEST_EPOCH_PAUSE": "1.5",
        })
        return subprocess.Popen(
            [
                sys.executable, "-m", "edl_tpu.launch",
                "--job_id", job_id,
                "--store", store.endpoint,
                "--nodes_range", "1:2",
                "--nproc_per_node", "1",
                "--ttl", "0.8",
                "--ckpt_path", ckpt,
                WORKER,
            ],
            env=env,
            cwd=REPO,
        )

    a = spawn_cold("cold1")
    b = None
    try:
        wait_for(
            lambda: any(
                w == 1 for runs in hot_marks(out).values()
                for (_, w, _, _) in runs
            ),
            90, "world-1 stage trained",
        )
        b = spawn_cold("cold1")
        wait_for(
            lambda: any(
                w == 2 for runs in hot_marks(out).values()
                for (_, w, _, _) in runs
            ),
            120, "world-2 stage trained",
        )
        pids_by_world = defaultdict(set)
        for runs in hot_marks(out).values():
            for rank, world, pid, _ in runs:
                pids_by_world[world].add(pid)
        assert not (pids_by_world[1] & pids_by_world[2]), (
            "cold mode must respawn between stages: %r" % pids_by_world
        )
    finally:
        for p in (a, b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
