"""Chaos subsystem tests: fault-plane unit semantics, satellite
integrations (retry helper, injection observability), the fault-point
catalogue lint, and the deterministic recovery scenarios (tier-1; each
drives real launcher pods + store under injected faults).
"""

import json
import os
import pathlib
import re
import subprocess
import sys
import time

import pytest

from edl_tpu.chaos import invariants as inv
from edl_tpu.chaos import plane
from edl_tpu.chaos.plane import ChaosDrop

pytestmark = pytest.mark.chaos

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with a disarmed plane (it is
    process-global state)."""
    plane.disarm()
    yield
    plane.disarm()


class TestFaultPoint:
    def test_disarmed_is_identity(self):
        fp = plane.fault_point("test.unit.idle", "never armed")
        assert fp.armed is False
        assert fp.fire(b"payload") == b"payload"
        assert fp.fire() is None

    def test_after_times_and_reset(self):
        fp = plane.fault_point("test.unit.count", "x")
        plane.configure(
            {"rules": [{"point": "test.unit.count", "action": "corrupt",
                        "after": 2, "times": 2}]},
            who="w",
        )
        assert fp.fire(b"aaaa") == b"aaaa"       # 1st matching fire passes
        assert fp.fire(b"aaaa") != b"aaaa"       # 2nd triggers
        assert fp.fire(b"aaaa") != b"aaaa"       # 3rd still (times=2)
        assert fp.fire(b"aaaa") == b"aaaa"       # exhausted
        plane.disarm()
        assert not fp.armed

    def test_match_filters_ctx(self):
        fp = plane.fault_point("test.unit.match", "x")
        plane.configure(
            {"rules": [{"point": "test.unit.match", "action": "drop",
                        "match": {"rank": "1"}}]},
            who="w",
        )
        fp.fire(rank=0)  # no match, no fault
        with pytest.raises(ChaosDrop):
            fp.fire(rank=1)

    def test_proc_prefix_filter(self):
        fp = plane.fault_point("test.unit.proc", "x")
        armed = plane.configure(
            {"rules": [{"point": "test.unit.proc", "action": "drop",
                        "proc": "launcher"}]},
            who="worker-3",
        )
        assert armed == 0 and not fp.armed

    def test_delay_sleeps(self):
        fp = plane.fault_point("test.unit.delay", "x")
        plane.configure(
            {"rules": [{"point": "test.unit.delay", "action": "delay",
                        "delay_s": 0.05}]},
            who="w",
        )
        t0 = time.monotonic()
        fp.fire()
        assert time.monotonic() - t0 >= 0.05

    def test_seeded_prob_schedule_is_deterministic(self):
        fp = plane.fault_point("test.unit.seeded", "x")

        def schedule(seed):
            plane.configure(
                {"seed": seed,
                 "rules": [{"point": "test.unit.seeded", "action": "corrupt",
                            "prob": 0.5, "times": 0}]},
                who="w",
            )
            return [fp.fire(b"zz") != b"zz" for _ in range(32)]

        a, b = schedule(7), schedule(7)
        assert a == b
        assert any(a) and not all(a)
        assert schedule(8) != a

    def test_partition_windows_reopen(self):
        """``times`` counts WINDOWS for partition: after one window
        expires, the next matching fire can open another."""
        fp = plane.fault_point("test.unit.partition", "x")
        plane.configure(
            {"rules": [{"point": "test.unit.partition", "action": "partition",
                        "duration_s": 0.05, "times": 2}]},
            who="w",
        )
        with pytest.raises(ChaosDrop):
            fp.fire()  # opens window 1
        with pytest.raises(ChaosDrop):
            fp.fire()  # still inside window 1
        time.sleep(0.06)
        with pytest.raises(ChaosDrop):
            fp.fire()  # opens window 2
        time.sleep(0.06)
        fp.fire()  # both windows spent: no fault

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            plane.configure(
                {"rules": [{"point": "p", "action": "meltdown"}]}, who="w"
            )

    def test_rule_attaches_to_later_declared_point(self):
        plane.configure(
            {"rules": [{"point": "test.unit.late%d" % os.getpid(),
                        "action": "drop"}]},
            who="w",
        )
        fp = plane.fault_point("test.unit.late%d" % os.getpid(), "declared after")
        assert fp.armed
        with pytest.raises(ChaosDrop):
            fp.fire()

    def test_injection_metric_and_ledger(self, tmp_path, monkeypatch):
        from edl_tpu.obs import metrics as obs_metrics

        log = tmp_path / "chaos.log"
        monkeypatch.setenv("EDL_CHAOS_LOG", str(log))
        fp = plane.fault_point("test.unit.ledger", "x")
        plane.configure(
            {"rules": [{"point": "test.unit.ledger", "action": "delay",
                        "delay_s": 0.0}]},
            who="w",
        )
        counter = obs_metrics.counter("edl_chaos_faults_injected_total")
        before = counter.value(point="test.unit.ledger", action="delay")
        fp.fire(step=3)
        assert counter.value(point="test.unit.ledger", action="delay") == before + 1
        entries = inv.read_chaos_log(str(log))
        assert entries and entries[-1]["point"] == "test.unit.ledger"
        assert entries[-1]["ctx"]["step"] == "3"

    def test_arm_from_env_inline_and_file(self, tmp_path, monkeypatch):
        spec = {"rules": [{"point": "test.unit.env", "action": "drop"}]}
        monkeypatch.setenv("EDL_CHAOS", json.dumps(spec))
        assert plane.arm_from_env("w") == 1
        path = tmp_path / "spec.json"
        path.write_text(json.dumps(spec))
        monkeypatch.setenv("EDL_CHAOS", "@%s" % path)
        assert plane.arm_from_env("w") == 1
        monkeypatch.setenv("EDL_CHAOS", "not json {")
        assert plane.arm_from_env("w") == 0
        monkeypatch.delenv("EDL_CHAOS")
        assert plane.arm_from_env("w") == 0

    def test_cohosted_arming_accumulates_identities(self, monkeypatch):
        """A launcher embedding a store arms twice ('store', then
        'launcher'); the second arm must not strip the first's rules."""
        spec = {"rules": [
            {"point": "test.unit.cohost.store", "action": "drop",
             "proc": "store"},
            {"point": "test.unit.cohost.launch", "action": "drop",
             "proc": "launcher"},
        ]}
        monkeypatch.setenv("EDL_CHAOS", json.dumps(spec))
        fp_store = plane.fault_point("test.unit.cohost.store", "x")
        fp_launch = plane.fault_point("test.unit.cohost.launch", "x")
        assert plane.arm_from_env("store") == 1
        assert plane.arm_from_env("launcher") == 2  # union, not last-wins
        assert fp_store.armed and fp_launch.armed

    def test_arm_from_store_keyspace(self, store):
        from edl_tpu.store.client import StoreClient

        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            spec = {"rules": [{"point": "test.unit.store", "action": "drop"}]}
            plane.publish_spec(client, "chaosjob", spec)
            assert plane.arm_from_store(client, "chaosjob", "w") == 1
            assert plane.arm_from_store(client, "emptyjob", "w") == 0
        finally:
            client.close()


class TestStoreClientFaults:
    """The store.client fault points convert to the Edl error family so
    every existing retry path handles an injected blip."""

    def test_request_drop_is_edl_connection_error(self, store):
        from edl_tpu.store.client import StoreClient
        from edl_tpu.utils.exceptions import EdlConnectionError

        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            plane.configure(
                {"rules": [{"point": "store.client.request", "action": "drop",
                            "times": 2}]},
                who="w",
            )
            with pytest.raises(EdlConnectionError):
                client.put("/k", b"v")
            # retrying() rides over the remaining drop and lands the put
            assert client.retrying("put", k="/k", v=b"v", l=0)["r"] > 0
            plane.disarm()
            assert client.get("/k") == b"v"
        finally:
            client.close()

    def test_watch_gap_resyncs_under_request_faults(self, monkeypatch):
        """Satellite: a watch whose resume revision was compacted away
        must fall back to a full resync (one RESYNC marker, then live
        events) — and get there THROUGH injected store.client.request
        drops on the re-establishment path."""
        import socket as _socket
        import threading

        from edl_tpu.store.client import RESYNC, StoreClient
        from edl_tpu.store.kv import StoreState
        from edl_tpu.store.server import StoreServer

        monkeypatch.setattr(StoreState, "HISTORY_LIMIT", 4)
        srv = StoreServer(host="127.0.0.1", port=0).start()
        writer = StoreClient(srv.endpoint, timeout=5.0)
        client = StoreClient(srv.endpoint, timeout=5.0)
        try:
            events = []
            lock = threading.Lock()

            def cb(evs):
                with lock:
                    events.extend(evs)

            client.watch("/g/", cb)
            client.put("/g/before", b"1")
            deadline = time.time() + 5
            while time.time() < deadline and not events:
                time.sleep(0.02)
            # drop the FIRST watch re-establishment attempt: the resume
            # path must absorb the blip and retry on the next lap
            plane.configure(
                {"rules": [{"point": "store.client.request",
                            "action": "drop", "match": {"method": "watch"},
                            "times": 1}]},
                who="w",
            )
            # sever the link, then blow past the 4-event history ring
            # while the client is down: the resume revision is gone
            client._sock.shutdown(_socket.SHUT_RDWR)
            for i in range(8):
                writer.put("/g/gap%d" % i, b"%d" % i)
            deadline = time.time() + 10
            while time.time() < deadline and not any(
                e.type == RESYNC for e in events
            ):
                time.sleep(0.05)
            with lock:
                types = [e.type for e in events]
            assert RESYNC in types, types
            # consumer contract after a resync: re-range, then live
            # events flow again
            kvs, _rev = client.retrying("range", p="/g/")["kvs"], None
            assert len(kvs) == 9
            writer.put("/g/live", b"z")
            deadline = time.time() + 5
            while time.time() < deadline and not any(
                e.key == "/g/live" for e in events
            ):
                time.sleep(0.05)
            with lock:
                assert any(e.key == "/g/live" for e in events)
                # the resync replaced the gap: none of the compacted
                # events were replayed piecemeal
                assert sum(1 for e in events if e.type == RESYNC) == 1
        finally:
            plane.disarm()
            client.close()
            writer.close()
            srv.stop()

    def test_replication_stream_drop_recovers_by_resync(self, tmp_path):
        """An injected store.replication.stream drop severs the standby's
        link; it must re-bootstrap and converge again."""
        from edl_tpu.store.client import StoreClient
        from edl_tpu.store.server import StoreServer

        primary = StoreServer(
            host="127.0.0.1", port=0, data_dir=str(tmp_path / "p")
        ).start()
        standby = StoreServer(
            host="127.0.0.1", port=0, data_dir=str(tmp_path / "s"),
            follow=primary.endpoint, priority=1, failover_grace=30.0,
        ).start()
        client = StoreClient(primary.endpoint, timeout=5.0)
        try:
            deadline = time.time() + 15
            while time.time() < deadline and not standby._has_state:
                time.sleep(0.02)
            plane.configure(
                {"rules": [{"point": "store.replication.stream",
                            "action": "drop", "match": {"side": "tx"},
                            "times": 1}]},
                who="w",
            )
            for i in range(4):
                client.put("/rs/k%d" % i, b"%d" % i)
            plane.disarm()
            client.put("/rs/final", b"done")
            deadline = time.time() + 20
            while time.time() < deadline and (
                standby._state.get("/rs/final") is None
            ):
                time.sleep(0.05)
            assert standby._state.get("/rs/final") is not None
            for i in range(4):
                assert standby._state.get("/rs/k%d" % i) is not None
        finally:
            plane.disarm()
            client.close()
            standby.stop()
            primary.stop()

    def test_retry_counter_advances(self, store):
        from edl_tpu.obs import metrics as obs_metrics
        from edl_tpu.store.client import StoreClient

        client = StoreClient(store.endpoint, timeout=5.0)
        counter = obs_metrics.counter("edl_rpc_retries_total")
        before = counter.value(what="store.request")
        try:
            plane.configure(
                {"rules": [{"point": "store.client.request", "action": "drop",
                            "times": 3}]},
                who="w",
            )
            client.retrying("put", k="/r", v=b"1", l=0)
        finally:
            plane.disarm()
            client.close()
        assert counter.value(what="store.request") >= before + 3


class TestWireFaults:
    def test_corrupt_tx_breaks_magic(self):
        from edl_tpu.rpc.wire import FrameReader, WireError, pack_frame

        plane.configure(
            {"rules": [{"point": "rpc.wire.tx", "action": "corrupt"}]},
            who="w",
        )
        frame = pack_frame({"i": 1, "m": "ping"})
        with pytest.raises(WireError):
            FrameReader().feed(frame)
        plane.disarm()
        assert FrameReader().feed(pack_frame({"i": 2}))[0]["i"] == 2

    def test_wal_paths_exempt_from_wire_faults(self):
        """The store's journal serializes through the same codec as the
        network: a 'network' fault must never corrupt durable state, and
        WAL replay must never see an injected rx drop."""
        from edl_tpu.rpc.wire import FrameReader, pack_frame

        plane.configure(
            {"rules": [
                {"point": "rpc.wire.tx", "action": "corrupt", "times": 0},
                {"point": "rpc.wire.rx", "action": "drop", "times": 0},
            ]},
            who="w",
        )
        frame = pack_frame({"op": "ev", "k": "/x"}, fault=False)  # journal write
        got = FrameReader(fault=False).feed(frame)                # journal replay
        assert got == [{"op": "ev", "k": "/x"}]

    def test_store_durability_survives_tx_corrupt(self, tmp_path):
        """End-to-end: a tx-corrupt rule on the store process must not
        poison the WAL — a killed-and-restarted store still recovers
        every key."""
        from edl_tpu.store.client import StoreClient
        from edl_tpu.store.server import StoreServer

        data_dir = str(tmp_path / "store")
        srv = StoreServer(host="127.0.0.1", port=0, data_dir=data_dir).start()
        plane.configure(
            {"rules": [{"point": "rpc.wire.tx", "action": "corrupt",
                        "after": 3, "times": 2}]},
            who="w",
        )
        try:
            client = StoreClient(srv.endpoint, timeout=5.0, reconnect=True)
            for i in range(6):
                client.retrying("put", k="/d/%d" % i, v=b"v%d" % i, l=0)
            client.close()
        finally:
            plane.disarm()
            srv.stop()
        srv2 = StoreServer(host="127.0.0.1", port=0, data_dir=data_dir).start()
        try:
            client = StoreClient(srv2.endpoint, timeout=5.0)
            assert client.get("/d/5") == b"v5"
            client.close()
        finally:
            srv2.stop()


class TestRetryHelper:
    def test_retries_then_succeeds(self):
        from edl_tpu.utils.retry import retry_call

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ValueError("boom")
            return "ok"

        assert retry_call(
            flaky, what="t", retry_on=(ValueError,), base_delay=0.001
        ) == "ok"
        assert len(calls) == 3

    def test_bounded_retries_reraise(self):
        from edl_tpu.utils.retry import retry_call

        def always():
            raise ValueError("nope")

        with pytest.raises(ValueError):
            retry_call(
                always, what="t", retry_on=(ValueError,), retries=2,
                base_delay=0.001,
            )

    def test_give_up_stops_immediately(self):
        from edl_tpu.utils.retry import retry_call

        calls = []

        def always():
            calls.append(1)
            raise ValueError("nope")

        with pytest.raises(ValueError):
            retry_call(
                always, what="t", retry_on=(ValueError,),
                give_up=lambda: True, base_delay=0.001,
            )
        assert len(calls) == 1

    def test_deadline_bounds_total_time(self):
        from edl_tpu.utils.retry import retry_call

        t0 = time.monotonic()
        with pytest.raises(ValueError):
            retry_call(
                lambda: (_ for _ in ()).throw(ValueError("x")),
                what="t", retry_on=(ValueError,), deadline=0.2,
                base_delay=0.05,
            )
        assert time.monotonic() - t0 < 2.0

    def test_non_retryable_escapes_uncounted(self):
        from edl_tpu.utils.retry import retry_call

        with pytest.raises(KeyError):
            retry_call(
                lambda: (_ for _ in ()).throw(KeyError("x")),
                what="t", retry_on=(ValueError,), base_delay=0.001,
            )


# -- catalogue lint -----------------------------------------------------------


def test_every_fault_point_is_catalogued_in_design_md():
    """Mirror of the PR-1 metric-naming lint: every fault point declared
    in edl_tpu/ must appear in DESIGN.md's chaos catalogue (and the
    plane's own registry naming stays dotted-lowercase). Since the
    edl-lint PR this is a thin wrapper over the `fault-catalogue`
    analyzer pass — one AST-based implementation, shared with
    `python -m tools.edl_lint`."""
    from edl_tpu.analysis import (
        collect_fault_points, repo_context, run_analysis,
    )

    ctx = repo_context()
    declared = collect_fault_points(ctx)
    assert declared, "expected fault points declared under edl_tpu/"
    assert "train.step" in declared and "store.client.request" in declared
    findings, _ = run_analysis(ctx, only=["fault-catalogue"])
    assert not findings, (
        "fault-point catalogue violations:\n"
        + "\n".join(str(f) for f in findings)
    )


def test_chaos_marker_registered():
    text = (REPO / "pyproject.toml").read_text()
    assert "chaos:" in text, "register the chaos marker in pyproject.toml"


# -- deterministic recovery scenarios (tier-1) --------------------------------


class TestScenarios:
    """Each scenario drives real launcher pods + a real store through an
    injected fault and asserts the full recovery-invariant set. These are
    the acceptance drills for the elastic contract — deliberately kept in
    tier-1 (not slow) so elasticity regressions fail CI, not a demo."""

    def _run(self, name, tmp_path, seed=0):
        from edl_tpu.chaos.scenario import run_scenario

        outcome = run_scenario(name, seed, str(tmp_path))
        assert outcome.ok, "scenario %s RED:\n%s" % (
            name,
            "\n".join(str(r) for r in outcome.invariants if not r.ok),
        )
        return outcome

    def test_worker_kill_recovers(self, tmp_path):
        self._run("worker-kill", tmp_path)

    def test_store_blip_recovers(self, tmp_path):
        self._run("store-blip", tmp_path)

    def test_corrupt_checkpoint_falls_back(self, tmp_path):
        self._run("corrupt-ckpt", tmp_path)

    def test_ckpt_peer_loss_restores_from_peers(self, tmp_path):
        self._run("ckpt-peer-loss", tmp_path)

    def test_slow_rpc_tail_completes_single_stage(self, tmp_path):
        self._run("slow-rpc", tmp_path)

    def test_teacher_failover_exactly_once(self, tmp_path):
        self._run("teacher-failover", tmp_path)

    def test_store_failover_promotes_and_fences(self, tmp_path):
        outcome = self._run("store-failover", tmp_path)
        assert outcome.info.get("promote_s") is not None

    def test_store_shard_failover_zero_acked_loss_per_shard(self, tmp_path):
        """EVERY primary of a 2-shard control plane dies: each shard's
        standby promotes independently, an acked (semi-sync held) write
        on each shard survives with its original revision, and the job
        trains through it — the strict per-shard zero-loss contract."""
        outcome = self._run("store-shard-failover", tmp_path)
        assert len(outcome.info.get("shards", [])) == 2
        assert all(e >= 1 for e in outcome.info.get("epochs", []))

    def test_preempt_drain_restages_without_grace(self, tmp_path):
        """SIGTERM is an advance notice, not a kill: emergency ckpt within
        budget, DRAINED exit, proactive restage, lost work <= one step."""
        outcome = self._run("preempt-drain", tmp_path)
        assert outcome.info.get("drained_rc") == 76

    def test_straggler_stall_ejects_wedged_worker(self, tmp_path):
        """A worker wedged mid-step forever is ejected by the heartbeat
        watchdog within its deadline (the false-positive drill rides the
        slow-rpc scenario: zero ejections there)."""
        self._run("straggler-stall", tmp_path)

    def test_monitor_clean_fires_nothing(self, tmp_path):
        """The monitor plane's zero-false-positive control: a clean run
        through completion and the post-completion quiet publishes not a
        single alert (the red counterpart — goodput-degraded MUST fire —
        rides the worker-kill and preempt-drain drills above)."""
        outcome = self._run("monitor-clean", tmp_path)
        assert outcome.info.get("monitor_health", {}).get("firing") == []


class TestChaosRunCli:
    def test_list_and_unknown(self, tmp_path):
        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "chaos_run.py"), "--list"],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0
        for name in ("worker-kill", "store-blip", "corrupt-ckpt"):
            assert name in out.stdout
