"""Goodput ledger & flight recorder: crash-safe recording, wall-clock
attribution, the edl-timeline postmortem tool, and the conformance
invariant that audits the accounting itself.

Tier-1 (no jax): everything here is pure control-plane code.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

from edl_tpu.chaos import invariants as inv
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import goodput as obs_goodput
from edl_tpu.obs.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """The recorder is a process singleton keyed off the env: reset it
    around every test so EDL_FLIGHT_DIR monkeypatching takes effect."""
    obs_events.reset()
    yield
    obs_events.reset()


# -- flight recorder ----------------------------------------------------------


class TestFlightRecorder:
    def test_record_and_read_roundtrip(self, tmp_path):
        rec = obs_events.FlightRecorder(str(tmp_path), component="w0", pid=42)
        rec.record("goodput", fsync=True, state="train", prev="restage", dur=1.5)
        rec.record("step", step=3)
        rec.close()
        events = obs_events.read_segments(str(tmp_path))
        assert [e["event"] for e in events] == ["goodput", "step"]
        assert events[0]["component"] == "w0" and events[0]["pid"] == 42
        assert events[0]["state"] == "train" and events[1]["step"] == 3
        assert events[0]["ts"] <= events[1]["ts"]

    def test_ring_rotation_keeps_max_segments(self, tmp_path):
        rec = obs_events.FlightRecorder(
            str(tmp_path), component="w", pid=1, seg_bytes=4096, max_segs=3
        )
        for i in range(2000):
            rec.record("e", i=i, pad="x" * 64)
        rec.close()
        segs = sorted(tmp_path.glob("*.flight.jsonl"))
        assert 1 <= len(segs) <= 3
        # the newest records survive the ring; the oldest were dropped
        events = obs_events.read_segments(str(tmp_path))
        assert events[-1]["i"] == 1999
        assert events[0]["i"] > 0

    def test_torn_tail_line_is_skipped(self, tmp_path):
        rec = obs_events.FlightRecorder(str(tmp_path), component="w", pid=7)
        rec.record("good", k=1)
        rec.close()
        seg = next(tmp_path.glob("*.flight.jsonl"))
        with open(seg, "ab") as f:
            f.write(b'{"ts": 1.0, "event": "torn", "half')  # kill mid-write
        events = obs_events.read_segments(str(tmp_path))
        assert [e["event"] for e in events] == ["good"]

    def test_module_record_noop_without_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv("EDL_FLIGHT_DIR", raising=False)
        obs_events.reset()
        obs_events.record("anything", k=1)  # must not raise, must not write
        assert obs_events.get_recorder() is None
        assert list(tmp_path.iterdir()) == []

    def test_module_record_writes_with_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("EDL_FLIGHT_DIR", str(tmp_path))
        obs_events.reset()
        obs_events.record("hello", fsync=True, n=1)
        events = obs_events.read_segments(str(tmp_path))
        assert events and events[0]["event"] == "hello"

    def test_survives_sigkill_style_death(self, tmp_path):
        """The acceptance property: a process that records transitions
        then dies via os._exit(137) — no atexit, no flush — leaves every
        recorded transition readable."""
        script = """
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["EDL_FLIGHT_DIR"] = %(dir)r
from edl_tpu.obs import events, goodput
goodput.enter("restage", cause="spawn")
goodput.enter("train", cause="resumed")
events.record("step", step=5)
os._exit(137)  # SIGKILL-equivalent: torn, unflushed, no teardown
""" % {"repo": REPO, "dir": str(tmp_path)}
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 137
        events = obs_events.read_segments(str(tmp_path))
        kinds = [(e["event"], e.get("state")) for e in events]
        assert ("goodput", "restage") in kinds
        assert ("goodput", "train") in kinds  # the LAST transition survived
        assert events[-1]["event"] == "step"


# -- goodput ledger -----------------------------------------------------------


class TestGoodputLedger:
    def test_transitions_accumulate_per_state_and_cause(self, tmp_path, monkeypatch):
        monkeypatch.setenv("EDL_FLIGHT_DIR", str(tmp_path))
        obs_events.reset()
        reg = MetricsRegistry()
        led = obs_goodput.GoodputLedger(registry=reg)
        led.enter("restage", cause="spawn")
        time.sleep(0.02)
        led.enter("train")
        time.sleep(0.02)
        led.close(cause="complete")
        counter = reg.get("edl_goodput_seconds_total")
        assert counter.value(state="restage", cause="spawn") >= 0.02
        assert counter.value(state="train", cause="") >= 0.02
        # the fsync'd transitions are on disk
        recorded = [
            e for e in obs_events.read_segments(str(tmp_path))
            if e["event"] == "goodput"
        ]
        assert [e["state"] for e in recorded] == ["restage", "train", None]
        assert recorded[1]["prev"] == "restage" and recorded[1]["dur"] >= 0.02

    def test_phase_nests_and_restores(self):
        reg = MetricsRegistry()
        led = obs_goodput.GoodputLedger(registry=reg)
        led.enter("train")
        with led.phase("ckpt_save", cause="emergency"):
            assert led.state() == "ckpt_save"
            with led.phase("ckpt_restore"):
                assert led.state() == "ckpt_restore"
            assert led.state() == "ckpt_save"
        assert led.state() == "train"
        led.close()

    def test_ratio_counts_open_interval(self):
        reg = MetricsRegistry()
        led = obs_goodput.GoodputLedger(registry=reg)
        assert led._ratio() == 0.0
        led.enter("train")
        time.sleep(0.02)
        assert led.seconds("train") >= 0.02  # open interval included
        assert led._ratio() == pytest.approx(1.0, abs=0.05)
        led.close()

    def test_unknown_state_rejected(self):
        led = obs_goodput.GoodputLedger(registry=MetricsRegistry())
        with pytest.raises(ValueError):
            led.enter("coffee_break")

    def test_ratio_gauge_registered_for_scrapes(self):
        reg = MetricsRegistry()
        obs_goodput.GoodputLedger(registry=reg)
        assert "edl_goodput_ratio" in reg.render()


# -- merged attribution -------------------------------------------------------


def _ev(ts, component, pid, state, prev, dur):
    return {
        "ts": ts, "event": "goodput", "component": component, "pid": pid,
        "state": state, "prev": prev, "dur": dur,
    }


class TestAttribution:
    def test_partitions_wall_clock_with_down_gap(self):
        # lane A: [0,4) restage(1) train(3); dies. lane B: [6,9) restage(1)
        # train(2). The [4,6) gap is down. Window = [0,9].
        events = [
            _ev(0.0, "w0", 1, "restage", None, 0.0),
            _ev(1.0, "w0", 1, "train", "restage", 1.0),
            _ev(4.0, "w0", 1, None, "train", 3.0),
            _ev(6.0, "w0", 2, "restage", None, 0.0),
            _ev(7.0, "w0", 2, "train", "restage", 1.0),
            _ev(9.0, "w0", 2, None, "train", 2.0),
        ]
        att = obs_goodput.attribute(events)
        assert att["wall_s"] == pytest.approx(9.0)
        assert att["states"]["train"] == pytest.approx(5.0)
        assert att["states"]["restage"] == pytest.approx(2.0)
        assert att["states"]["down"] == pytest.approx(2.0)
        assert sum(att["states"].values()) == pytest.approx(att["wall_s"])
        table = obs_goodput.render_table(att)
        assert "100.00" in table.splitlines()[-1]

    def test_priority_prefers_train_across_lanes(self):
        # one lane trains [0,4) while the other restages [0,4): the job
        # lane counts those seconds as train
        events = [
            _ev(0.0, "a", 1, "train", None, 0.0),
            _ev(4.0, "a", 1, None, "train", 4.0),
            _ev(0.0, "b", 2, "restage", None, 0.0),
            _ev(4.0, "b", 2, None, "restage", 4.0),
        ]
        att = obs_goodput.attribute(events)
        assert att["states"].get("train") == pytest.approx(4.0)
        assert "restage" not in att["states"]

    def test_killed_lane_bounded_by_last_record(self):
        # the open train interval is bounded by the lane's last record
        # (a step marker), not extrapolated to the window end
        events = [
            _ev(0.0, "w", 1, "train", None, 0.0),
            {"ts": 2.0, "event": "step", "component": "w", "pid": 1, "step": 9},
            {"ts": 10.0, "event": "publish", "component": "launcher", "pid": 2},
        ]
        att = obs_goodput.attribute(events)
        assert att["states"]["train"] == pytest.approx(2.0)
        assert att["states"]["down"] == pytest.approx(8.0)


class TestGoodputAccountedInvariant:
    def test_green_on_contiguous_accounting(self):
        events = [
            _ev(0.0, "w", 1, "restage", None, 0.0),
            _ev(2.0, "w", 1, "train", "restage", 2.0),
            _ev(10.0, "w", 1, None, "train", 8.0),
        ]
        result = inv.goodput_accounted(events)
        assert result.ok, result.detail

    def test_red_when_a_lane_loses_seconds(self):
        # the ledger "lost" [2,8): intervals cover 4s of a 10s lifetime
        events = [
            _ev(0.0, "w", 1, "restage", None, 0.0),
            _ev(2.0, "w", 1, "train", "restage", 2.0),
            # 6-second hole: next transition claims only 2s of history
            _ev(10.0, "w", 1, None, "train", 2.0),
        ]
        result = inv.goodput_accounted(events)
        assert not result.ok
        assert "lane gaps" in result.detail

    def test_red_without_any_training(self):
        events = [
            _ev(0.0, "w", 1, "restage", None, 0.0),
            _ev(5.0, "w", 1, None, "restage", 5.0),
        ]
        result = inv.goodput_accounted(events)
        assert not result.ok
        assert "NO train" in result.detail

    def test_red_on_empty_evidence(self):
        assert not inv.goodput_accounted([]).ok


# -- edl-timeline -------------------------------------------------------------


def _write_flight(dirpath, component, pid, events):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(
        dirpath, "%s-%d.0000.flight.jsonl" % (component, pid)
    )
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(dict(ev, component=component, pid=pid)) + "\n")


class TestEdlTimeline:
    def _make_run(self, tmp_path):
        t0 = 1_700_000_000.0
        flight = str(tmp_path / "flight")
        _write_flight(flight, "launcher", 10, [
            {"ts": t0 + 0.0, "event": "leader", "leader": True},
            {"ts": t0 + 0.1, "event": "drain", "token": "abc", "cause": "bootstrap"},
            {"ts": t0 + 0.2, "event": "publish", "stage": "abc", "world": 1},
            {"ts": t0 + 0.3, "event": "spawn", "stage": "abc", "world": 1},
        ])
        _write_flight(flight, "worker-0", 11, [
            _ev(t0 + 1.0, "worker-0", 11, "restage", None, 0.0),
            _ev(t0 + 3.0, "worker-0", 11, "train", "restage", 2.0),
            _ev(t0 + 9.0, "worker-0", 11, None, "train", 6.0),
        ])
        # an obs trace alongside (merged into the chrome output)
        from edl_tpu.obs.trace import SpanTracer

        tracer = SpanTracer(component="worker-0")
        with tracer.span("train_step", step=1):
            time.sleep(0.002)
        os.makedirs(str(tmp_path / "traces"), exist_ok=True)
        tracer.export(str(tmp_path / "traces" / "worker-0-11.trace.json"))
        with open(str(tmp_path / "chaos.log"), "w") as f:
            f.write(json.dumps({
                "ts": t0 + 5.0, "point": "train.step", "action": "kill",
                "who": "worker", "pid": 11, "ctx": {"step": "4"},
            }) + "\n")
        return t0

    def test_prints_timeline_and_table_summing_to_100(self, tmp_path, capsys):
        import edl_timeline

        self._make_run(tmp_path)
        rc = edl_timeline.main([str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "TIMELINE" in out and "ATTRIBUTION" in out
        # causal chain present and ordered
        for a, b in (("leader", "drain"), ("drain", "publish"),
                     ("publish", "spawn"), ("spawn", "chaos_kill")):
            assert out.index(a) < out.index(b), (a, b)
        # the table's total row sums to 100%
        total_line = next(
            l for l in out.splitlines() if l.startswith("total")
        )
        assert float(total_line.split()[-1]) == pytest.approx(100.0, abs=0.1)
        assert "PER-PROCESS" in out and "worker-0-11" in out

    def test_emits_merged_chrome_trace(self, tmp_path, capsys):
        import edl_timeline

        self._make_run(tmp_path)
        out_path = str(tmp_path / "run.trace.json")
        assert edl_timeline.main([str(tmp_path), "-o", out_path]) == 0
        doc = json.loads(pathlib.Path(out_path).read_text())
        events = doc["traceEvents"]
        names = {e.get("name") for e in events}
        assert "train" in names          # goodput lane slice
        assert "train_step" in names     # obs-trace span rode along
        lanes = {
            e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert any("goodput worker-0-11" in l for l in lanes)

    def test_exit_2_on_empty_dir(self, tmp_path, capsys):
        import edl_timeline

        assert edl_timeline.main([str(tmp_path)]) == 2

    def test_runnable_as_module(self, tmp_path):
        """README contract: python -m tools.edl_timeline <run_dir>."""
        self._make_run(tmp_path)
        out = subprocess.run(
            [sys.executable, "-m", "tools.edl_timeline", str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert out.returncode == 0, out.stderr
        assert "ATTRIBUTION" in out.stdout


# -- edl-top quantile helper --------------------------------------------------


def test_histogram_quantile_from_scrape():
    import edl_top

    metrics = {
        "edl_train_step_heartbeat_age_seconds_bucket": {
            '{le="0.1",worker="0"}': 50.0,
            '{le="1",worker="0"}': 90.0,
            '{le="+Inf",worker="0"}': 100.0,
        }
    }
    p50 = edl_top.histogram_quantile(
        metrics, "edl_train_step_heartbeat_age_seconds", 0.5
    )
    p95 = edl_top.histogram_quantile(
        metrics, "edl_train_step_heartbeat_age_seconds", 0.95
    )
    assert p50 == pytest.approx(0.1)
    assert p95 == pytest.approx(1.0)  # open bucket: lower bound reported
    assert edl_top.histogram_quantile({}, "nope", 0.5) is None
