"""Worker proving the REAL multi-host seam: launcher env contract ->
``edl_tpu.train.init()`` -> ``jax.distributed.initialize`` -> a global
array + cross-process XLA collective (Gloo on CPU; ICI/DCN on TPU pods).

This is the exact bootstrap path the reference fills with
``fleet.init(PaddleCloudRoleMaker)`` + NCCL (train_with_fleet.py:377).
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")

from edl_tpu.train import init  # noqa: E402

env = init()  # world > 1: dials the coordinator published by the launcher

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

mesh = Mesh(jax.devices(), ("dp",))
local = jnp.ones((jax.local_device_count(),), jnp.float32) * (
    env.global_rank + 1
)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local
)
total = jax.jit(
    lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P())
)(arr)

out = os.path.join(
    os.environ["TEST_OUT_DIR"], "psum.%d" % env.global_rank
)
with open(out, "w") as f:
    f.write(
        "%d %d %d %.1f"
        % (
            env.world_size,
            jax.process_count(),
            jax.local_device_count(),
            float(total),
        )
    )

# hold until the launcher terminates us: coordinator-death tests need live
# workers to drain + restage (an exited job can't re-form a world)
import time  # noqa: E402

while True:
    time.sleep(0.1)
