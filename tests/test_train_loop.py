"""ElasticTrainer: the one-call elastic loop (reference intent:
test_train.py:28-67 PaddleState/register_adjust_function sketch)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.checkpoint import AdjustRegistry, linear_scaled_lr
from edl_tpu.models import MLP
from edl_tpu.train import ElasticTrainer, mse_loss


def _records(epoch, n=256, d=8, seed_base=100):
    rs = np.random.RandomState(seed_base + epoch)
    w = np.linspace(-1, 1, d)[:, None].astype(np.float32)
    for _ in range(n):
        x = rs.randn(d).astype(np.float32)
        yield x, (x @ w).astype(np.float32)


def test_fit_record_stream_loss_decreases():
    seen = []
    trainer = ElasticTrainer(
        MLP(hidden=(16,), features=1),
        optax.sgd(0.05),
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        batch_size=8,
        log=False,
    )
    state = trainer.fit(
        _records, epochs=3,
        on_epoch_end=lambda e, m: seen.append(float(m["loss"])),
    )
    assert len(seen) == 3
    assert seen[-1] < seen[0] * 0.5, seen
    assert int(state.step) == 3 * (256 // 8)


def test_fit_resumes_from_checkpoint(tmp_path):
    def make(log=False):
        return ElasticTrainer(
            MLP(hidden=(16,), features=1),
            optax.sgd(0.05),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            batch_size=8,
            ckpt_dir=str(tmp_path / "ckpt"),
            log=log,
        )

    s1 = make().fit(_records, epochs=2)
    assert int(s1.step) == 2 * 32
    # second run resumes at epoch 2 and only trains epochs 2..3
    epochs_run = []
    s2 = make().fit(
        _records, epochs=4,
        on_epoch_end=lambda e, m: epochs_run.append(e),
    )
    assert epochs_run == [2, 3]
    assert int(s2.step) == 4 * 32


def test_adjust_registry_feeds_optimizer_factory(monkeypatch):
    monkeypatch.setenv("EDL_NUM_WORKERS", "4")
    adjusts = AdjustRegistry()
    adjusts.register(linear_scaled_lr(0.1, base_world_size=1))
    got = {}

    def factory(overrides):
        got.update(overrides)
        return optax.sgd(overrides.get("lr", 0.1))

    # world_size=4 from env, but no store/coordinator: barrier no-ops
    trainer = ElasticTrainer(
        MLP(hidden=(8,), features=1),
        factory,
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        batch_size=8,
        adjusts=adjusts,
        log=False,
    )
    trainer.fit(lambda e: _records(e, n=32), epochs=1)
    assert got == {"lr": pytest.approx(0.4)}


def test_fit_ready_batches_no_batch_size():
    def data(epoch):
        rs = np.random.RandomState(epoch)
        for _ in range(8):
            x = rs.randn(8, 8).astype(np.float32)
            yield x, x.sum(axis=1, keepdims=True).astype(np.float32)

    trainer = ElasticTrainer(
        MLP(hidden=(16,), features=1),
        optax.sgd(0.01),
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        log=False,
    )
    state = trainer.fit(data, epochs=2)
    assert int(state.step) == 16


class TestSchedulesAndProfiler:
    def test_piecewise_decay_boundaries(self):
        from edl_tpu.train import piecewise_decay

        sched = piecewise_decay(0.8, steps_per_epoch=10, boundaries_epochs=(2, 4))
        assert float(sched(0)) == pytest.approx(0.8)
        assert float(sched(19)) == pytest.approx(0.8)
        assert float(sched(20)) == pytest.approx(0.08)
        assert float(sched(40)) == pytest.approx(0.008)

    def test_warmup_cosine_shape(self):
        from edl_tpu.train import warmup_cosine

        sched = warmup_cosine(1.0, steps_per_epoch=10, total_epochs=10,
                              warmup_epochs=2)
        assert float(sched(0)) == pytest.approx(0.0)
        assert float(sched(20)) == pytest.approx(1.0)
        assert float(sched(100)) == pytest.approx(0.0, abs=1e-6)
        assert 0.0 < float(sched(60)) < 1.0

    def test_scaled_schedule_factory_in_trainer(self, monkeypatch):
        from edl_tpu.checkpoint import AdjustRegistry, linear_scaled_lr
        from edl_tpu.train import scaled_schedule_factory, warmup_cosine

        monkeypatch.setenv("EDL_NUM_WORKERS", "2")
        adjusts = AdjustRegistry()
        adjusts.register(linear_scaled_lr(0.1, base_world_size=1))
        peaks = []

        def make_sched(lr):
            peaks.append(lr)
            return warmup_cosine(lr, steps_per_epoch=4, total_epochs=2)

        trainer = ElasticTrainer(
            MLP(hidden=(8,), features=1),
            scaled_schedule_factory(make_sched),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            batch_size=8,
            adjusts=adjusts,
            log=False,
        )
        trainer.fit(lambda e: _records(e, n=32), epochs=1)
        assert peaks == [pytest.approx(0.2)]  # 0.1 x world 2

    def test_scaled_factory_requires_lr_override(self):
        from edl_tpu.train import scaled_schedule_factory, warmup_cosine

        factory = scaled_schedule_factory(
            lambda lr: warmup_cosine(lr, 1, 1)
        )
        with pytest.raises(ValueError, match="lr"):
            factory({})

    def test_profile_window_writes_trace(self, tmp_path, monkeypatch):
        monkeypatch.setenv("EDL_PROFILE_DIR", str(tmp_path / "trace"))
        trainer = ElasticTrainer(
            MLP(hidden=(8,), features=1),
            optax.sgd(0.01),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            batch_size=8,
            log=False,
        )
        # 20 steps/epoch crosses the (10, 15) profile window
        trainer.fit(lambda e: _records(e, n=160), epochs=1)
        import glob

        files = glob.glob(str(tmp_path / "trace" / "**" / "*"), recursive=True)
        assert files, "no trace output written"


class TestShuffled:
    def test_deterministic_and_complete(self):
        from edl_tpu.data import shuffled

        src = list(range(100))
        a = list(shuffled(iter(src), buffer_size=16, seed=3))
        b = list(shuffled(iter(src), buffer_size=16, seed=3))
        c = list(shuffled(iter(src), buffer_size=16, seed=4))
        assert a == b
        assert sorted(a) == src
        assert a != src  # actually shuffles
        assert a != c

    def test_small_stream_fits_in_buffer(self):
        from edl_tpu.data import shuffled

        out = list(shuffled(iter([1, 2, 3]), buffer_size=100, seed=0))
        assert sorted(out) == [1, 2, 3]


class TestEvaluate:
    def test_eval_covers_every_record_once(self):
        """37 records at batch 8: 4 full batches + 1 ragged(5); the
        weighted mean must equal the exact per-record mean."""
        trainer = ElasticTrainer(
            MLP(hidden=(16,), features=1),
            optax.sgd(0.05),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            batch_size=8,
            log=False,
        )
        state = trainer.fit(lambda e: _records(e, n=64), epochs=1)

        recs = list(_records(0, n=37))
        got = trainer.evaluate(state, lambda: iter(recs))
        # exact reference: mean over all 37 records in one device call
        x = jnp.asarray(np.stack([r[0] for r in recs]))
        y = jnp.asarray(np.stack([r[1] for r in recs]))
        preds = state.apply_fn({"params": state.params}, x)
        want = float(jnp.mean((preds - y) ** 2))
        assert got["loss"] == pytest.approx(want, rel=1e-4), (got, want)

    def test_eval_ready_batches(self):
        trainer = ElasticTrainer(
            MLP(hidden=(8,), features=1),
            optax.sgd(0.05),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            log=False,
        )
        state = trainer.fit(
            lambda e: iter(
                [(np.ones((8, 8), np.float32), np.ones((8, 1), np.float32))]
            ),
            epochs=1,
        )
        out = trainer.evaluate(
            state,
            lambda: iter(
                [(np.ones((8, 8), np.float32), np.ones((8, 1), np.float32))] * 3
            ),
        )
        assert "loss" in out and np.isfinite(out["loss"])
