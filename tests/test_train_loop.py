"""ElasticTrainer: the one-call elastic loop (reference intent:
test_train.py:28-67 PaddleState/register_adjust_function sketch)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.checkpoint import AdjustRegistry, linear_scaled_lr
from edl_tpu.models import MLP
from edl_tpu.train import ElasticTrainer, mse_loss


def _records(epoch, n=256, d=8, seed_base=100):
    rs = np.random.RandomState(seed_base + epoch)
    w = np.linspace(-1, 1, d)[:, None].astype(np.float32)
    for _ in range(n):
        x = rs.randn(d).astype(np.float32)
        yield x, (x @ w).astype(np.float32)


def test_fit_record_stream_loss_decreases():
    seen = []
    trainer = ElasticTrainer(
        MLP(hidden=(16,), features=1),
        optax.sgd(0.05),
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        batch_size=8,
        log=False,
    )
    state = trainer.fit(
        _records, epochs=3,
        on_epoch_end=lambda e, m: seen.append(float(m["loss"])),
    )
    assert len(seen) == 3
    assert seen[-1] < seen[0] * 0.5, seen
    assert int(state.step) == 3 * (256 // 8)


def test_fit_resumes_from_checkpoint(tmp_path):
    def make(log=False):
        return ElasticTrainer(
            MLP(hidden=(16,), features=1),
            optax.sgd(0.05),
            mse_loss,
            sample_input=jnp.zeros((8, 8)),
            batch_size=8,
            ckpt_dir=str(tmp_path / "ckpt"),
            log=log,
        )

    s1 = make().fit(_records, epochs=2)
    assert int(s1.step) == 2 * 32
    # second run resumes at epoch 2 and only trains epochs 2..3
    epochs_run = []
    s2 = make().fit(
        _records, epochs=4,
        on_epoch_end=lambda e, m: epochs_run.append(e),
    )
    assert epochs_run == [2, 3]
    assert int(s2.step) == 4 * 32


def test_adjust_registry_feeds_optimizer_factory(monkeypatch):
    monkeypatch.setenv("EDL_NUM_WORKERS", "4")
    adjusts = AdjustRegistry()
    adjusts.register(linear_scaled_lr(0.1, base_world_size=1))
    got = {}

    def factory(overrides):
        got.update(overrides)
        return optax.sgd(overrides.get("lr", 0.1))

    # world_size=4 from env, but no store/coordinator: barrier no-ops
    trainer = ElasticTrainer(
        MLP(hidden=(8,), features=1),
        factory,
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        batch_size=8,
        adjusts=adjusts,
        log=False,
    )
    trainer.fit(lambda e: _records(e, n=32), epochs=1)
    assert got == {"lr": pytest.approx(0.4)}


def test_fit_ready_batches_no_batch_size():
    def data(epoch):
        rs = np.random.RandomState(epoch)
        for _ in range(8):
            x = rs.randn(8, 8).astype(np.float32)
            yield x, x.sum(axis=1, keepdims=True).astype(np.float32)

    trainer = ElasticTrainer(
        MLP(hidden=(16,), features=1),
        optax.sgd(0.01),
        mse_loss,
        sample_input=jnp.zeros((8, 8)),
        log=False,
    )
    state = trainer.fit(data, epochs=2)
    assert int(state.step) == 16
