"""AOT resize ladder + compile-cache exchange (edl_tpu/train/aot.py).

Covers the rung enumeration and claim dedupe, the manifest/digest
machinery, the exchange end-to-end on real sockets + a real store, the
chaos drill (a corrupted or dropped cache-entry pull degrades to a
normal compile, never a wedged worker), and the acceptance e2e: a pod
joining with an EMPTY cache dir pulls entries a peer already compiled
and provably first-jits from them — zero real compiles, nonzero rx
bytes.
"""

import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import pytest

from edl_tpu.chaos.plane import configure as chaos_configure
from edl_tpu.store.client import StoreClient
from edl_tpu.train import aot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env_stub(**over):
    base = dict(
        world_size=2, nproc_per_node=1, min_nodes=1, max_nodes=3,
        global_rank=0, pod_id="podA", job_id="aotjob", store_endpoint="",
    )
    base.update(over)
    return SimpleNamespace(**base)


def _wait_until(pred, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


# -- rung enumeration ---------------------------------------------------------

class TestNeighborWorlds:
    def test_nearest_first_shrink_before_grow(self):
        # 4 pods in a 1..6 window: ±1 then ±2, shrink first at equal
        # distance — the shrink is what this process can compile itself
        assert aot.neighbor_worlds(4, 1, 1, 6) == [3, 5, 2, 6]

    def test_window_clamps(self):
        assert aot.neighbor_worlds(1, 1, 1, 3) == [2, 3]
        assert aot.neighbor_worlds(3, 1, 1, 3) == [2, 1]
        # a window pinned to the current world: nothing to speculate
        assert aot.neighbor_worlds(4, 1, 4, 4) == []

    def test_nproc_scales_worlds(self):
        # 2 procs/node, 2 pods, window 1..4 -> pod targets 1,3,4 as worlds
        assert aot.neighbor_worlds(4, 2, 1, 4) == [2, 6, 8]

    def test_non_divisible_world_is_a_noop(self):
        assert aot.neighbor_worlds(5, 2, 1, 4) == []


# -- manifest / digest machinery ----------------------------------------------

class TestManifest:
    def test_scan_digests_entries_and_skips_sidecars(self, tmp_path):
        (tmp_path / "key1-cache").write_bytes(b"exec one")
        (tmp_path / "key2-cache").write_bytes(b"exec two")
        (tmp_path / "key1-cache-atime").write_bytes(b"12345678")
        (tmp_path / ".hidden").write_bytes(b"x")
        (tmp_path / ("key3" + aot._TMP_MARK + ".99")).write_bytes(b"torn")
        m = aot.scan_manifest(str(tmp_path))
        assert sorted(m) == ["key1-cache", "key2-cache"]
        assert m["key1-cache"]["sha"] == hashlib.sha256(b"exec one").hexdigest()
        assert m["key2-cache"]["size"] == len(b"exec two")

    def test_scan_missing_dir_is_empty(self, tmp_path):
        assert aot.scan_manifest(str(tmp_path / "nope")) == {}


# -- portable keys + cache-event seam -----------------------------------------

class TestJitSeamPatches:
    def test_portable_keys_enable_and_idempotent(self, monkeypatch):
        monkeypatch.delenv("EDL_CACHE_PORTABLE_KEYS", raising=False)
        assert aot.enable_portable_cache_keys() is True
        from jax._src import cache_key as ck

        assert getattr(ck._hash_accelerator_config, "_edl_portable", False)
        assert aot.enable_portable_cache_keys() is True  # no double-wrap

    def test_portable_keys_opt_out(self, monkeypatch):
        monkeypatch.setenv("EDL_CACHE_PORTABLE_KEYS", "0")
        assert aot.enable_portable_cache_keys() is False

    def test_instrumentation_idempotent_and_counts_shape(self, monkeypatch):
        monkeypatch.delenv("EDL_CACHE_EVENTS", raising=False)
        assert aot.instrument_compilation_cache() is True
        assert aot.instrument_compilation_cache() is True
        counts = aot.cache_event_counts()
        assert sorted(counts) == ["hit", "miss", "write"]
        assert all(v >= 0 for v in counts.values())


# -- the ladder ---------------------------------------------------------------

class TestAotLadder:
    @pytest.fixture(autouse=True)
    def _one_device_per_proc(self, monkeypatch):
        # these rigs model 1-device processes (the CPU resize rig pins
        # the same); without the pin devices_per_process() derives
        # 8-virtual-devices / world_size from the host mesh
        monkeypatch.setenv("EDL_DEVICES_PER_PROC", "1")

    def test_multi_device_processes_scale_rungs(self, monkeypatch):
        # TPU shape: world counts PROCESSES but meshes are devices — a
        # 2-process stage over the 8-device host mesh owns 4 devices
        # per process, so world 1 compiles a 4-device mesh and world 3
        # (12 devices) is a grow this process cannot see
        monkeypatch.delenv("EDL_DEVICES_PER_PROC", raising=False)
        compiled = []
        before = aot._M_AOT.value(outcome="skipped_grow")
        ladder = aot.AotLadder(
            _env_stub(), compiled.append, delay=0.0
        ).start()
        assert _wait_until(
            lambda: aot._M_AOT.value(outcome="skipped_grow") == before + 1
        )
        ladder.close()
        assert compiled == [1]
        assert aot.devices_per_process(_env_stub()) == 4

    def test_compiles_neighbor_worlds_in_order(self):
        compiled = []
        ladder = aot.AotLadder(
            _env_stub(), compiled.append, delay=0.0
        ).start()
        assert _wait_until(lambda: len(ladder.compiled) == 2)
        ladder.close()
        # world 2 in a 1..3 pod window -> worlds [1, 3]; the 8-device
        # virtual CPU mesh makes both compilable in-process
        assert compiled == [1, 3]
        assert ladder.compiled == [1, 3]

    def test_nonzero_rank_without_store_defers(self):
        compiled = []
        ladder = aot.AotLadder(
            _env_stub(global_rank=1), compiled.append, delay=0.0
        ).start()
        time.sleep(0.3)
        ladder.close()
        assert compiled == []

    def test_failed_compile_is_counted_never_raised(self):
        def boom(world):
            raise RuntimeError("xla says no")

        before = aot._M_AOT.value(outcome="failed")
        ladder = aot.AotLadder(_env_stub(), boom, delay=0.0).start()
        assert _wait_until(
            lambda: aot._M_AOT.value(outcome="failed") >= before + 2
        )
        ladder.close()
        assert ladder.compiled == []

    def test_indivisible_rung_is_skipped_not_failed(self):
        # a sharded dim that doesn't divide over the neighbor mesh is a
        # permanent model/window property: its own outcome, never noise
        # in the failed counter
        def indivisible(world):
            raise aot.RungUnavailable("dim 0 (5) not divisible over dp=2")

        before_f = aot._M_AOT.value(outcome="failed")
        before_s = aot._M_AOT.value(outcome="skipped_indivisible")
        ladder = aot.AotLadder(_env_stub(), indivisible, delay=0.0).start()
        assert _wait_until(
            lambda: aot._M_AOT.value(outcome="skipped_indivisible")
            >= before_s + 2
        )
        ladder.close()
        assert aot._M_AOT.value(outcome="failed") == before_f
        assert ladder.compiled == []

    def test_store_claim_dedupes_across_pods(self, store):
        client = StoreClient(store.endpoint)
        a_worlds, b_worlds = [], []
        env_a = _env_stub(store_endpoint=store.endpoint)
        env_b = _env_stub(
            store_endpoint=store.endpoint, pod_id="podB", global_rank=0
        )
        try:
            ladder_a = aot.AotLadder(
                env_a, a_worlds.append, client=client, delay=0.0
            ).start()
            assert _wait_until(lambda: len(ladder_a.compiled) == 2)
            ladder_a.close()
            before = aot._M_AOT.value(outcome="skipped_claimed")
            ladder_b = aot.AotLadder(
                env_b, b_worlds.append, client=client, delay=0.0
            ).start()
            assert _wait_until(
                lambda: aot._M_AOT.value(outcome="skipped_claimed")
                >= before + 2
            )
            ladder_b.close()
        finally:
            client.close()
        assert a_worlds == [1, 3]
        assert b_worlds == []  # every rung already done: by podA

    def test_peer_failure_releases_rung_to_deferred_retry(
        self, store, monkeypatch
    ):
        # a rung claimed by a peer whose compile then FAILS (lease
        # deleted, no done marker) must not be stranded: the deferred
        # re-pass picks it up
        monkeypatch.setattr(aot.AotLadder, "_RETRY_DELAY", 0.3)
        from edl_tpu.discovery.registry import Registry

        client = StoreClient(store.endpoint)
        try:
            regs = [
                Registry(client, "aotjob").register_if_absent(
                    "aot", str(w), b"podA.0", ttl=60.0
                )[0]
                for w in (1, 3)
            ]
            before = aot._M_AOT.value(outcome="skipped_claimed")
            compiled = []
            ladder = aot.AotLadder(
                _env_stub(pod_id="podB", store_endpoint=store.endpoint),
                compiled.append, client=client, delay=0.0,
            ).start()
            assert _wait_until(
                lambda: aot._M_AOT.value(outcome="skipped_claimed")
                >= before + 2
            )
            for reg in regs:  # the peer's compiles fail: claims released
                reg.stop(delete=True)
            assert _wait_until(lambda: len(ladder.compiled) == 2)
            ladder.close()
            assert compiled == [1, 3]
        finally:
            client.close()

    def test_grow_beyond_visible_devices_is_skipped(self):
        # 8 virtual devices: a 12-device rung cannot compile here
        compiled = []
        before = aot._M_AOT.value(outcome="skipped_grow")
        ladder = aot.AotLadder(
            _env_stub(), compiled.append, worlds=[12], delay=0.0
        ).start()
        assert _wait_until(
            lambda: aot._M_AOT.value(outcome="skipped_grow") == before + 1
        )
        ladder.close()
        assert compiled == []

    def test_ladder_seconds_land_in_aot_compile_state(self):
        from edl_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.default_registry()
        counter = reg.counter("edl_goodput_seconds_total")
        before = counter.value(state="aot_compile", cause="w1")
        ladder = aot.AotLadder(
            _env_stub(), lambda w: time.sleep(0.05), delay=0.0
        ).start()
        assert _wait_until(lambda: len(ladder.compiled) == 2)
        ladder.close()
        assert counter.value(state="aot_compile", cause="w1") > before

    def test_chaos_drop_on_compile_point_is_a_counted_failure(self):
        chaos_configure(
            {"rules": [{"point": "train.aot.compile", "action": "drop",
                        "times": 0}]},
            who="pytest",
        )
        try:
            compiled = []
            before = aot._M_AOT.value(outcome="failed")
            ladder = aot.AotLadder(
                _env_stub(), compiled.append, delay=0.0
            ).start()
            assert _wait_until(
                lambda: aot._M_AOT.value(outcome="failed") >= before + 2
            )
            ladder.close()
            assert compiled == []  # every rung dropped, nobody crashed
        finally:
            chaos_configure({"rules": []}, who="pytest")


# -- the exchange -------------------------------------------------------------

@pytest.fixture()
def exchange_rig(store, tmp_path):
    """A served pod-A cache dir + an empty pod-B dir on a real store."""
    client = StoreClient(store.endpoint)
    dir_a = tmp_path / "cache_a"
    dir_b = tmp_path / "cache_b"
    dir_a.mkdir()
    dir_b.mkdir()
    entries = {
        "k1-cache": b"executable one" * 100,
        "k2-cache": b"executable two" * 100,
        "k3-cache": b"\x00\x01binary\xff" * 64,
    }
    for name, data in entries.items():
        (dir_a / name).write_bytes(data)
    (dir_a / "k1-cache-atime").write_bytes(b"01234567")  # never shipped
    xchg = aot.CacheExchange(
        str(dir_a), client, "xjob", "podA", host="127.0.0.1"
    ).start()
    # publication rides the exchange's scan thread; land it before the
    # tests look (peers in production simply pull on their next look)
    assert _wait_until(lambda: "podA" in aot.read_manifests(client, "xjob"))
    yield SimpleNamespace(
        store=store, client=client, dir_a=dir_a, dir_b=dir_b,
        entries=entries, xchg=xchg,
    )
    xchg.stop()
    client.close()


class TestCacheExchange:
    def test_manifest_published_and_readable(self, exchange_rig):
        r = exchange_rig
        manifests = aot.read_manifests(r.client, "xjob")
        assert set(manifests) == {"podA"}
        m = manifests["podA"]
        assert sorted(m["entries"]) == sorted(r.entries)
        assert m["endpoint"].endswith(":%d" % r.xchg.port)
        assert "k1-cache-atime" not in m["entries"]

    def test_empty_pod_pulls_everything_byte_identical(self, exchange_rig):
        r = exchange_rig
        rx_before = aot._M_XCHG_BYTES.value(dir="rx")
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB"
        )
        assert stats["pulled"] == len(r.entries)
        assert stats["skipped_bad"] == 0
        assert stats["peers"] == 1
        for name, data in r.entries.items():
            assert (r.dir_b / name).read_bytes() == data
        assert aot._M_XCHG_BYTES.value(dir="rx") == rx_before + stats["bytes"]
        assert stats["bytes"] == sum(len(d) for d in r.entries.values())
        # second pull: nothing missing anymore
        again = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB"
        )
        assert again["pulled"] == 0

    def test_own_manifest_is_never_pulled(self, exchange_rig):
        r = exchange_rig
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podA"
        )
        assert stats == {"pulled": 0, "bytes": 0, "skipped_bad": 0, "peers": 0}

    def test_unchanged_refresh_does_not_republish(self, exchange_rig):
        # the manifest put is journal traffic on the control plane (and
        # rides HA replication streams): an unchanged cache dir must not
        # republish — the embedded ts may not defeat the change check
        r = exchange_rig
        key = "/xjob/%s/podA" % aot.MANIFEST_SERVICE
        _, rev_before = r.client.get_with_rev(key)
        r.xchg.refresh(force=True)
        r.xchg.refresh(force=True)
        _, rev_after = r.client.get_with_rev(key)
        assert rev_after == rev_before

    def test_refresh_republishes_new_entries(self, exchange_rig):
        r = exchange_rig
        (r.dir_a / "k4-cache").write_bytes(b"late entry" * 50)
        r.xchg.refresh(force=True)
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB"
        )
        assert stats["pulled"] == len(r.entries) + 1
        assert (r.dir_b / "k4-cache").read_bytes() == b"late entry" * 50

    def test_server_refuses_path_shaped_names(self, exchange_rig, tmp_path):
        from edl_tpu.rpc.wire import request_once

        secret = tmp_path / "secret.txt"
        secret.write_bytes(b"not a cache entry")
        resp = request_once(
            exchange_rig.xchg.endpoint,
            {"i": 1, "m": "cache_pull",
             "names": ["../secret.txt", ".hidden", "a/b", "k1-cache"]},
            timeout=5.0,
        )
        assert resp["ok"]
        assert set(resp["entries"]) == {"k1-cache"}

    def test_tampered_entry_is_skipped_not_landed(self, exchange_rig):
        # peer's file changes AFTER the manifest was published (a torn
        # write at the peer in miniature): digest mismatch -> skipped
        r = exchange_rig
        (r.dir_a / "k1-cache").write_bytes(b"tampered!")
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB"
        )
        assert stats["skipped_bad"] == 1
        assert stats["pulled"] == len(r.entries) - 1
        assert not (r.dir_b / "k1-cache").exists()
        assert not any(
            aot._TMP_MARK in p.name for p in r.dir_b.iterdir()
        ), "a skipped entry must not leave temp litter"

    def test_pull_without_store_is_a_noop(self, tmp_path):
        stats = aot.pull_missing(str(tmp_path), endpoint="", job_id="j")
        assert stats["pulled"] == 0

    def test_pull_survives_dead_peer_endpoint(self, exchange_rig):
        # a manifest pointing at a gone peer: the pull skips it inside
        # its budget instead of raising
        r = exchange_rig
        r.client.put(
            "/xjob/compile_cache/podGone",
            json.dumps({
                "endpoint": "127.0.0.1:1",  # nothing listens there
                "entries": {"kX-cache": "0" * 64},
                "ts": 0,
            }).encode(),
        )
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB",
            deadline=5.0,
        )
        assert stats["pulled"] == len(r.entries)  # podA still served
        assert not (r.dir_b / "kX-cache").exists()

    def test_hostile_manifest_name_never_dialed_or_landed(self, exchange_rig):
        # the WRITE direction of the path-refusal rule: a manifest naming
        # "../escape" must not choose where pulled bytes land — the name
        # is dropped before the peer is even dialed
        r = exchange_rig
        evil = {"../escape": "0" * 64, ".dotted": "1" * 64, "a/b": "2" * 64}
        r.client.put(
            "/xjob/compile_cache/podEvil",
            json.dumps({
                "endpoint": r.xchg.endpoint,  # a live server, deliberately
                "entries": evil, "ts": 0,
            }).encode(),
        )
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB",
        )
        assert stats["pulled"] == len(r.entries)  # podA's real entries only
        assert stats["peers"] == 1  # podEvil had nothing pullable
        assert not (r.dir_b.parent / "escape").exists()
        assert sorted(p.name for p in r.dir_b.iterdir()) == sorted(r.entries)

    def test_byte_capped_response_splits_and_completes(
        self, exchange_rig, monkeypatch
    ):
        # entries are ~1400/1400/768 bytes; a 2000-byte cap forces the
        # server to truncate every chunk and the puller to re-request the
        # pushed-out names — everything still lands, byte-identical
        monkeypatch.setenv("EDL_CACHE_PULL_MAX_BYTES", "2000")
        r = exchange_rig
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB",
        )
        assert stats["pulled"] == len(r.entries)
        for name, data in r.entries.items():
            assert (r.dir_b / name).read_bytes() == data

    def test_oversize_single_entry_still_ships(self, exchange_rig, monkeypatch):
        # one entry alone over the cap: the server must still serve it
        # (a cap that starves is worse than a fat frame) rather than
        # truncate forever
        monkeypatch.setenv("EDL_CACHE_PULL_MAX_BYTES", "10")
        r = exchange_rig
        stats = aot.pull_missing(
            str(r.dir_b), client=r.client, job_id="xjob", own_pod="podB",
        )
        assert stats["pulled"] == len(r.entries)

    def test_scan_thread_republishes_without_caller(self, store, tmp_path):
        # the recurring digest scan is the exchange's own thread — new
        # entries must surface in the manifest with nobody calling
        # refresh() (the launcher loop doesn't anymore)
        client = StoreClient(store.endpoint)
        try:
            d = tmp_path / "cache_t"
            d.mkdir()
            xchg = aot.CacheExchange(
                str(d), client, "xjob3", "podT", host="127.0.0.1"
            )
            xchg._REFRESH_EVERY = 0.2
            xchg.start()
            try:
                (d / "kN-cache").write_bytes(b"fresh entry")
                assert _wait_until(
                    lambda: "kN-cache" in (
                        aot.read_manifests(client, "xjob3")
                        .get("podT", {}).get("entries") or {}
                    ),
                    timeout=5.0,
                )
            finally:
                xchg.stop()
        finally:
            client.close()

    def test_stop_retracts_manifest(self, store, tmp_path):
        client = StoreClient(store.endpoint)
        try:
            d = tmp_path / "cache_c"
            d.mkdir()
            (d / "kZ-cache").write_bytes(b"entry")
            xchg = aot.CacheExchange(
                str(d), client, "xjob2", "podC", host="127.0.0.1"
            ).start()
            assert _wait_until(
                lambda: "podC" in aot.read_manifests(client, "xjob2")
            )
            xchg.stop()
            # a departed pod must not leave a manifest for later pulls to
            # burn budget on (SIGKILL still can; the per-peer dial cap is
            # the backstop there)
            assert "podC" not in aot.read_manifests(client, "xjob2")
        finally:
            client.close()


class TestChaosDrill:
    """Satellite drill: a corrupted/dropped cache-entry pull degrades to
    a normal compile — entries are skipped, nothing lands poisoned,
    nothing wedges or crashes."""

    def test_corrupt_pull_skips_every_entry(self, exchange_rig):
        chaos_configure(
            {"rules": [{"point": "store.cache.exchange",
                        "action": "corrupt", "times": 0}]},
            who="pytest",
        )
        try:
            stats = aot.pull_missing(
                str(exchange_rig.dir_b), client=exchange_rig.client,
                job_id="xjob", own_pod="podB",
            )
        finally:
            chaos_configure({"rules": []}, who="pytest")
        assert stats["pulled"] == 0
        assert stats["skipped_bad"] == len(exchange_rig.entries)
        assert list(exchange_rig.dir_b.iterdir()) == []

    def test_dropped_pull_is_contained_and_bounded(self, exchange_rig):
        chaos_configure(
            {"rules": [{"point": "store.cache.exchange",
                        "action": "drop", "times": 0}]},
            who="pytest",
        )
        t0 = time.monotonic()
        try:
            stats = aot.pull_missing(
                str(exchange_rig.dir_b), client=exchange_rig.client,
                job_id="xjob", own_pod="podB", deadline=10.0,
            )
        finally:
            chaos_configure({"rules": []}, who="pytest")
        assert time.monotonic() - t0 < 10.0
        assert stats["pulled"] == 0
        assert stats["skipped_bad"] == len(exchange_rig.entries)
        assert list(exchange_rig.dir_b.iterdir()) == []


# -- acceptance e2e: join with an empty cache, first-jit from pulled entries --

# the worker both pods run: edl init (arms the cache + portable keys +
# event counters and, pod B, pulls from peers), one jitted step, then a
# JSON proof of what the persistent cache did
WORKER = """
import json, os, sys
sys.path.insert(0, %(repo)r)
from edl_tpu.chaos import plane as chaos_plane
chaos_plane.arm_from_env("worker")
from edl_tpu.train import init
from edl_tpu.train import aot
init()
import jax, jax.numpy as jnp
f = jax.jit(lambda x: jnp.tanh(x @ x.T).sum())
print(float(f(jnp.ones((96, 96)))), file=sys.stderr)
print(json.dumps({
    "counts": aot.cache_event_counts(),
    "rx": aot._M_XCHG_BYTES.value(dir="rx"),
}))
"""


def _run_worker(cache_dir, pod, store, extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "EDL_JOB_ID": "e2ejob",
        "EDL_POD_ID": pod,
        "EDL_STORE_ENDPOINT": store.endpoint,
        "EDL_COMPILE_CACHE_DIR": str(cache_dir),
        "EDL_AOT": "0",  # the pull is what's under test, not the ladder
    })
    env.update(extra or {})
    out = subprocess.run(
        [sys.executable, "-c", WORKER % {"repo": REPO}],
        env=env, capture_output=True, text=True, timeout=180,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


class TestJoinFromPeerCache:
    def test_empty_pod_first_jits_from_pulled_entries(self, store, tmp_path):
        dir_a = tmp_path / "a"
        dir_b = tmp_path / "b"
        dir_a.mkdir()
        dir_b.mkdir()
        # pod A pays the real compile
        a = _run_worker(dir_a, "podA", store)
        assert a["counts"]["miss"] >= 1 and a["counts"]["write"] >= 1
        assert any(
            not n.endswith("-atime") for n in os.listdir(dir_a)
        ), "pod A must leave cache entries"
        # ... and serves its cache (the launcher's role, in miniature)
        client = StoreClient(store.endpoint)
        xchg = aot.CacheExchange(
            str(dir_a), client, "e2ejob", "podA", host="127.0.0.1"
        ).start()
        try:
            # pod B joins with an EMPTY dir: init() pulls, the first jit
            # is a cache LOAD — zero real compiles, nonzero rx bytes
            b = _run_worker(dir_b, "podB", store)
        finally:
            xchg.stop()
            client.close()
        assert b["rx"] > 0, b
        assert b["counts"]["hit"] >= 1, b
        assert b["counts"]["miss"] == 0, (
            "pod B paid a real compile despite a peer's warm cache: %r" % b
        )

    def test_corrupted_pull_degrades_to_a_normal_compile(
        self, store, tmp_path
    ):
        """The chaos drill end-to-end: every pulled entry corrupted in
        flight — pod B must simply compile (miss+write), finish its
        step, and exit clean."""
        dir_a = tmp_path / "a"
        dir_b = tmp_path / "b"
        dir_a.mkdir()
        dir_b.mkdir()
        _run_worker(dir_a, "podA", store)
        client = StoreClient(store.endpoint)
        xchg = aot.CacheExchange(
            str(dir_a), client, "e2ejob", "podA", host="127.0.0.1"
        ).start()
        try:
            b = _run_worker(
                dir_b, "podB", store,
                extra={"EDL_CHAOS": json.dumps({
                    "rules": [{"point": "store.cache.exchange",
                               "action": "corrupt", "times": 0}],
                })},
            )
        finally:
            xchg.stop()
            client.close()
        assert b["rx"] == 0, b
        assert b["counts"]["miss"] >= 1 and b["counts"]["write"] >= 1, b
