"""Installed-package smoke test (VERDICT r4 #7).

Everything else in the suite runs from the checkout via PYTHONPATH; this
file is the one place the package is actually BUILT and INSTALLED — a
fresh venv, ``pip install .``, then the console scripts and the
Dockerfile's CMD module driven end-to-end from the installed copy with
the checkout deliberately off sys.path. Catches what structure-only
checks cannot: a module missing from packages.find, package-data (the
attention dispatch calibration) dropped from the wheel, a console script
pointing at a function that doesn't exist, or a dependency pin no
environment can satisfy (``pip check`` validates Requires-Dist against
the installed world).

Zero-egress constraints shape the mechanics: the venv shares the host's
site-packages (numpy/psutil/jax come from there — pip cannot download),
and the install runs ``--no-deps --no-build-isolation``; ``pip check``
then still verifies the declared pins against what is present.
"""

import json
import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def venv(tmp_path_factory):
    """A venv with edl-tpu pip-installed; yields its bin dir."""
    import sysconfig

    root = tmp_path_factory.mktemp("venv")
    subprocess.run(
        [sys.executable, "-m", "venv", str(root)], check=True,
    )
    # the dev environment is ITSELF a venv, so --system-site-packages
    # would expose the wrong prefix; a .pth makes the host environment's
    # packages (numpy/psutil/jax AND setuptools for the build) visible
    host_purelib = sysconfig.get_paths()["purelib"]
    venv_purelib = (
        root / "lib" / ("python%d.%d" % sys.version_info[:2])
        / "site-packages"
    )
    (venv_purelib / "_host_env.pth").write_text(host_purelib + "\n")
    bin_dir = root / "bin"
    pip = str(bin_dir / "pip")
    out = subprocess.run(
        [pip, "install", "--no-deps", "--no-build-isolation",
         "--no-index", REPO],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, "pip install . failed:\n" + out.stderr[-2000:]
    return bin_dir


def _run(cmd, timeout=60, env_extra=None, cwd=None):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # the checkout must NOT rescue imports
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        [str(c) for c in cmd], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=cwd or "/tmp",
    )


def test_pip_check_validates_pins(venv):
    # The venv sees the host's site-packages through the .pth, so pip
    # check also re-reports the host's own conflicts (e.g. google-cloud
    # pins protobuf<6 while the host ships 6.x).  Those predate the
    # install and are not ours to fix: baseline them from the host
    # interpreter and fail only on NEW lines, which can only come from
    # edl-tpu's Requires-Dist.
    baseline = _run([sys.executable, "-m", "pip", "check"], timeout=120)
    preexisting = set(baseline.stdout.splitlines())
    out = _run([venv / "pip", "check"], timeout=120)
    new = [l for l in out.stdout.splitlines()
           if l.strip() and l not in preexisting]
    assert not new, "edl-tpu introduced dependency conflicts:\n" + "\n".join(new)


def test_console_scripts_exist_and_answer_help(venv):
    for script in (
        "edl-store", "edl-launch", "edl-register",
        "edl-discovery-server", "edl-resize", "edl-status",
    ):
        path = venv / script
        assert path.exists(), "console script %s not installed" % script
        out = _run([path, "--help"], timeout=60)
        assert out.returncode == 0, "%s --help failed:\n%s" % (
            script, out.stderr[-800:],
        )


def test_package_data_rides_the_install(venv):
    """The measured attention-dispatch calibration must be importable
    from the INSTALLED package, not just the checkout."""
    code = (
        "import importlib, os;"
        "A = importlib.import_module('edl_tpu.ops.attention');"
        "assert os.path.dirname(A.__file__).startswith(%r), A.__file__;"
        "print(os.path.exists(A._PACKAGED_DISPATCH))"
        % str(venv.parent / "lib")
    )
    out = _run([venv / "python", "-c", code], timeout=120)
    assert out.returncode == 0, out.stderr[-800:]
    assert out.stdout.strip() == "True", (
        "attention_dispatch.json missing from the installed package "
        "(package-data broke): %r" % out.stdout
    )


def test_dockerfile_cmd_module_serves(venv, tmp_path):
    """The image's CMD (python -m edl_tpu.store.server) must run from the
    installed package and actually serve."""
    with open(os.path.join(REPO, "docker", "Dockerfile")) as f:
        cmd_lines = [l for l in f if l.strip().startswith("CMD")]
    assert cmd_lines, "Dockerfile has no CMD"
    argv = json.loads(cmd_lines[-1].strip()[len("CMD"):].strip())
    assert argv[:2] == ["python", "-m"], argv
    # port 0 instead of the image's fixed port: the host may be busy
    module_argv = [venv / "python", "-m", argv[2], "--port", "0"]
    proc = subprocess.Popen(
        [str(c) for c in module_argv],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
        cwd="/tmp",
    )
    try:
        deadline = time.time() + 30
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
        assert "serving" in line, "store never announced serving: %r" % line
    finally:
        proc.kill()
        proc.wait()


def test_launch_toy_job_from_installed_package(venv, tmp_path):
    """Full control-plane drill from the installed copy: edl-launch with
    an embedded store runs a worker to completion, edl-status reads the
    job back. The worker script lives OUTSIDE the repo and imports
    nothing from it."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "with open(os.environ['OUT'], 'w') as f:\n"
        "    f.write(os.environ['EDL_STAGE'])\n"
    )
    marker = tmp_path / "ran"
    out = _run(
        [venv / "edl-launch", "--job_id", "inst1",
         "--store", "127.0.0.1:29641", "--embed_store",
         "--nodes_range", "1:1", "--ttl", "1.0", str(script)],
        timeout=120, env_extra={"OUT": str(marker)}, cwd=str(tmp_path),
    )
    assert out.returncode == 0, (
        "edl-launch failed rc=%d:\n%s" % (out.returncode, out.stderr[-1500:])
    )
    assert marker.exists() and marker.read_text(), "worker never ran"
