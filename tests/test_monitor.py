"""Monitor plane: rule-engine decision table, ring-file retention,
store-published alerts, the chaos alert invariants, and the
rule-catalogue lint.

Tier-1 (no jax): everything here is pure control-plane code. The
end-to-end conformance (the monitor inside a live chaos rig) rides the
scenario drills in tests/test_chaos.py; here the engine is driven with
injected samples at injected timestamps, so every decision-table row is
deterministic.
"""

import json
import os
import pathlib
import re
import subprocess
import sys
import time

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

from edl_tpu.chaos import invariants as inv
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import monitor as obs_monitor
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.obs.monitor import Monitor, Rule, builtin_rules, rules_from_json

REPO = pathlib.Path(__file__).resolve().parent.parent

T0 = 1_000_000.0


def engine(*rules, **kwargs):
    """A headless monitor: no store, fresh registry, test-driven time."""
    kwargs.setdefault("registry", MetricsRegistry())
    kwargs.setdefault("interval", 0.25)
    return Monitor(None, "testjob", rules=list(rules), **kwargs)


def counter_series(name, value, labels='{cause="step",state="train"}'):
    return {name: {labels: value}}


# -- rule model ---------------------------------------------------------------


class TestRuleModel:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown kind"):
            Rule("x", kind="sorcery")

    def test_unknown_op_rejected(self):
        with pytest.raises(ValueError, match="unknown op"):
            Rule("x", op="~")

    def test_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown keys"):
            Rule.from_dict({"name": "x", "kind": "threshold", "knob": 1})

    def test_roundtrip(self):
        rule = Rule("gp", kind="rate", metric="edl_goodput_seconds_total",
                    labels='state="train"', op="<", value=0.05)
        assert Rule.from_dict(rule.to_dict()) == rule

    def test_rules_from_json_overrides_and_appends(self):
        base = builtin_rules()
        merged = rules_from_json(
            json.dumps([
                {"name": "goodput-degraded", "for_s": 1.0, "window_s": 2.0},
                {"name": "my-slo", "kind": "threshold",
                 "metric": "edl_store_requests_total", "op": ">", "value": 9},
            ]),
            base=base,
        )
        by_name = {r.name: r for r in merged}
        assert by_name["goodput-degraded"].for_s == 1.0
        assert by_name["goodput-degraded"].severity == "critical"  # kept
        assert by_name["my-slo"].value == 9
        assert len(merged) == len(base) + 1

    def test_duplicate_rule_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            engine(Rule("a"), Rule("a"))


# -- decision table -----------------------------------------------------------


class TestThresholdRules:
    def test_fires_after_for_duration_and_resolves(self):
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<",
                          value=0.7, for_s=1.0))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.9}}, ts=T0)
        assert mon.evaluate(now=T0) == []
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.5}}, ts=T0 + 1)
        assert mon.evaluate(now=T0 + 1) == []          # pending, not firing
        assert mon.evaluate(now=T0 + 1.5) == []        # for_s not yet served
        out = mon.evaluate(now=T0 + 2.1)
        assert [t["state"] for t in out] == ["firing"]
        assert out[0]["evidence"][0]["target"] == "w0"
        assert mon.firing() == ["gp"]
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.95}}, ts=T0 + 3)
        out = mon.evaluate(now=T0 + 3)
        assert [t["state"] for t in out] == ["resolved"]
        assert mon.firing() == []

    def test_flapping_condition_never_serves_for_duration(self):
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<",
                          value=0.7, for_s=1.0))
        for i in range(6):  # bad, good, bad, good ... each 0.4s apart
            v = 0.5 if i % 2 == 0 else 0.9
            ts = T0 + 0.4 * i
            mon.ingest("w0", {"edl_goodput_ratio": {"": v}}, ts=ts)
            assert mon.evaluate(now=ts) == []
        assert mon.firing() == []

    def test_no_matching_series_is_silent(self):
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7))
        mon.ingest("w0", {"edl_other_metric_total": {"": 1.0}}, ts=T0)
        assert mon.evaluate(now=T0) == []

    def test_label_filter_selects_series(self):
        mon = engine(Rule("lag", metric="edl_goodput_seconds_total",
                          labels='state="stalled"', op=">", value=5.0))
        mon.ingest(
            "w0",
            {"edl_goodput_seconds_total": {
                '{cause="",state="train"}': 100.0,
                '{cause="",state="stalled"}': 2.0,
            }},
            ts=T0,
        )
        assert mon.evaluate(now=T0) == []      # stalled=2 <= 5; train ignored
        mon.ingest(
            "w0",
            {"edl_goodput_seconds_total": {'{cause="",state="stalled"}': 9.0}},
            ts=T0 + 1,
        )
        out = mon.evaluate(now=T0 + 1)
        assert [t["rule"] for t in out] == ["lag"]


class TestRateRules:
    def _feed(self, mon, target, values, t0=T0, dt=0.25,
              name="edl_launch_straggler_ejections_total", labels=""):
        transitions = []
        ts = t0
        for v in values:
            mon.ingest(target, {name: {labels or "": v}}, ts=ts)
            transitions.extend(mon.evaluate(now=ts))
            ts += dt
        return transitions, ts - dt

    def test_nonzero_rate_fires(self):
        mon = engine(Rule("ej", kind="rate",
                          metric="edl_launch_straggler_ejections_total",
                          op=">", value=0.0, window_s=2.0))
        out, _ = self._feed(mon, "launcher", [0, 0, 0, 0, 0, 0, 0, 0, 0])
        assert out == []  # flat counter: no rate
        out, _ = self._feed(mon, "launcher", [1, 1, 1], t0=T0 + 2.5)
        assert [t["state"] for t in out] == ["firing"]

    def test_counter_reset_reads_as_fresh_increase(self):
        mon = engine(Rule("ej", kind="rate",
                          metric="edl_launch_straggler_ejections_total",
                          op=">", value=0.0, window_s=2.0))
        # 5 -> 5 -> 2: the process restarted and ejected twice since
        out, _ = self._feed(mon, "launcher", [5, 5, 5, 5, 5, 5, 5, 5, 2])
        assert [t["state"] for t in out] == ["firing"]

    def test_require_advance_arms_only_after_movement(self):
        rule = Rule("gd", kind="rate", metric="edl_goodput_seconds_total",
                    labels='state="train"', op="<", value=0.05,
                    window_s=2.0, for_s=0.5, require_advance=True)
        mon = engine(rule)
        # a job that NEVER trained: flat zero forever must not "degrade"
        ts = T0
        for _ in range(16):
            mon.ingest("w0", counter_series("edl_goodput_seconds_total", 0.0), ts=ts)
            assert mon.evaluate(now=ts) == []
            ts += 0.25
        # now it trains, then goes silent: armed -> fires
        v = 0.0
        for _ in range(10):
            v += 0.2
            mon.ingest("w0", counter_series("edl_goodput_seconds_total", v), ts=ts)
            assert mon.evaluate(now=ts) == []
            ts += 0.25
        fired = []
        for _ in range(16):  # the worker is gone; only the launcher remains
            mon.ingest("launcher", {"edl_launch_workers_running": {"": 1.0}}, ts=ts)
            fired.extend(mon.evaluate(now=ts))
            ts += 0.25
        assert [t["state"] for t in fired] == ["firing"]
        assert fired[0]["rule"] == "gd"
        # a too-LOW rate indicts the bearer that went silent, not the
        # (healthy, still-scraped) launcher
        assert [e["target"] for e in fired[0]["evidence"]] == ["w0"]

    def test_blind_window_never_fires(self):
        """No up samples at all (store outage, every endpoint dead): the
        rule must report nothing rather than alert on the absence of
        evidence."""
        rule = Rule("gd", kind="rate", metric="edl_goodput_seconds_total",
                    labels='state="train"', op="<", value=0.05,
                    window_s=2.0, require_advance=True)
        mon = engine(rule)
        v = 0.0
        ts = T0
        for _ in range(10):
            v += 0.2
            mon.ingest("w0", counter_series("edl_goodput_seconds_total", v), ts=ts)
            mon.evaluate(now=ts)
            ts += 0.25
        for _ in range(16):  # probes now FAIL: up=False samples only
            mon.ingest("w0", {}, up=False, ts=ts)
            assert mon.evaluate(now=ts) == []
            ts += 0.25


class TestServingRules:
    """Red/green drills for the serving resilience plane's rule pair:
    ``serve-shed-rate`` (teachers refusing work at a sustained rate)
    and ``breaker-open`` (a client breaker holding a teacher ejected)."""

    def _shed_rule(self):
        rule = [r for r in builtin_rules() if r.name == "serve-shed-rate"][0]
        rule.window_s, rule.for_s = 2.0, 0.5    # CPU-test pacing
        return rule

    def _breaker_rule(self):
        rule = [r for r in builtin_rules() if r.name == "breaker-open"][0]
        rule.for_s = 0.5
        return rule

    def test_shed_rate_red_on_sustained_shedding(self):
        mon = engine(self._shed_rule())
        ts, v = T0, 0.0
        # arm: the counter registers at 0 with the first served request
        for _ in range(8):
            mon.ingest("student", {"edl_distill_shed_total": {
                '{cause="queue",port="9000"}': v}}, ts=ts)
            assert mon.evaluate(now=ts) == []
            ts += 0.25
        fired = []
        for _ in range(16):  # ~8 sheds/s, far past the 1/s bound
            v += 2.0
            mon.ingest("student", {"edl_distill_shed_total": {
                '{cause="queue",port="9000"}': v}}, ts=ts)
            fired.extend(mon.evaluate(now=ts))
            ts += 0.25
        assert [t["state"] for t in fired] == ["firing"]
        assert fired[0]["rule"] == "serve-shed-rate"

    def test_shed_rate_green_on_occasional_shed(self):
        """A burst-absorbing fleet sheds the odd request: under the
        rate bound, the rule stays silent (shed != overloaded)."""
        mon = engine(self._shed_rule())
        ts, v = T0, 0.0
        for i in range(24):
            if i % 8 == 7:
                v += 1.0      # one shed every 2s: 0.5/s < the 1/s bound
            mon.ingest("student", {"edl_distill_shed_total": {
                '{cause="queue",port="9000"}': v}}, ts=ts)
            assert mon.evaluate(now=ts) == []
            ts += 0.25
        assert mon.firing() == []

    def test_breaker_open_red_and_resolves_on_close(self):
        mon = engine(self._breaker_rule())
        series = 'edl_distill_breaker_open'
        label = '{teacher="192.0.2.1:9000"}'
        mon.ingest("student", {series: {label: 0.0}}, ts=T0)
        assert mon.evaluate(now=T0) == []
        fired = []
        for i in range(4):  # breaker OPEN, held past for_s
            ts = T0 + 1 + 0.25 * i
            mon.ingest("student", {series: {label: 1.0}}, ts=ts)
            fired.extend(mon.evaluate(now=ts))
        assert [t["state"] for t in fired] == ["firing"]
        assert fired[0]["rule"] == "breaker-open"
        # probe succeeded, breaker closed: the alert must resolve
        mon.ingest("student", {series: {label: 0.0}}, ts=T0 + 3)
        out = mon.evaluate(now=T0 + 3)
        assert [t["state"] for t in out] == ["resolved"]
        assert mon.firing() == []

    def test_breaker_open_green_on_half_open_flap(self):
        """A breaker that opens and re-closes inside ``for_s`` (a
        successful half-open probe) never serves the hold — flaps are
        the breaker working, not an operator page."""
        mon = engine(self._breaker_rule())
        series = 'edl_distill_breaker_open'
        label = '{teacher="192.0.2.1:9000"}'
        for i in range(8):
            v = 1.0 if i % 2 == 0 else 0.0
            ts = T0 + 0.25 * i
            mon.ingest("student", {series: {label: v}}, ts=ts)
            assert mon.evaluate(now=ts) == []
        assert mon.firing() == []


class TestQuantileStaleness:
    BUCKET = "edl_train_step_heartbeat_age_seconds_bucket"

    def _series(self, fast, slow):
        """Cumulative heartbeat-age histogram: ``fast`` observations
        under 1s, ``slow`` observations past 10s (a silent worker)."""
        return {
            self.BUCKET: {
                '{le="1"}': float(fast),
                '{le="10"}': float(fast),
                '{le="+Inf"}': float(fast + slow),
            }
        }

    def test_windowed_delta_quantile_fires_on_silent_heartbeats(self):
        rule = Rule("hb", kind="quantile",
                    metric="edl_train_step_heartbeat_age_seconds",
                    q=0.95, op=">", value=5.0, window_s=4.0)
        mon = engine(rule)
        # watchdog passes observing small ages: p95 of the window delta
        # stays inside le=1
        mon.ingest("launcher", self._series(10, 0), ts=T0)
        mon.ingest("launcher", self._series(30, 0), ts=T0 + 2)
        assert mon.evaluate(now=T0 + 2) == []
        # then every NEW observation lands in the open bucket (the
        # worker's heartbeat went silent; its sampled age keeps growing)
        mon.ingest("launcher", self._series(30, 20), ts=T0 + 4)
        out = mon.evaluate(now=T0 + 4)
        assert [t["rule"] for t in out] == ["hb"]
        # the old cumulative counts must not mask the fresh tail: the
        # windowed DELTA is what the quantile judges
        assert out[0]["value"] >= 5.0

    def test_no_new_observations_is_unknown(self):
        rule = Rule("hb", kind="quantile",
                    metric="edl_train_step_heartbeat_age_seconds",
                    q=0.95, op=">", value=5.0, window_s=4.0)
        mon = engine(rule)
        mon.ingest("launcher", self._series(10, 5), ts=T0)
        mon.ingest("launcher", self._series(10, 5), ts=T0 + 2)
        assert mon.evaluate(now=T0 + 2) == []


class TestAbsentAndRestart:
    def test_dead_endpoint_fires_after_stale_bound(self):
        mon = engine(Rule("dead", kind="absent", stale_s=3.0))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 1.0}}, ts=T0)
        assert mon.evaluate(now=T0 + 2) == []       # silent, inside bound
        out = mon.evaluate(now=T0 + 3.5)
        assert [t["rule"] for t in out] == ["dead"]
        assert out[0]["evidence"][0]["target"] == "w0"
        # the endpoint comes back: resolved
        mon.ingest("w0", {"edl_goodput_ratio": {"": 1.0}}, ts=T0 + 4)
        out = mon.evaluate(now=T0 + 4)
        assert [t["state"] for t in out] == ["resolved"]

    def test_never_up_target_is_not_dead(self):
        mon = engine(Rule("dead", kind="absent", stale_s=3.0))
        mon.ingest("w0", {}, up=False, ts=T0)
        assert mon.evaluate(now=T0 + 10) == []

    def test_departed_target_is_retired_after_forget_bound(self):
        """Obs registrations are permanent keys: a worker that left in a
        downsize must stop paging once silent past forget_s — the alert
        stood long enough, then resolves instead of firing forever."""
        mon = engine(Rule("dead", kind="absent", stale_s=3.0, forget_s=10.0))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 1.0}}, ts=T0)
        out = mon.evaluate(now=T0 + 4)
        assert [t["state"] for t in out] == ["firing"]
        assert mon.evaluate(now=T0 + 9) == []        # still firing, no flap
        assert mon.firing() == ["dead"]
        out = mon.evaluate(now=T0 + 11)              # past forget_s: retired
        assert [t["state"] for t in out] == ["resolved"]
        assert mon.firing() == []
        assert mon.evaluate(now=T0 + 20) == []       # and stays quiet

    def test_restart_detected_and_self_resolves(self):
        mon = engine(Rule("re", kind="restart",
                          metric="edl_process_start_time_seconds",
                          resolve_s=2.0))
        start = {"edl_process_start_time_seconds": {"": T0 - 100}}
        mon.ingest("w0", start, ts=T0)
        assert mon.evaluate(now=T0) == []
        mon.ingest("w0", start, ts=T0 + 1)
        assert mon.evaluate(now=T0 + 1) == []       # stable: wedged != restarted
        restarted = {"edl_process_start_time_seconds": {"": T0 + 1.5}}
        mon.ingest("w0", restarted, ts=T0 + 2)
        out = mon.evaluate(now=T0 + 2)
        assert [t["state"] for t in out] == ["firing"]
        # a restart is an event: the alert resolves itself after the hold
        mon.ingest("w0", restarted, ts=T0 + 5)
        out = mon.evaluate(now=T0 + 5)
        assert [t["state"] for t in out] == ["resolved"]


class TestCompletionSuppression:
    def test_complete_job_suppresses_and_resolves(self):
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.1}}, ts=T0)
        out = mon.evaluate(now=T0)
        assert [t["state"] for t in out] == ["firing"]
        mon._complete = True  # what _check_complete sets on COMPLETE
        out = mon.evaluate(now=T0 + 1)
        assert [t["state"] for t in out] == ["resolved"]
        assert out[0]["job_complete"] is True
        # and nothing re-fires while complete, however bad the samples
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.0}}, ts=T0 + 2)
        assert mon.evaluate(now=T0 + 2) == []


# -- retention ----------------------------------------------------------------


class TestRetention:
    def test_samples_persist_and_warm_start(self, tmp_path):
        d = str(tmp_path)
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7),
                     monitor_dir=d, retention_s=3600.0)
        now = time.time()
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.9}}, ts=now - 2)
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.8}}, ts=now - 1)
        mon.stop()
        segs = list(tmp_path.glob("*" + obs_monitor.SERIES_SUFFIX))
        assert segs, "no series ring segments written"
        # a restarted monitor resumes the retained window from disk
        mon2 = engine(Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7),
                      monitor_dir=d, retention_s=3600.0)
        assert mon2.health()["retained_samples"] == 2
        assert "w0" in mon2._window
        mon2.stop()

    def test_torn_tail_sample_is_skipped(self, tmp_path):
        d = str(tmp_path)
        mon = engine(monitor_dir=d, retention_s=3600.0)
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.9}}, ts=time.time())
        mon.stop()
        seg = next(tmp_path.glob("*" + obs_monitor.SERIES_SUFFIX))
        with open(seg, "ab") as f:
            f.write(b'{"ts": 1.0, "event": "sample", "target": "w1", "ser')
        mon2 = engine(monitor_dir=d, retention_s=3600.0)
        assert mon2.health()["retained_samples"] == 1  # torn line dropped
        assert "w1" not in mon2._window
        mon2.stop()

    def test_ring_rotation_bounds_disk(self, tmp_path, monkeypatch):
        monkeypatch.setenv("EDL_FLIGHT_SEG_BYTES", "4096")
        monkeypatch.setenv("EDL_FLIGHT_SEGS", "3")
        mon = engine(monitor_dir=str(tmp_path), retention_s=5.0)
        now = time.time()
        for i in range(800):
            mon.ingest("w0", {"edl_goodput_ratio": {"": float(i)}},
                       ts=now + i * 0.01)
        mon.stop()
        segs = list(tmp_path.glob("*" + obs_monitor.SERIES_SUFFIX))
        assert 1 <= len(segs) <= 3
        # in-memory retention is bounded too
        assert all(
            len(w) <= 5.0 / 0.01 + 1 for w in mon._window.values()
        )

    def test_flight_suffix_unchanged_for_other_readers(self, tmp_path):
        """The monitor's .series.jsonl segments must be invisible to
        flight-segment readers (edl-timeline merges *.flight.jsonl of
        the same directory tree)."""
        mon = engine(monitor_dir=str(tmp_path))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 1.0}}, ts=time.time())
        mon.stop()
        flight = obs_events.read_segments(str(tmp_path))  # default suffix
        assert all(e.get("event") != "sample" for e in flight)


# -- alert publication (real store) ------------------------------------------


class TestAlertPublication:
    def test_firing_and_resolution_publish_records(self, store):
        from edl_tpu.store.client import StoreClient

        reg = MetricsRegistry()
        mon = Monitor(
            store.endpoint, "monjob", registry=reg,
            rules=[Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7,
                        severity="critical")],
        )
        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            mon.ingest("w0", {"edl_goodput_ratio": {"": 0.2}}, ts=time.time())
            mon.evaluate()
            alerts = obs_monitor.read_alerts(client, "monjob")
            assert set(alerts) == {"gp"}
            rec = alerts["gp"]
            assert rec["state"] == "firing"
            assert rec["severity"] == "critical"
            assert rec["fired_count"] == 1
            assert rec["firings"] and rec["evidence"][0]["target"] == "w0"
            assert reg.get("edl_monitor_alerts_total").value(
                rule="gp", severity="critical"
            ) == 1
            mon.ingest("w0", {"edl_goodput_ratio": {"": 0.99}}, ts=time.time())
            mon.evaluate()
            rec = obs_monitor.read_alerts(client, "monjob")["gp"]
            assert rec["state"] == "resolved"
            assert rec["fired_count"] == 1  # resolution is not a firing
        finally:
            client.close()
            mon.stop()

    def test_complete_status_key_suppresses(self, store):
        from edl_tpu.store.client import StoreClient

        mon = Monitor(
            store.endpoint, "donejob", registry=MetricsRegistry(),
            rules=[Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7)],
        )
        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            client.put("/donejob/job/status", b"COMPLETE")
            mon.ingest("w0", {"edl_goodput_ratio": {"": 0.0}}, ts=time.time())
            mon.poll_once()
            assert mon._complete
            assert obs_monitor.read_alerts(client, "donejob") == {}
        finally:
            client.close()
            mon.stop()

    def test_alert_transitions_are_flight_recorded(self, tmp_path):
        mon = engine(Rule("gp", metric="edl_goodput_ratio", op="<", value=0.7),
                     monitor_dir=str(tmp_path))
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.1}}, ts=time.time())
        mon.evaluate()
        mon.stop()
        events = obs_events.read_segments(str(tmp_path))
        alerts = [e for e in events if e.get("event") == "alert"]
        assert alerts and alerts[0]["rule"] == "gp"
        assert alerts[0]["state"] == "firing"


# -- self-sample + scraper-side satellites ------------------------------------


class TestScraperSatellites:
    def test_endpoints_export_identity_gauges(self):
        from edl_tpu.obs.http import ObsServer, fetch_metrics

        reg = MetricsRegistry()
        srv = ObsServer("tester", host="127.0.0.1", port=0, registry=reg).start()
        try:
            scraped = fetch_metrics("127.0.0.1:%d" % srv.port, timeout=2.0)
            assert scraped["edl_process_start_time_seconds"][""] > 0
            (labels, value), = scraped["edl_build_info"].items()
            assert value == 1.0
            assert 'version="' in labels and 'python="' in labels
        finally:
            srv.stop()

    def test_collect_exports_dropped_keys_counter(self, store):
        from edl_tpu.obs import metrics as obs_metrics
        from edl_tpu.store.client import StoreClient
        from edl_tpu.utils import telemetry

        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            ctr = obs_metrics.counter("edl_obs_telemetry_dropped_keys_total")
            before = ctr.value()
            client.put("/dropjob/events/s/first_step.w0", b"garbage")
            data = telemetry.collect(client, "dropjob")
            assert data["dropped"] == 1
            assert ctr.value() == before + 1
            # every collect pass that still sees the corruption advances
            # the counter: a nonzero RATE = "corrupt right now"
            telemetry.collect(client, "dropjob")
            assert ctr.value() == before + 2
        finally:
            client.close()

    def test_self_sample_feeds_rules(self, store):
        """The monitor's own registry rides the scrape path: the
        telemetry-dropped-keys rule fires off the monitor's self-sample
        with no external endpoint involved."""
        from edl_tpu.store.client import StoreClient

        rule = Rule("telemetry-dropped-keys", kind="rate",
                    metric="edl_obs_telemetry_dropped_keys_total",
                    op=">", value=0.0, window_s=2.0)
        mon = Monitor(store.endpoint, "corruptjob", rules=[rule], interval=0.3)
        client = StoreClient(store.endpoint, timeout=5.0)
        try:
            client.put("/corruptjob/events/s/first_step.w0", b"garbage")
            fired = []
            deadline = time.time() + 10
            while time.time() < deadline and not fired:
                fired.extend(
                    t for t in mon.poll_once() if t["state"] == "firing"
                )
                time.sleep(0.3)
            assert fired and fired[0]["rule"] == "telemetry-dropped-keys"
        finally:
            client.close()
            mon.stop()


# -- chaos invariants (green/red pair) ---------------------------------------


class TestAlertInvariants:
    def _record(self, firings):
        return {"goodput-degraded": {
            "rule": "goodput-degraded", "fired_count": len(firings),
            "firings": firings,
        }}

    def test_alert_fired_green(self):
        r = inv.alert_fired(self._record([T0 + 5]), "goodput-degraded",
                            after_ts=T0, within_s=30.0)
        assert r.ok, r.detail
        assert "5.00s after the fault" in r.detail

    def test_alert_fired_ignores_prefault_firing(self):
        """A legitimate earlier firing (grow-restage gap) must neither
        satisfy nor mask the post-fault verdict."""
        r = inv.alert_fired(self._record([T0 - 20, T0 + 4]),
                            "goodput-degraded", after_ts=T0, within_s=30.0)
        assert r.ok, r.detail
        r = inv.alert_fired(self._record([T0 - 20]), "goodput-degraded",
                            after_ts=T0, within_s=30.0)
        assert not r.ok

    def test_alert_fired_red_when_late_or_missing(self):
        assert not inv.alert_fired(self._record([T0 + 60]),
                                   "goodput-degraded", T0, 30.0).ok
        assert not inv.alert_fired({}, "goodput-degraded", T0, 30.0).ok
        assert not inv.alert_fired(None, "goodput-degraded", T0, 30.0).ok

    def test_no_false_alerts_pair(self):
        assert inv.no_false_alerts({}).ok
        assert inv.no_false_alerts(None).ok
        red = inv.no_false_alerts(self._record([T0]))
        assert not red.ok and "goodput-degraded" in red.detail


# -- daemon CLI ---------------------------------------------------------------


class TestMonitordCli:
    def test_list_rules(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.edl_monitord",
             "--store", "x", "--job", "j", "--list-rules", "--json"],
            capture_output=True, text=True, timeout=60, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr
        rules = json.loads(out.stdout)
        assert {r["name"] for r in rules} >= {
            "goodput-degraded", "dead-endpoint", "restart-detected"
        }

    def test_once_against_real_store(self, store, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "tools.edl_monitord",
             "--store", store.endpoint, "--job", "clijob", "--once",
             "--json", "--monitor-dir", str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["health"]["job"] == "clijob"
        assert doc["transitions"] == []
        # the sweep retained its self-sample in the ring files
        assert list(tmp_path.glob("*" + obs_monitor.SERIES_SUFFIX))

    def test_rule_overrides_from_file(self, tmp_path):
        rules = tmp_path / "rules.json"
        rules.write_text(json.dumps(
            [{"name": "goodput-degraded", "for_s": 2.5}]
        ))
        out = subprocess.run(
            [sys.executable, "-m", "tools.edl_monitord",
             "--store", "x", "--job", "j", "--list-rules", "--json",
             "--rules", "@%s" % rules],
            capture_output=True, text=True, timeout=60, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr
        by_name = {r["name"]: r for r in json.loads(out.stdout)}
        assert by_name["goodput-degraded"]["for_s"] == 2.5


# -- rule-catalogue lint ------------------------------------------------------


# Since the edl-lint PR these are thin wrappers over the
# `rule-catalogue` analyzer pass (edl_tpu/analysis/catalogue.py): one
# implementation, finding identities distinguish the three contracts.


def _rule_findings(prefixes):
    from edl_tpu.analysis import repo_context, run_analysis

    findings, _ = run_analysis(repo_context(), only=["rule-catalogue"])
    return [
        f for f in findings
        if any(f.identity.startswith(p) for p in prefixes)
    ]


def test_every_builtin_rule_metric_is_catalogued():
    """The rule-catalogue lint (the metric-catalogue lint's sibling):
    every built-in rule must watch a metric that has a DESIGN.md
    catalogue row — renaming a metric without re-pointing the rule that
    watches it must fail CI, not silently produce a rule that can never
    fire again."""
    assert builtin_rules(), "expected built-in rules"
    bad = _rule_findings(["rule-metric:"])
    assert not bad, (
        "built-in rules watching uncatalogued metrics:\n"
        + "\n".join(str(f) for f in bad)
    )


def test_every_builtin_rule_has_a_design_row():
    """Every built-in rule is documented in DESIGN.md's monitor-plane
    rule table (same contract as the fault-point catalogue)."""
    bad = _rule_findings(["rule-row:"])
    assert not bad, (
        "rules missing from the DESIGN.md rule table:\n"
        + "\n".join(str(f) for f in bad)
    )


def test_builtin_rule_names_are_unique_and_slug_shaped():
    assert not _rule_findings(["rule-shape:", "rule-dup:"])


# -- on_fire hook registry (PR 17) --------------------------------------------


class TestOnFireRegistry:
    """``Monitor.on_fire`` is a multi-subscriber registry: the PR-7
    AutoCapture hook and the scale plane's pressure hook must coexist,
    each fired exactly once per firing transition."""

    def _rule(self):
        return Rule("gp", metric="edl_goodput_ratio", op="<",
                    value=0.7, for_s=1.0)

    def _drive_to_firing(self, mon):
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.5}}, ts=T0)
        mon.evaluate(now=T0)
        out = mon.evaluate(now=T0 + 2.1)
        assert [t["state"] for t in out] == ["firing"]

    def test_every_hook_fires_exactly_once_per_firing(self):
        calls = []
        mon = engine(self._rule())
        mon.add_on_fire(lambda rule, doc: calls.append(("a", rule.name)))
        mon.add_on_fire(lambda rule, doc: calls.append(("b", rule.name)))
        self._drive_to_firing(mon)
        assert calls == [("a", "gp"), ("b", "gp")]
        # resolution is NOT a firing: no extra dispatch
        mon.ingest("w0", {"edl_goodput_ratio": {"": 0.9}}, ts=T0 + 3)
        out = mon.evaluate(now=T0 + 3)
        assert [t["state"] for t in out] == ["resolved"]
        assert len(calls) == 2

    def test_raising_hook_does_not_block_the_next(self):
        calls = []

        def bad(rule, doc):
            raise RuntimeError("capture disk full")

        mon = engine(self._rule())
        mon.add_on_fire(bad)
        mon.add_on_fire(lambda rule, doc: calls.append(rule.name))
        self._drive_to_firing(mon)  # the firing itself must not die
        assert calls == ["gp"]

    def test_sole_owner_property_back_compat(self):
        mon = engine(self._rule())

        def first(rule, doc):
            pass

        def second(rule, doc):
            pass

        assert mon.on_fire is None
        mon.on_fire = first                 # the pre-registry shorthand
        assert mon.on_fire is first
        assert mon.add_on_fire(second) is second
        assert mon.on_fire is first         # property reads the head
        mon.remove_on_fire(first)
        assert mon.on_fire is second
        mon.on_fire = None                  # sole-owner clear drops ALL
        assert mon.on_fire is None

    def test_ctor_hook_registered(self):
        calls = []
        mon = engine(
            self._rule(),
            on_fire=lambda rule, doc: calls.append(rule.name),
        )
        self._drive_to_firing(mon)
        assert calls == ["gp"]

    def test_remove_unknown_hook_is_a_noop(self):
        mon = engine(self._rule())
        mon.remove_on_fire(lambda rule, doc: None)  # must not raise
