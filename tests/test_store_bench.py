"""store_bench harness lanes (tools/store_bench.py).

The fast ``--smoke`` lane is tier-1 so the bench harness itself cannot
rot: it drives 200 simulated pods (leased registrations renewed through
the coalesced batch path, pipelined heartbeat/telemetry puts, cluster
watches) against one real durable shard subprocess in a few seconds and
sanity-asserts every layer it claims to measure. The checked-in 10k-pod
results are shape-guarded here too.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

RESULTS = REPO / "bench_results" / "store_bench_cpu_r12.json"


def test_smoke_lane_drives_every_layer(tmp_path):
    """``store_bench --smoke``: one durable shard, 200 pods, <20 s —
    exits 0 only when puts flowed, the renew coalescer ran, latency got
    shard-attributed, and the server-side histograms were scraped (the
    bench's own asserts)."""
    out = subprocess.run(
        [
            sys.executable, str(REPO / "tools" / "store_bench.py"),
            "--smoke", "--workdir", str(tmp_path),
        ],
        capture_output=True, text=True, timeout=120,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    (result,) = doc["results"]
    assert result["shards"] == 1
    assert result["puts"] > 200
    assert result["renew_rpcs_per_s"] > 0
    row = result["client_put_ms_by_shard"]["store-0"]
    assert row["n"] > 0 and row["p99_ms"] > 0
    # trace-plane attribution: the per-method server histograms came
    # back from the shard's /metrics endpoint
    server = result["server_ms_by_shard"]["store-0"]
    assert server["put"]["n"] > 200


def test_checked_in_results_shape():
    """The committed 10k-pod results carry the acceptance numbers: a
    baseline lane, the 1/2/4-shard sweep, and the vs-baseline ratios
    (>=2x aggregate write throughput and a lower per-shard p99 at 4
    shards)."""
    doc = json.loads(RESULTS.read_text())
    modes = [(r["mode"], r["shards"]) for r in doc["results"]]
    assert ("baseline-per-write-fsync", 1) in modes
    assert ("sharded", 4) in modes
    assert doc["config"]["pods"] == 10000
    assert doc["config"]["durable"] is True
    assert doc["speedup_4shard_vs_baseline"] >= 2.0
    assert doc["p99_4shard_over_baseline"] < 1.0
    four = next(
        r for r in doc["results"]
        if r["mode"] == "sharded" and r["shards"] == 4
    )
    # per-shard attribution present for every shard, client and server
    assert len(four["client_put_ms_by_shard"]) == 4
    assert len(four["server_ms_by_shard"]) == 4
