"""Zero-copy (EDL2) frames: ndref encode/resolve + socket roundtrips.

The bulk-data extension of the wire protocol (edl_tpu/rpc/wire.py): large
arrays ride as raw attachments after the msgpack body via scatter/gather
send, received into a single buffer and viewed zero-copy.
"""

import socket
import threading

import numpy as np
import pytest

from edl_tpu.rpc.ndarray import encode_tree_zc, resolve_ndrefs
from edl_tpu.rpc.wire import (
    FrameReader,
    pack_frame,
    pack_frame_buffers,
    read_frame_blocking,
    send_buffers,
)


def roundtrip_via_socket(buffers):
    a, b = socket.socketpair()
    try:
        t = threading.Thread(target=send_buffers, args=(a, buffers))
        t.start()
        out = read_frame_blocking(b)
        t.join()
        return out
    finally:
        a.close()
        b.close()


class TestNdRefs:
    def test_encode_resolve_roundtrip(self):
        tree = {
            "x": np.arange(12, dtype=np.float32).reshape(3, 4),
            "nested": {"y": np.ones((2, 2), np.int64)},
            "plain": [1, "two", 3.0],
        }
        encoded, atts = encode_tree_zc(tree)
        assert len(atts) == 2
        region = memoryview(b"".join(bytes(a) for a in atts))
        out = resolve_ndrefs(encoded, region)
        np.testing.assert_array_equal(out["x"], tree["x"])
        np.testing.assert_array_equal(out["nested"]["y"], tree["nested"]["y"])
        assert out["plain"] == [1, "two", 3.0]

    def test_noncontiguous_input(self):
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        view = base[:, ::2]  # non-contiguous
        encoded, atts = encode_tree_zc({"v": view})
        region = memoryview(b"".join(bytes(a) for a in atts))
        np.testing.assert_array_equal(resolve_ndrefs(encoded, region)["v"], view)

    def test_zero_length_array(self):
        encoded, atts = encode_tree_zc({"empty": np.zeros((0, 5), np.float32)})
        region = memoryview(b"".join(bytes(a) for a in atts))
        out = resolve_ndrefs(encoded, region)
        assert out["empty"].shape == (0, 5)


class TestEdl2Frames:
    def test_socket_roundtrip(self):
        arr = np.random.rand(16, 7).astype(np.float32)
        payload, atts = encode_tree_zc({"i": 1, "feeds": {"img": arr}})
        out = roundtrip_via_socket(pack_frame_buffers(payload, atts))
        assert out["i"] == 1
        np.testing.assert_array_equal(out["feeds"]["img"], arr)

    def test_frame_reader_handles_both_magics(self):
        arr = np.arange(6, dtype=np.int32)
        payload, atts = encode_tree_zc({"a": arr})
        edl2 = b"".join(bytes(memoryview(b).cast("B")) for b in
                        pack_frame_buffers(payload, atts))
        edl1 = pack_frame({"b": 2})
        reader = FrameReader()
        # interleaved + split across feeds at an awkward boundary
        stream = edl1 + edl2 + edl1
        out = []
        for i in range(0, len(stream), 7):
            out.extend(reader.feed(stream[i : i + 7]))
        assert len(out) == 3
        assert out[0] == {"b": 2} and out[2] == {"b": 2}
        np.testing.assert_array_equal(out[1]["a"], arr)

    def test_zero_size_array_over_socket(self):
        """Empty attachments must not stall send_buffers (sendmsg reports
        0 bytes for them — indistinguishable from no progress)."""
        payload, atts = encode_tree_zc(
            {"a": np.zeros((0, 10), np.float32), "b": np.ones((2,), np.int32)}
        )
        out = roundtrip_via_socket(pack_frame_buffers(payload, atts))
        assert out["a"].shape == (0, 10)
        np.testing.assert_array_equal(out["b"], np.ones((2,), np.int32))

    def test_received_arrays_are_readonly_both_paths(self):
        arr = np.arange(4, dtype=np.float32)
        payload, atts = encode_tree_zc({"a": arr})
        via_blocking = roundtrip_via_socket(pack_frame_buffers(payload, atts))
        reader = FrameReader()
        stream = b"".join(bytes(memoryview(b).cast("B")) for b in
                          pack_frame_buffers(*encode_tree_zc({"a": arr})))
        (via_reader,) = reader.feed(stream)
        for out in (via_blocking, via_reader):
            with pytest.raises(ValueError):
                out["a"][0] = 9.0

    def test_received_array_values_independent_of_sender_mutation(self):
        """The receive side owns its buffer: sender-side reuse of the array
        after send cannot corrupt what was received."""
        arr = np.zeros((4,), np.float32)
        payload, atts = encode_tree_zc({"a": arr})
        buffers = [bytes(memoryview(b).cast("B")) for b in
                   pack_frame_buffers(payload, atts)]  # snapshot pre-mutation
        arr += 99.0
        out = roundtrip_via_socket(buffers)
        np.testing.assert_array_equal(out["a"], np.zeros((4,), np.float32))
