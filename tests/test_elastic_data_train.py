"""Elastic data + train integration (VERDICT #4): the dispatcher, loader,
DataCheckpoint, CheckpointManager, launcher and resize harness driven
TOGETHER.

- coverage: real launcher pods churned mid-epoch (kill + add); afterwards
  every (file, record) of every epoch was consumed, exactly once in
  epochs untouched by churn, with only a bounded re-read tail in churned
  epochs (re-dispatched tasks resume at the last *reported* record).
- exact resume: a single worker checkpointing (model + DataCheckpoint in
  TrainStatus.meta) is SIGKILLed mid-epoch and relaunched; because model
  and data position roll back atomically and task order is a pure
  function of (seed, epoch) — the reference's pass_id_as_seed contract
  (train_with_fleet.py:458-464) — its final params are IDENTICAL to an
  uninterrupted run's.
"""

import collections
import json
import os
import subprocess
import sys
import time

from edl_tpu.harness.resize import ResizeHarness
import pytest

pytestmark = pytest.mark.slow  # compile-heavy / multi-process integration


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "data_train_worker.py")

FILES = 3
LINES = 80


def make_corpus(root) -> str:
    data_dir = os.path.join(str(root), "corpus")
    os.makedirs(data_dir, exist_ok=True)
    for i in range(FILES):
        with open(os.path.join(data_dir, "part-%02d.txt" % i), "w") as f:
            for j in range(LINES):
                f.write("file %d line %d payload\n" % (i, j))
    return data_dir


def wait_for(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(0.05)
    raise AssertionError("timed out waiting for %s" % msg)


def test_coverage_exactly_once_under_churn(store, tmp_path):
    out = str(tmp_path / "out")
    os.makedirs(out)
    data_dir = make_corpus(tmp_path)
    epochs = 3
    harness = ResizeHarness(
        store.endpoint, "jdata", WORKER,
        nodes_range="1:3", ttl=0.8,
        extra_env={
            "TEST_MODE": "coverage",
            "TEST_OUT_DIR": out,
            "TEST_DATA_DIR": data_dir,
            "TEST_EPOCHS": str(epochs),
            "JAX_PLATFORMS": "cpu",
            "EDL_DEVICES_PER_PROC": "1",
        },
    )
    try:
        # 2 pods -> kill one -> back to 2: two churn transitions while the
        # epochs stream
        assert harness.run_schedule([2, 1, 2], interval=2.5, timeout=240)
    finally:
        harness.shutdown()

    # one consumption log per worker incarnation: consume.<stage>.<rank>.<pid>
    per_epoch = collections.defaultdict(collections.Counter)
    epoch_stages = collections.defaultdict(set)
    for name in os.listdir(out):
        if not name.startswith("consume."):
            continue
        stage = name.split(".")[1]
        with open(os.path.join(out, name)) as f:
            for line in f:
                e, fi, ri = map(int, line.split())
                per_epoch[e][(fi, ri)] += 1
                epoch_stages[e].add(stage)

    want = {(f, r) for f in range(FILES) for r in range(LINES)}
    total_dupes = 0
    for e in range(epochs):
        counts = per_epoch[e]
        missing = want - set(counts)
        assert not missing, "epoch %d missing %d records, e.g. %s" % (
            e, len(missing), sorted(missing)[:5],
        )
        extra = set(counts) - want
        assert not extra, "epoch %d has unknown records %s" % (e, extra)
        dupes = sum(c - 1 for c in counts.values())
        if len(epoch_stages[e]) == 1:
            # no restart touched this epoch: exactly-once, no excuses
            assert dupes == 0, "stable epoch %d has %d duplicates" % (e, dupes)
        total_dupes += dupes
    # churned epochs may re-read at most the yielded-but-unreported tail of
    # each killed incarnation's in-flight task (report_every=1)
    assert total_dupes <= 20, "unreasonable duplicate volume: %d" % total_dupes


def _final(out):
    path = os.path.join(out, "final.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _run_static(store_endpoint, out, data_dir, ckpt, epochs):
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        EDL_JOB_ID="jstatic",
        EDL_STORE_ENDPOINT=store_endpoint,
        TEST_MODE="train",
        TEST_OUT_DIR=out,
        TEST_DATA_DIR=data_dir,
        TEST_CKPT_DIR=ckpt,
        TEST_EPOCHS=str(epochs),
        TEST_CKPT_EVERY="20",
        JAX_PLATFORMS="cpu",
        EDL_DEVICES_PER_PROC="1",
    )
    proc = subprocess.run(
        [sys.executable, WORKER], env=env, capture_output=True, text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    final = _final(out)
    assert final is not None
    return final


def test_exact_resume_matches_static_run(store, tmp_path):
    data_dir = make_corpus(tmp_path)
    epochs = 2

    # uninterrupted baseline (same code path, no churn)
    out_a = str(tmp_path / "static_out")
    os.makedirs(out_a)
    static = _run_static(
        store.endpoint, out_a, data_dir, str(tmp_path / "static_ckpt"), epochs
    )

    # churned run under the launcher: SIGKILL mid-epoch after >=1 ckpt
    out_b = str(tmp_path / "churn_out")
    os.makedirs(out_b)
    ckpt_b = str(tmp_path / "churn_ckpt")
    harness = ResizeHarness(
        store.endpoint, "jresume", WORKER,
        nodes_range="1:1", ttl=0.8,
        extra_env={
            "TEST_MODE": "train",
            "TEST_OUT_DIR": out_b,
            "TEST_DATA_DIR": data_dir,
            "TEST_CKPT_DIR": ckpt_b,
            "TEST_EPOCHS": str(epochs),
            "TEST_CKPT_EVERY": "20",
            "TEST_STEP_DELAY": "0.05",
            "JAX_PLATFORMS": "cpu",
            "EDL_DEVICES_PER_PROC": "1",
        },
    )
    try:
        harness.start_pod()

        def has_ckpt():
            try:
                return any(d.isdigit() for d in os.listdir(ckpt_b))
            except OSError:
                return False

        wait_for(has_ckpt, 120, "first checkpoint")
        time.sleep(0.5)  # run a few steps past the checkpoint
        assert _final(out_b) is None, "job finished before the kill"
        harness.kill_pod(harness.pods[0])
        harness.start_pod()
        wait_for(harness.job_complete, 180, "job completion after resume")
    finally:
        harness.shutdown()

    churned = _final(out_b)
    assert churned is not None
    assert churned["steps"] == static["steps"]
    assert churned["b"] == static["b"]
    assert churned["w"] == static["w"], (
        "kill-resume must be invisible to the training trajectory"
    )
