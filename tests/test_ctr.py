"""CTR model family: DeepFM with mesh-sharded embedding tables + AUC.

The reference trains CTR under a parameter-server architecture
(example/ctr/ctr/train.py); here the embedding tables shard over the
``mp`` mesh axis (SURVEY §2 "Parameter-server" row: re-scope as
embedding-heavy DP with sharded tables). Tests run on the virtual
8-device CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import CTR_EMBEDDING_RULES, DeepFM, binary_cross_entropy_loss
from edl_tpu.parallel import make_mesh, shard_batch, shard_params_by_rules
from edl_tpu.train import (
    auc_compute,
    auc_init,
    auc_merge,
    auc_update,
    create_state,
    make_train_step,
)

VOCAB, FIELDS, DENSE = 512, 6, 4


def make_batch(rng, batch=32):
    k1, k2, k3 = jax.random.split(rng, 3)
    sparse = jax.random.randint(k1, (batch, FIELDS), 0, VOCAB)
    dense = jax.random.normal(k2, (batch, DENSE))
    labels = jax.random.bernoulli(k3, 0.3, (batch,)).astype(jnp.int32)
    return (sparse, dense), labels


@pytest.fixture(scope="module")
def model():
    return DeepFM(
        vocab_size=VOCAB, embed_dim=8, num_fields=FIELDS,
        dense_features=DENSE, mlp_dims=(16, 8), dtype=jnp.float32,
    )


class TestDeepFM:
    def test_forward_shape(self, model):
        (x, labels) = make_batch(jax.random.PRNGKey(0))
        state = create_state(model, jax.random.PRNGKey(1), x, optax.sgd(0.1))
        logits = model.apply({"params": state.params}, x)
        assert logits.shape == labels.shape
        assert logits.dtype == jnp.float32

    def test_loss_decreases_under_training(self, model):
        x, y = make_batch(jax.random.PRNGKey(0), batch=64)
        state = create_state(model, jax.random.PRNGKey(1), x, optax.adam(1e-2))
        step = make_train_step(binary_cross_entropy_loss)
        first = None
        for _ in range(30):
            state, metrics = step(state, (x, y))
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first

    def test_sharded_embedding_train_step(self, model):
        """dp x mp mesh: batch over dp, embedding vocab over mp; one real
        step executes and matches the unsharded step numerically."""
        x, y = make_batch(jax.random.PRNGKey(0), batch=16)
        state = create_state(model, jax.random.PRNGKey(1), x, optax.sgd(0.1))
        step = make_train_step(binary_cross_entropy_loss, donate=False)
        _, ref_metrics = step(state, (x, y))

        mesh = make_mesh({"dp": 2, "mp": 4})
        with mesh:
            sharded = state.replace(
                params=shard_params_by_rules(
                    mesh, state.params, CTR_EMBEDDING_RULES
                )
            )
            batch = shard_batch(mesh, (x, y))
            new_state, metrics = step(sharded, batch)
            jax.block_until_ready(metrics["loss"])
        assert np.isclose(
            float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
        )
        # embedding table sharding is preserved through the update
        emb = new_state.params["embedding"]["embedding"]
        spec = emb.sharding.spec
        assert spec and spec[0] == "mp", spec

    def test_embedding_rules_match_param_paths(self, model):
        x, _ = make_batch(jax.random.PRNGKey(0), batch=4)
        state = create_state(model, jax.random.PRNGKey(1), x, optax.sgd(0.1))
        mesh = make_mesh({"dp": 2, "mp": 4})
        params = shard_params_by_rules(mesh, state.params, CTR_EMBEDDING_RULES)
        for name in ("embedding", "wide"):
            spec = params[name]["embedding"].sharding.spec
            assert spec and spec[0] == "mp", (name, spec)


class TestStreamingAUC:
    def _numpy_auc(self, scores, labels):
        """Rank-statistic AUC with tie correction (the exact value the
        bucketed estimator approaches as buckets -> inf)."""
        order = np.argsort(scores)
        ranks = np.empty(len(scores), dtype=np.float64)
        sorted_scores = scores[order]
        i = 0
        rank = 1
        while i < len(scores):
            j = i
            while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
                j += 1
            ranks[order[i : j + 1]] = (rank + rank + (j - i)) / 2.0
            rank += j - i + 1
            i = j + 1
        pos = labels == 1
        n_pos, n_neg = pos.sum(), (~pos).sum()
        return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)

    def test_matches_exact_auc(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(4000).astype(np.float32) * 2
        labels = (rng.rand(4000) < jax.nn.sigmoid(logits * 0.7)).astype(np.int32)
        state = auc_init(num_buckets=4096)
        # stream in 4 chunks through a jitted update
        update = jax.jit(auc_update)
        for i in range(4):
            sl = slice(i * 1000, (i + 1) * 1000)
            state = update(state, jnp.asarray(logits[sl]), jnp.asarray(labels[sl]))
        got = float(auc_compute(state))
        want = self._numpy_auc(
            np.asarray(jax.nn.sigmoid(jnp.asarray(logits))), labels
        )
        assert abs(got - want) < 2e-3, (got, want)

    def test_merge_equals_single_stream(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(2000).astype(np.float32))
        labels = jnp.asarray((rng.rand(2000) < 0.4).astype(np.int32))
        whole = auc_update(auc_init(256), logits, labels)
        a = auc_update(auc_init(256), logits[:800], labels[:800])
        b = auc_update(auc_init(256), logits[800:], labels[800:])
        merged = auc_merge(a, b)
        assert np.allclose(whole.pos, merged.pos)
        assert np.allclose(whole.neg, merged.neg)
        assert np.isclose(float(auc_compute(whole)), float(auc_compute(merged)))

    def test_perfect_and_random_classifiers(self):
        labels = jnp.asarray([0, 0, 0, 1, 1, 1], jnp.int32)
        perfect = auc_update(
            auc_init(1024), jnp.asarray([-5.0, -4.0, -3.0, 3.0, 4.0, 5.0]), labels
        )
        assert float(auc_compute(perfect)) > 0.999
        constant = auc_update(auc_init(1024), jnp.zeros((6,)), labels)
        assert abs(float(auc_compute(constant)) - 0.5) < 1e-6
