"""Fast dispatch-table units for ops.attention.attention (no compile-heavy
kernel work — the composition numerics live in test_attention.py)."""

import importlib
import importlib.util
import json
import os

import jax
import numpy as np
import pytest

from edl_tpu.ops.attention import attention, attention_reference


def _qkv(b=2, h=2, t=24, d=8, seed=0):
    rng = np.random.RandomState(seed)
    import jax.numpy as jnp
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    return mk(), mk(), mk()


class TestDispatchFast:
    def test_entry_point_off_tpu_is_reference(self):
        q, k, v = _qkv(t=24)  # 24 is even ragged-ish; fine for dense
        out = attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_dispatch_table_env_override(self, tmp_path, monkeypatch):
        import importlib
        import json

        A = importlib.import_module("edl_tpu.ops.attention")

        path = tmp_path / "table.json"
        path.write_text(json.dumps({
            "fwd": [[512, "ref"], [None, "flash"]],
            "bwd": [[None, "flash"]],
            "whole": [[None, "builtin"]],
        }))
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(path))
        A._dispatch_table.cache_clear()
        try:
            table = A._dispatch_table()
            assert A._lookup(table["fwd"], 512) == "ref"
            assert A._lookup(table["fwd"], 513) == "flash"
            assert A._lookup(table["whole"], 10_000) == "builtin"
            assert A._lookup(table["bwd"], 4096) == "flash"
        finally:
            A._dispatch_table.cache_clear()

    def test_rows_from_winners(self):
        mod = _load_bench()
        rows = mod._rows_from_winners(
            [(1024, "ref"), (2048, "ref"), (4096, "flash")]
        )
        assert rows == [[2048, "ref"], [None, "flash"]]
        assert mod._rows_from_winners([]) == []

    def test_unknown_impl_falls_back_to_default(self, tmp_path, monkeypatch):
        A = importlib.import_module("edl_tpu.ops.attention")
        # isolate the bottom tier: the real packaged artifact (shipped
        # since r4) would otherwise be the fallback
        monkeypatch.setattr(A, "_PACKAGED_DISPATCH", str(tmp_path / "none"))
        path = tmp_path / "table.json"
        path.write_text(json.dumps({
            "fwd": [[None, "flsh"]],  # typo: must not silently reroute
            "bwd": [[None, "flash"]],
        }))
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(path))
        A._dispatch_table.cache_clear()
        try:
            assert A._dispatch_table() == A._DEFAULT_DISPATCH
        finally:
            A._dispatch_table.cache_clear()

    def test_malformed_file_falls_back_to_default(self, tmp_path, monkeypatch):
        A = importlib.import_module("edl_tpu.ops.attention")
        monkeypatch.setattr(A, "_PACKAGED_DISPATCH", str(tmp_path / "none"))
        path = tmp_path / "table.json"
        path.write_text("{not json")
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(path))
        A._dispatch_table.cache_clear()
        try:
            assert A._dispatch_table() == A._DEFAULT_DISPATCH
        finally:
            A._dispatch_table.cache_clear()
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(tmp_path / "missing"))
        A._dispatch_table.cache_clear()
        try:
            assert A._dispatch_table() == A._DEFAULT_DISPATCH
        finally:
            A._dispatch_table.cache_clear()

    def test_packaged_artifact_is_default_when_no_env(
        self, tmp_path, monkeypatch
    ):
        """No EDL_ATTN_DISPATCH -> the calibration artifact shipped next
        to ops/attention.py is the table; a malformed packaged file
        degrades to the hard-coded default."""
        A = importlib.import_module("edl_tpu.ops.attention")
        monkeypatch.delenv("EDL_ATTN_DISPATCH", raising=False)
        packaged = tmp_path / "attention_dispatch.json"
        packaged.write_text(json.dumps({
            "fwd": [[1024, "ref"], [None, "flash2"]],
            "bwd": [[4096, "flash"], [None, "ref"]],
        }))
        monkeypatch.setattr(A, "_PACKAGED_DISPATCH", str(packaged))
        A._dispatch_table.cache_clear()
        try:
            table = A._dispatch_table()
            assert A._lookup(table["fwd"], 2048) == "flash2"
            assert A._lookup(table["bwd"], 8192) == "ref"
        finally:
            A._dispatch_table.cache_clear()
        # env var outranks the packaged artifact; keys the env artifact
        # omits inherit the PACKAGED rows, not the hard-coded default
        override = tmp_path / "override.json"
        override.write_text(json.dumps({"fwd": [[None, "flash"]]}))
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(override))
        A._dispatch_table.cache_clear()
        try:
            table = A._dispatch_table()
            assert A._lookup(table["fwd"], 64) == "flash"
            assert A._lookup(table["bwd"], 8192) == "ref"
        finally:
            A._dispatch_table.cache_clear()
        # malformed packaged file -> hard-coded default, no crash
        monkeypatch.delenv("EDL_ATTN_DISPATCH")
        packaged.write_text("{broken")
        A._dispatch_table.cache_clear()
        try:
            assert A._dispatch_table() == A._DEFAULT_DISPATCH
        finally:
            A._dispatch_table.cache_clear()

    def test_memory_guard_reroutes_huge_dense_fwd(self, monkeypatch):
        A = importlib.import_module("edl_tpu.ops.attention")
        table = {
            "fwd": ((A._INF, "ref"),),
            "bwd": ((A._INF, "ref"),),
            "whole": (),
        }
        # under the limit: table wins
        assert A._select_impls(table, 4, 16, 2048, 2048) == ("ref", "ref")
        # 32 * 32 * 8192^2 * 4B = 256 GiB of scores: guard reroutes both
        # directions; at 8192 the flash-compile guard then lands both on
        # flash2 (the whole-KV kernel does not compile past 4096)
        assert A._select_impls(table, 32, 32, 8192, 8192) == (
            "flash2", "flash2"
        )
        monkeypatch.setenv("EDL_ATTN_DENSE_LIMIT", str(1 << 60))
        A._dense_score_bytes_limit.cache_clear()
        try:
            assert A._select_impls(table, 32, 32, 8192, 8192) == ("ref", "ref")
        finally:
            A._dense_score_bytes_limit.cache_clear()

    def test_flash_compile_guard_remaps_long_seq_to_flash2(self):
        A = importlib.import_module("edl_tpu.ops.attention")
        table = {
            "fwd": ((A._INF, "flash"),),
            "bwd": ((A._INF, "flash"),),
            "whole": (),
        }
        # within the compile limit: flash stays
        assert A._select_impls(table, 4, 16, 4096, 4096) == ("flash", "flash")
        # past it: flash does not compile -> flash2 both directions
        assert A._select_impls(table, 4, 16, 8192, 8192) == (
            "flash2", "flash2"
        )
        # an explicit ref routing is left alone (the memory guard owns
        # that decision)
        table_ref = {
            "fwd": ((A._INF, "ref"),), "bwd": ((A._INF, "ref"),),
            "whole": (),
        }
        assert A._select_impls(table_ref, 1, 1, 8192, 8192) == ("ref", "ref")

    def test_public_flash_entry_points_reroute_past_compile_limit(
        self, monkeypatch
    ):
        """flash_attention/flash_with_lse must not build the whole-KV
        kernel past the flash compile limit (it crashes the TPU
        compiler); with the limit shrunk, both must still match the
        reference through the grid-pipelined route."""
        A = importlib.import_module("edl_tpu.ops.attention")
        monkeypatch.setenv("EDL_FLASH_MAX_SEQ", "64")
        A._flash_max_seq.cache_clear()
        try:
            q, k, v = _qkv(t=128, d=8)
            out = A.flash_attention(q, k, v, causal=True)
            ref = A.attention_reference(q, k, v, causal=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-4
            )
            o2, lse = A.flash_with_lse(q, k, v, causal=True)
            _, lse_ref = A.attention_reference_with_lse(q, k, v, causal=True)
            np.testing.assert_allclose(
                np.asarray(o2), np.asarray(ref), atol=2e-4
            )
            np.testing.assert_allclose(
                np.asarray(lse), np.asarray(lse_ref), atol=2e-4
            )
        finally:
            A._flash_max_seq.cache_clear()

    def test_kernel_blocks_table(self):
        A = importlib.import_module("edl_tpu.ops.attention")
        assert A._kernel_blocks(1024) == ((256, 512), (256, 512))
        assert A._kernel_blocks(2048) == ((512, 512), (256, 512))
        assert A._kernel_blocks(4096) == ((128, 512), (512, 512))
        assert A._kernel_blocks(65536) == ((128, 512), (512, 512))


def _load_tool(filename):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        filename[:-3], os.path.join(root, "tools", filename)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_bench():
    return _load_tool("attention_bench.py")


class TestCalibrationPicksMinima:
    """The table builder must pick per-row minima from a recorded
    measurement file — so a calibration artifact can never ship a row the
    measurements contradict (the r2 artifact implied dense bwd beat flash
    bwd at 4096 while the then-default said flash everywhere)."""

    def _results(self):
        # seconds, shaped like the round-2 on-chip artifact
        # (bench_results/attention_tpu_r2.jsonl, v5e [4,16,T,64] bf16):
        # dense fwd wins <=2048, flash fwd wins at 4096; and at 4096 the
        # dense-bwd composition beats the flash-bwd one (the inversion).
        r = {}
        fwd = {
            1024: {"reference": 0.97e-3, "flash": 1.64e-3, "builtin": 1.2e-3,
                   "comp_flash2_flash": 1.7e-3},
            4096: {"reference": 30.87e-3, "flash": 25.01e-3, "builtin": 26e-3,
                   "comp_flash2_flash": 25.5e-3},
        }
        fwd_bwd = {
            1024: {"reference": 2.8e-3, "flash": 2.7e-3, "builtin": 3.0e-3,
                   "comp_ref_flash": 2.1e-3, "comp_flash_ref": 3.4e-3,
                   "comp_flash2_flash": 2.9e-3, "comp_flash2_ref": 3.5e-3,
                   "comp_flash2_flash2": 3.0e-3, "comp_ref_flash2": 2.3e-3,
                   "comp_flash_flash2": 2.8e-3},
            4096: {"reference": 57.97e-3, "flash": 60.15e-3, "builtin": 59e-3,
                   # flash fwd (winner) + ref bwd: 25.01 + 27.1 = 52.1
                   "comp_flash_ref": 52.1e-3,
                   "comp_ref_flash": 66.0e-3,
                   "comp_flash2_flash": 61.0e-3, "comp_flash2_ref": 53.0e-3,
                   "comp_flash2_flash2": 62.0e-3, "comp_ref_flash2": 67.0e-3,
                   "comp_flash_flash2": 61.0e-3},
        }
        for seq, times in fwd.items():
            for name, t in times.items():
                r[(name, "fwd", seq)] = t
        for seq, times in fwd_bwd.items():
            for name, t in times.items():
                r[(name, "fwd_bwd", seq)] = t
        return r

    def test_minima_and_inversion(self):
        mod = _load_bench()
        A = importlib.import_module("edl_tpu.ops.attention")
        table = mod.build_dispatch_table(self._results(), [1024, 4096], True)
        # fwd: dense wins at 1024, flash at 4096
        assert table["fwd"] == [[1024, "ref"], [None, "flash"]]
        # bwd: flash wins at 1024 (comp_ref_flash fastest with ref fwd);
        # ref wins at 4096 (comp_flash_ref < flash and < builtin) — the
        # inversion the r2 numbers implied MUST survive into the table
        assert table["bwd"] == [[1024, "flash"], [None, "ref"]]
        # builtin never beats the best composition in this recording
        assert table["whole"] == [[None, "comp"]]
        # every impl name in the artifact is loadable (validation gate)
        for key in ("fwd", "bwd", "whole"):
            for _, impl in table[key]:
                assert impl in A._VALID_IMPLS[key]

    def test_joint_pair_beats_greedy_fwd_first(self):
        """The r4 recalibration regression: flash2 won fwd-only at 1024
        by 0.05 ms but every flash2 composition lost by ~0.2 ms — the
        winner must be the jointly-fastest (fwd, bwd) PAIR, not the best
        bwd for the fwd-only winner."""
        mod = _load_bench()
        r = self._results()
        # make flash2 the fwd-only winner at 1024...
        r[("comp_flash2_flash", "fwd", 1024)] = 0.90e-3
        # ...but keep every flash2 composition slower than (ref, flash)
        # (comp_ref_flash is 2.1e-3 in the base recording)
        table = mod.build_dispatch_table(r, [1024], False)
        assert table["fwd"] == [[None, "ref"]]
        assert table["bwd"] == [[None, "flash"]]

    def test_builtin_row_when_it_wins(self):
        mod = _load_bench()
        r = self._results()
        # make builtin strictly fastest at 4096, both modes
        r[("builtin", "fwd", 4096)] = 20e-3
        r[("builtin", "fwd_bwd", 4096)] = 45e-3
        table = mod.build_dispatch_table(r, [1024, 4096], True)
        assert table["whole"] == [[1024, "comp"], [None, "builtin"]]
        # and the calibrated artifact round-trips through the loader
        A = importlib.import_module("edl_tpu.ops.attention")
        for key in ("fwd", "bwd", "whole"):
            for _, impl in table[key]:
                assert impl in A._VALID_IMPLS[key]


def _load_installer():
    return _load_tool("install_dispatch.py")


class TestInstallDispatch:
    """tools/install_dispatch.py promotes a calibration artifact to the
    packaged default — refusing artifacts its own measurement file
    contradicts, so an inverted row can never become the shipped table."""

    def _write_jsonl(self, path, results):
        rows = []
        for (name, mode, seq), secs in results.items():
            rows.append(json.dumps({
                "metric": "attention_%s_%s" % (name, mode),
                "seq": seq, "ms": secs * 1e3,
            }))
        # summary rows the parser must skip
        rows.append(json.dumps({
            "metric": "attention_dispatch_speedup", "seq": 1024, "fwd": 1.0,
        }))
        path.write_text("\n".join(rows) + "\n")

    def test_roundtrip_and_contradiction_gate(self, tmp_path, monkeypatch):
        inst = _load_installer()
        bench = _load_bench()
        A = importlib.import_module("edl_tpu.ops.attention")
        results = TestCalibrationPicksMinima()._results()
        measured = tmp_path / "measured.jsonl"
        self._write_jsonl(measured, results)
        # jsonl -> results dict round-trips (float via ms conversion)
        got, seqs, has_builtin = inst.results_from_jsonl(str(measured))
        assert seqs == [1024, 4096] and has_builtin
        assert got.keys() == results.keys()
        table = bench.build_dispatch_table(results, seqs, has_builtin)
        artifact = tmp_path / "dispatch.json"
        artifact.write_text(json.dumps(table))
        packaged = tmp_path / "attention_dispatch.json"
        monkeypatch.setattr(A, "_PACKAGED_DISPATCH", str(packaged))
        # consistent artifact installs
        monkeypatch.setattr(
            "sys.argv",
            ["x", str(artifact), "--check-against", str(measured)],
        )
        assert inst.main() == 0
        assert json.loads(packaged.read_text()) == table
        # an inverted bwd row is refused (flash@4096 composes 60.15 ms vs
        # the measured-best 52.1 ms — far beyond the rounding tolerance)
        bad = dict(table)
        bad["bwd"] = [[None, "flash"]]
        artifact.write_text(json.dumps(bad))
        packaged.unlink()
        assert inst.main() == 1
        assert not packaged.exists()
        # a near-tie within TOLERANCE is NOT a contradiction: rows carry
        # ms rounded to 3 decimals, so exact-winner equality would refuse
        # artifacts the same run produced
        tied = dict(results)
        tied[("comp_flash_ref", "fwd_bwd", 4096)] = 52.1e-3
        tied[("comp_flash2_ref", "fwd_bwd", 4096)] = 52.1004e-3
        measured2 = tmp_path / "measured_tie.jsonl"
        self._write_jsonl(measured2, tied)
        art2 = tmp_path / "dispatch2.json"
        t2 = dict(table)
        t2["bwd"] = [[1024, "flash"], [None, "ref"]]
        art2.write_text(json.dumps(t2))
        monkeypatch.setattr(
            "sys.argv",
            ["x", str(art2), "--check-against", str(measured2), "--dry-run"],
        )
        assert inst.main() == 0

    def test_unusable_measurement_file_is_diagnosed(
        self, tmp_path, monkeypatch, capsys
    ):
        inst = _load_installer()
        bench = _load_bench()
        results = TestCalibrationPicksMinima()._results()
        table = bench.build_dispatch_table(results, [1024, 4096], True)
        artifact = tmp_path / "dispatch.json"
        artifact.write_text(json.dumps(table))
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        monkeypatch.setattr(
            "sys.argv", ["x", str(artifact), "--check-against", str(empty)],
        )
        assert inst.main() == 1
        assert "no calibration rows" in capsys.readouterr().err
