"""Fast dispatch-table units for ops.attention.attention (no compile-heavy
kernel work — the composition numerics live in test_attention.py)."""

import importlib
import importlib.util
import json
import os

import jax
import numpy as np
import pytest

from edl_tpu.ops.attention import attention, attention_reference


def _qkv(b=2, h=2, t=24, d=8, seed=0):
    rng = np.random.RandomState(seed)
    import jax.numpy as jnp
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    return mk(), mk(), mk()


class TestDispatchFast:
    def test_entry_point_off_tpu_is_reference(self):
        q, k, v = _qkv(t=24)  # 24 is even ragged-ish; fine for dense
        out = attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_dispatch_table_env_override(self, tmp_path, monkeypatch):
        import importlib
        import json

        A = importlib.import_module("edl_tpu.ops.attention")

        path = tmp_path / "table.json"
        path.write_text(json.dumps({
            "fwd": [[512, "ref"], [None, "flash"]],
            "bwd": [[None, "flash"]],
            "whole": [[None, "builtin"]],
        }))
        monkeypatch.setenv("EDL_ATTN_DISPATCH", str(path))
        A._dispatch_table.cache_clear()
        try:
            table = A._dispatch_table()
            assert A._lookup(table["fwd"], 512) == "ref"
            assert A._lookup(table["fwd"], 513) == "flash"
            assert A._lookup(table["whole"], 10_000) == "builtin"
            assert A._lookup(table["bwd"], 4096) == "flash"
        finally:
            A._dispatch_table.cache_clear()

    def test_rows_from_winners(self):
        import importlib.util
        import os as _os

        root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "attention_bench", _os.path.join(root, "tools", "attention_bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rows = mod._rows_from_winners(
            [(1024, "ref"), (2048, "ref"), (4096, "flash")]
        )
        assert rows == [[2048, "ref"], [None, "flash"]]
        assert mod._rows_from_winners([]) == []
