"""Data layer tests: splitters, checkpoint, dispatcher state machine,
elastic loader, and master failover (snapshot/recover) — the behaviors the
reference's Go master and WIP data layer only sketched (SURVEY §2 C21/C22).
"""

import threading
import time

import numpy as np
import pytest

from edl_tpu.data import (
    DataCheckpoint,
    DataDispatcher,
    DispatcherClient,
    ElasticDataLoader,
    FileListDataset,
    TxtFileSplitter,
)
from edl_tpu.discovery.registry import Registry
from edl_tpu.store.client import StoreClient
from edl_tpu.store.server import StoreServer


@pytest.fixture()
def data_files(tmp_path):
    files = []
    for i in range(4):
        p = tmp_path / ("part-%d.txt" % i)
        p.write_text("".join("f%d-rec%d\n" % (i, j) for j in range(10)))
        files.append(str(p))
    return files


class TestDataset:
    def test_txt_splitter(self, data_files):
        recs = list(TxtFileSplitter().split(data_files[0]))
        assert recs[0] == (0, b"f0-rec0")
        assert len(recs) == 10

    def test_file_list_dataset(self, tmp_path, data_files):
        list_path = tmp_path / "files.txt"
        list_path.write_text("\n".join(data_files) + "\n")
        ds = FileListDataset.from_file_list(str(list_path), TxtFileSplitter())
        assert len(ds) == 4
        assert list(ds.read_file(1, start_record=8)) == [
            (8, b"f1-rec8"),
            (9, b"f1-rec9"),
        ]


class TestDataCheckpoint:
    def test_roundtrip_and_progress(self):
        ck = DataCheckpoint(epoch=3)
        ck.record_progress(0, 128)
        ck.file_done(1)
        ck2 = DataCheckpoint.from_json(ck.to_json())
        assert ck2.epoch == 3
        assert ck2.start_offset(0) == 128
        assert ck2.is_file_done(1)
        ck2.next_epoch()
        assert ck2.epoch == 4 and ck2.start_offset(0) == 0


class TestDispatcher:
    def test_happy_path(self, data_files):
        disp = DataDispatcher(task_timeout=5.0).start()
        try:
            client = DispatcherClient(disp.endpoint, "w0")
            assert client.add_dataset(data_files) == 4
            seen = []
            while True:
                resp = client.get_task()
                if resp.get("epoch_done"):
                    break
                assert "task" in resp
                seen.append(resp["task"]["path"])
                client.task_done(resp["task"]["id"])
            assert sorted(seen) == sorted(data_files)
            state = client.state()
            assert state["done"] == 4 and state["todo"] == 0
            # next epoch refills
            assert client.new_epoch(1)
            assert client.state()["todo"] == 4
            client.close()
        finally:
            disp.stop()

    def test_timeout_requeues_with_offset(self, data_files):
        disp = DataDispatcher(task_timeout=0.3, failure_max=3).start()
        try:
            w0 = DispatcherClient(disp.endpoint, "w0")
            w0.add_dataset(data_files[:1])
            resp = w0.get_task()
            task_id = resp["task"]["id"]
            w0.report(task_id, 7)  # progress heartbeat
            time.sleep(1.0)  # let the deadline expire
            # another worker now gets the same file, resuming at record 7
            w1 = DispatcherClient(disp.endpoint, "w1")
            resp2 = w1.get_task()
            assert resp2["task"]["id"] == task_id
            assert resp2["task"]["start_record"] == 7
            # the late ack from the timed-out worker is refused
            assert not w0.task_done(task_id)
            assert w1.task_done(task_id)
            w0.close()
            w1.close()
        finally:
            disp.stop()

    def test_failure_max_drops_task(self, data_files):
        disp = DataDispatcher(task_timeout=5.0, failure_max=2).start()
        try:
            c = DispatcherClient(disp.endpoint, "w0")
            c.add_dataset(data_files[:1])
            for _ in range(2):
                resp = c.get_task()
                c.task_failed(resp["task"]["id"])
            resp = c.get_task()
            assert resp.get("epoch_done")
            assert c.state()["failed"] == 1
            c.close()
        finally:
            disp.stop()

    def test_snapshot_recover(self, data_files):
        store = StoreServer(port=0).start()
        sc = StoreClient(store.endpoint)
        registry = Registry(sc, "job-ds")
        try:
            disp = DataDispatcher(task_timeout=60.0, registry=registry).start()
            c = DispatcherClient(disp.endpoint, "w0")
            c.add_dataset(data_files)
            resp = c.get_task()
            c.task_done(resp["task"]["id"])
            in_flight = c.get_task()["task"]["id"]  # pending at crash time
            c.close()
            disp.stop()  # "crash"

            disp2 = DataDispatcher(task_timeout=60.0, registry=registry).start()
            c2 = DispatcherClient(disp2.endpoint, "w1")
            state = c2.state()
            # 1 done survives; the pending task is back in todo
            assert state["done"] == 1
            assert state["todo"] == 3
            ids = []
            while True:
                resp = c2.get_task()
                if resp.get("epoch_done"):
                    break
                ids.append(resp["task"]["id"])
                c2.task_done(resp["task"]["id"])
            assert in_flight in ids
            c2.close()
            disp2.stop()
        finally:
            sc.close()
            store.stop()

    def test_mid_epoch_kill_resumes_from_reported_cursor(
        self, data_files, monkeypatch
    ):
        """The cursor-snapshot cadence: reported record offsets are
        flushed to the store by the timeout loop, so a dispatcher
        killed mid-epoch resumes every pending file from its last
        REPORTED cursor instead of replaying it from the start."""
        import json
        import time as _time

        monkeypatch.setenv("EDL_DATA_SNAPSHOT_EVERY", "0.1")
        store = StoreServer(port=0).start()
        sc = StoreClient(store.endpoint)
        registry = Registry(sc, "job-ds-cursor")
        try:
            # task_timeout 2.0 -> timeout-loop tick every 0.5s
            disp = DataDispatcher(task_timeout=2.0, registry=registry).start()
            c = DispatcherClient(disp.endpoint, "w0")
            c.add_dataset(data_files)
            task = c.get_task()["task"]
            assert task["start_record"] == 0
            c.report(task["id"], 512)  # mid-file progress heartbeat
            # wait for the cadence flush (tick 0.5s + margin)
            deadline = _time.time() + 5.0
            flushed = False
            while _time.time() < deadline and not flushed:
                meta = registry.get_server("data_master", "state")
                if meta is not None:
                    state = json.loads(meta.value.decode())
                    flushed = any(
                        t.get("next_record") == 512
                        for t in state.get("requeue", [])
                    )
                _time.sleep(0.1)
            assert flushed, "reported cursor never snapshotted"
            c.close()
            disp.stop()  # mid-epoch "kill" — no clean handoff

            disp2 = DataDispatcher(task_timeout=2.0, registry=registry).start()
            c2 = DispatcherClient(disp2.endpoint, "w1")
            # the killed worker's in-flight file comes back FIRST (the
            # requeue preserves offsets) — find it and check the cursor
            starts = {}
            while True:
                resp = c2.get_task()
                if resp.get("epoch_done"):
                    break
                t = resp["task"]
                starts[t["path"]] = t["start_record"]
                c2.task_done(t["id"])
            assert starts[task["path"]] == 512, starts
            c2.close()
            disp2.stop()
        finally:
            sc.close()
            store.stop()


class TestElasticLoader:
    def test_two_workers_cover_everything(self, data_files):
        disp = DataDispatcher(task_timeout=10.0).start()
        try:
            boot = DispatcherClient(disp.endpoint, "boot")
            boot.add_dataset(data_files)
            boot.close()
            records, lock = [], threading.Lock()

            def run(worker_id):
                client = DispatcherClient(disp.endpoint, worker_id)
                loader = ElasticDataLoader(
                    client, TxtFileSplitter(), report_every=3
                )
                for item in loader.epoch():
                    with lock:
                        records.append(item[2])
                client.close()

            threads = [
                threading.Thread(target=run, args=("w%d" % i,)) for i in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(records) == 40
            assert len(set(records)) == 40  # exactly-once
        finally:
            disp.stop()


class TestPrefetch:
    """Fixed-shape batching + device prefetch (edl_tpu/data/prefetch.py)."""

    def test_batched_pads_final_and_masks(self):
        from edl_tpu.data import batched

        recs = [(np.full((3,), i, np.float32), i) for i in range(10)]
        out = list(batched(recs, 4))
        assert len(out) == 3
        (xb, yb), mask = out[-1]
        assert xb.shape == (4, 3) and yb.shape == (4,)
        assert mask.tolist() == [True, True, False, False]
        # padded rows repeat the last real record
        assert yb.tolist() == [8, 9, 9, 9]
        (xb0, yb0), mask0 = out[0]
        assert mask0.all() and yb0.tolist() == [0, 1, 2, 3]

    def test_batched_drop_remainder(self):
        from edl_tpu.data import batched

        out = list(batched(range(10), 4, drop_remainder=True))
        assert len(out) == 2 and all(m.all() for _, m in out)

    def test_prefetch_to_device_order_and_values(self):
        import jax

        from edl_tpu.data import batched, prefetch_to_device

        recs = [np.full((2,), i, np.float32) for i in range(9)]
        src = (b for b, _ in batched(recs, 2, drop_remainder=True))
        got = list(prefetch_to_device(src, depth=2))
        assert len(got) == 4
        for i, b in enumerate(got):
            assert isinstance(b, jax.Array)
            np.testing.assert_array_equal(
                np.asarray(b), [[2 * i] * 2, [2 * i + 1] * 2]
            )

    def test_prefetch_with_dp_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from edl_tpu.data import prefetch_to_device
        from edl_tpu.parallel import make_mesh

        mesh = make_mesh()
        sh = NamedSharding(mesh, P("dp"))
        src = [np.arange(16, dtype=np.float32).reshape(8, 2)] * 3
        got = list(prefetch_to_device(iter(src), depth=2, sharding=sh))
        assert len(got) == 3
        assert got[0].sharding == sh
        np.testing.assert_array_equal(np.asarray(got[0]), src[0])

    def test_prefetch_propagates_source_error(self):
        from edl_tpu.data import prefetch_to_device

        def bad():
            yield np.zeros((2,))
            raise RuntimeError("boom")

        it = prefetch_to_device(bad(), depth=1)
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            list(it)

    def test_prefetch_abandoned_early_stops_feeder(self):
        """Breaking out of the loop must unblock + stop the feeder thread
        (it would otherwise pin `depth` staged batches forever)."""
        import threading as _th
        import time as _time

        from edl_tpu.data import prefetch_to_device

        src = (np.full((2,), i, np.float32) for i in range(1000))
        it = prefetch_to_device(src, depth=2)
        next(it)
        it.close()  # what a `break` in a for-loop does via GC/scope exit
        deadline = _time.time() + 5
        while _time.time() < deadline:
            if not any(
                t.name == "edl-prefetch" and t.is_alive()
                for t in _th.enumerate()
            ):
                break
            _time.sleep(0.05)
        assert not any(
            t.name == "edl-prefetch" and t.is_alive() for t in _th.enumerate()
        )
