"""ElasticTrainer worker for harness-churn tests: high-level API version
of toy_worker.py. Trains an MLP through ElasticTrainer with per-epoch
checkpointing; drops per-epoch markers so the test can prove which
epochs ran in which (stage, world) incarnation and that a respawned
incarnation RESUMED rather than restarted."""

import os
import time

import numpy as np
import optax

from edl_tpu.models import MLP
from edl_tpu.train import ElasticTrainer, mse_loss

out_dir = os.environ["TEST_OUT_DIR"]
stage = os.environ.get("EDL_STAGE", "nostage")
rank = os.environ.get("EDL_WORKER_RANK", "0")
world = os.environ.get("EDL_NUM_WORKERS", "1")
pause = float(os.environ.get("TEST_EPOCH_PAUSE", "0.5"))


def records(epoch):
    rs = np.random.RandomState(100 + epoch)
    w = np.linspace(-1, 1, 8)[:, None].astype(np.float32)
    for _ in range(64):
        x = rs.randn(8).astype(np.float32)
        yield x, (x @ w).astype(np.float32)


def mark(epoch, _metrics):
    name = "ep.%s.%s.%s.%d" % (stage, rank, world, epoch)
    with open(os.path.join(out_dir, name), "w") as f:
        f.write("1")
    time.sleep(pause)  # stretch the epoch so churn lands mid-training


use_fsdp = os.environ.get("TEST_FSDP") == "1"
trainer = ElasticTrainer(
    MLP(hidden=(16,), features=1),
    optax.sgd(0.05),
    mse_loss,
    # numpy, NOT jnp: device arrays before fit() would initialise
    # the backend and break jax.distributed in multi-worker stages
    sample_input=np.zeros((8, 8), np.float32),
    batch_size=8,
    # fsdp mode: params sharded over the fsdp axis of the (possibly
    # multi-process) mesh — exercises device_put_global's cross-process
    # make_array path for non-replicated specs. fsdp=2 divides the device
    # count even at world=1 because the test env's inherited XLA flag
    # gives every process 8 virtual CPU devices.
    mesh_axes={"dp": -1, "fsdp": 2} if use_fsdp else None,
    fsdp=use_fsdp,
    ckpt_dir=os.environ["EDL_CKPT_PATH"],
    log=False,
)
state = trainer.fit(records, epochs=6, on_epoch_end=mark)
with open(os.path.join(out_dir, "done.%s.%s" % (stage, rank)), "w") as f:
    f.write(str(int(state.step)))
