"""Teacher model fetch (C18 parity): HTTP + checksum cache, end-to-end.

Serves a real artifact from a local ``http.server`` (no egress), fetches
it through :func:`edl_tpu.distill.fetch_model`, and checks the checksum
cache short-circuits a second fetch even after the origin disappears —
the property an elastic teacher fleet actually needs (restarts are free).
"""

import hashlib
import http.server
import os
import threading

import pytest

from edl_tpu.distill import FetchError, fetch_model


@pytest.fixture()
def http_dir(tmp_path):
    root = tmp_path / "origin"
    root.mkdir()
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
        *a, directory=str(root), **kw
    )
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield root, "http://127.0.0.1:%d" % srv.server_address[1]
    finally:
        srv.shutdown()
        srv.server_close()


def test_http_fetch_verify_and_cache(http_dir, tmp_path):
    root, base = http_dir
    blob = os.urandom(4096)
    (root / "teacher.msgpack").write_bytes(blob)
    sha = hashlib.sha256(blob).hexdigest()
    cache = str(tmp_path / "cache")

    got = fetch_model(
        base + "/teacher.msgpack", sha256=sha, cache_dir=cache
    )
    assert open(got, "rb").read() == blob

    # origin gone: the checksum-keyed cache must still serve it
    (root / "teacher.msgpack").unlink()
    again = fetch_model(
        base + "/teacher.msgpack", sha256=sha, cache_dir=cache
    )
    assert again == got


def test_http_checksum_mismatch_rejected(http_dir, tmp_path):
    root, base = http_dir
    (root / "bad.bin").write_bytes(b"not the model")
    with pytest.raises(FetchError, match="checksum"):
        fetch_model(
            base + "/bad.bin", sha256="0" * 64,
            cache_dir=str(tmp_path / "cache"),
        )
    # a corrupt artifact must never be left in the cache
    for dirpath, _dirs, files in os.walk(str(tmp_path / "cache")):
        assert not files, files


def test_corrupted_cache_refetches(http_dir, tmp_path):
    root, base = http_dir
    blob = b"x" * 1000
    (root / "m.bin").write_bytes(blob)
    sha = hashlib.sha256(blob).hexdigest()
    cache = str(tmp_path / "cache")
    got = fetch_model(base + "/m.bin", sha256=sha, cache_dir=cache)
    with open(got, "wb") as f:
        f.write(b"corrupted")  # e.g. torn disk write
    again = fetch_model(base + "/m.bin", sha256=sha, cache_dir=cache)
    assert open(again, "rb").read() == blob


def test_local_path_verified_in_place(tmp_path):
    p = tmp_path / "local.bin"
    p.write_bytes(b"local artifact")
    sha = hashlib.sha256(b"local artifact").hexdigest()
    assert fetch_model(str(p), sha256=sha) == str(p)
    assert fetch_model("file://" + str(p)) == str(p)
    with pytest.raises(FetchError, match="checksum"):
        fetch_model(str(p), sha256="0" * 64)
    with pytest.raises(FetchError, match="does not exist"):
        fetch_model(str(tmp_path / "missing.bin"))


def test_unsupported_scheme_and_env(tmp_path, monkeypatch):
    with pytest.raises(FetchError, match="unsupported scheme"):
        fetch_model("ftp://host/x")
    from edl_tpu.distill import fetch_from_env

    monkeypatch.delenv("EDL_DISTILL_MODEL_URI", raising=False)
    assert fetch_from_env() is None
    p = tmp_path / "env.bin"
    p.write_bytes(b"abc")
    monkeypatch.setenv("EDL_DISTILL_MODEL_URI", str(p))
    assert fetch_from_env() == str(p)
