"""The store consistency checker (edl_tpu.chaos.consistency): synthetic
op-tape histories for every violation class the checker claims to catch,
the forgiveness rules (indeterminate writes, resync markers, pinned
reads, domain scoping), the chaos invariants over its report, and one
live churn run against a real primary+standby pair."""

import time

import pytest

import edl_tpu.chaos.consistency as cons
import edl_tpu.chaos.invariants as inv
from edl_tpu.chaos.consistency import ConsistencyChurn, check_history
from edl_tpu.obs import events as obs_events
from edl_tpu.store.client import StoreClient
from edl_tpu.store.server import StoreServer


# ---------------------------------------------------------------------------
# synthetic tape builders — plain dicts in the _OpTape wire shape
# ---------------------------------------------------------------------------

_SEQ = {"n": 0}


def _op(op, cid="s1", ok=True, **fields):
    _SEQ["n"] += 1
    doc = {
        "event": "store_op", "cid": cid, "cli": 1, "seq": _SEQ["n"],
        "op": op, "t0": float(_SEQ["n"]), "served": "leader", "ok": ok,
    }
    doc.update(fields)
    return doc


def put(key, rev, digest, cid="s1"):
    return _op("put", cid=cid, k=key, d=digest, r=rev)


def put_fail(key, digest, cid="s1"):
    return _op("put", cid=cid, ok=False, k=key, d=digest, err="EdlConnectionError")


def delete(key, rev, cid="s1"):
    return _op("del", cid=cid, k=key, r=rev, nd=1)


def get(key, asof, mr, digest, cid="s1", **fields):
    return _op("get", cid=cid, k=key, r=asof, mr=mr, d=digest, **fields)


def get_absent(key, asof, cid="s1"):
    return _op("get", cid=cid, k=key, r=asof, mr=0, d=None)


def rng(prefix, asof, rows, cid="s1", trunc=False):
    doc = _op("range", cid=cid, p=prefix, r=asof, n=len(rows), rows=rows)
    if trunc:
        doc["trunc"] = True
    return doc


def watch_start(wid, prefix, r0, cid="s1"):
    return {
        "event": "store_watch", "cid": cid, "cli": 1, "wid": wid,
        "p": prefix, "r0": r0,
    }


def watch_ev(wid, evs, cid="s1"):
    return {
        "event": "store_watch_ev", "cid": cid, "cli": 1, "wid": wid,
        "evs": evs,
    }


class TestCheckerStaleReads:
    """Check 1: every unpinned read must return the newest acked write
    at-or-below its answering revision."""

    def test_consistent_history_is_green(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            get("/cp/a", 2, 2, "d2"),
            rng("/cp/", 2, [["/cp/a", 2, "d2"]]),
        ])
        assert report.ok
        assert report.reads == 2 and report.writes_acked == 2

    def test_old_revision_is_stale_read(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            get("/cp/a", 2, 1, "d1"),  # answered asof 2 with rev-1 value
        ])
        assert [v["check"] for v in report.violations] == ["stale-read"]

    def test_acked_write_invisible_is_stale_read(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            get_absent("/cp/a", 1),
        ])
        assert [v["check"] for v in report.violations] == ["stale-read"]

    def test_tombstoned_revision_returned_is_stale_read(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            delete("/cp/a", 2),
            get("/cp/a", 2, 2, None),  # returned the delete's own rev
        ])
        assert [v["check"] for v in report.violations] == ["stale-read"]

    def test_digest_mismatch_is_value_mismatch(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            get("/cp/a", 1, 1, "dX"),
        ])
        assert [v["check"] for v in report.violations] == ["value-mismatch"]

    def test_range_coverage_catches_lost_key(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/b", 2, "d2"),
            rng("/cp/", 2, [["/cp/a", 1, "d1"]]),  # b missing, not trunc
        ])
        assert [v["check"] for v in report.violations] == ["stale-read"]
        assert report.violations[0]["key"] == "/cp/b"

    def test_truncated_range_skips_coverage(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/b", 2, "d2"),
            rng("/cp/", 2, [["/cp/a", 1, "d1"]], trunc=True),
        ])
        assert report.ok

    def test_deleted_key_absent_is_fine(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            delete("/cp/a", 2),
            get_absent("/cp/a", 2),
        ])
        assert report.ok

    def test_indeterminate_write_never_required(self):
        # a failed put may or may not have landed: reading the old value
        # AND reading the new value are both legal
        base = [put("/cp/a", 1, "d1"), put_fail("/cp/a", "d2")]
        old = check_history(base + [get("/cp/a", 1, 1, "d1")])
        new = check_history(base + [get("/cp/a", 2, 2, "d2")])
        assert old.ok and new.ok
        assert old.writes_indeterminate == 1
        assert new.unverified == 0  # rev-2 get judged against... nothing
        # above asof 1 there is no acked write, so the rev-2 observation
        # is unverifiable, never a violation
        assert check_history(
            base + [get("/cp/a", 2, 2, "d2")]
        ).violations == []

    def test_pinned_reads_are_exempt(self):
        # an explicit rev= pin ASKS for history; never judged stale
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            get("/cp/a", 2, 1, "d1", pin=1),
        ])
        assert report.ok

    def test_domain_scoping_ignores_foreign_keys(self):
        # an untaped writer owns /job/ — a "stale" read there must not
        # fabricate a verdict, and default prefix ignores it entirely
        report = check_history([
            put("/job/a", 5, "d5"),
            get("/job/a", 5, 3, "d3"),
        ])
        assert report.ops == 0 and report.ok


class TestCheckerSessionMonotonicity:
    """Check 2: one session's view of history never rewinds."""

    def test_answer_below_session_floor(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            get("/cp/a", 5, 1, "d1", cid="s7"),
            get("/cp/a", 3, 1, "d1", cid="s7"),  # rev 3 after seeing 5
        ])
        assert "non-monotonic-session" in [
            v["check"] for v in report.violations
        ]

    def test_key_mod_rev_regression(self):
        # the red drill's signature: same session sees rev 4 then rev 3
        report = check_history([
            put("/cp/x", 3, "dA", cid="w"),
            get("/cp/x", 4, 4, "dB", cid="s7"),
            get("/cp/x", 6, 3, "dA", cid="s7"),
        ])
        assert any(
            v["check"] == "non-monotonic-session"
            and "regressed from rev 4 to 3" in v["detail"]
            for v in report.violations
        )

    def test_key_vanished_without_delete(self):
        report = check_history([
            put("/cp/a", 2, "d2", cid="s7"),
            get("/cp/a", 2, 2, "d2", cid="s7"),
            get_absent("/cp/a", 3, cid="s7"),
        ])
        assert any(
            v["check"] == "non-monotonic-session"
            and "vanished" in v["detail"]
            for v in report.violations
        )

    def test_key_vanished_with_acked_delete_is_fine(self):
        report = check_history([
            put("/cp/a", 2, "d2", cid="s7"),
            get("/cp/a", 2, 2, "d2", cid="s7"),
            delete("/cp/a", 3, cid="s7"),
            get_absent("/cp/a", 3, cid="s7"),
        ])
        assert report.ok

    def test_sessions_are_independent(self):
        # two sessions at different revisions: no cross-session floor
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            get("/cp/a", 2, 2, "d2", cid="fast"),
            get("/cp/a", 1, 1, "d1", cid="slow"),
        ])
        assert report.ok
        assert report.sessions == 3  # writer + fast + slow


class TestCheckerWatch:
    """Check 3: per-watch deliveries are duplicate-free, ordered, and
    gap-free inside the delivered window; resync forgives its gap."""

    def test_gap_free_watch_is_green(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [["put", "/cp/a", 1], ["put", "/cp/a", 2]]),
        ])
        assert report.ok and report.watch_deliveries == 2

    def test_missing_middle_revision_is_gap(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            put("/cp/a", 3, "d3"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [["put", "/cp/a", 1], ["put", "/cp/a", 3]]),
        ])
        assert [v["check"] for v in report.violations] == ["watch-gap"]

    def test_write_after_last_delivery_not_judged(self):
        # rev 3 may still be in flight when the tape ends
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            put("/cp/a", 3, "d3"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [["put", "/cp/a", 1], ["put", "/cp/a", 2]]),
        ])
        assert report.ok

    def test_duplicate_and_reorder(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [
                ["put", "/cp/a", 2], ["put", "/cp/a", 1],
                ["put", "/cp/a", 2],
            ]),
        ])
        checks = sorted(v["check"] for v in report.violations)
        assert checks == ["watch-duplicate", "watch-order"]

    def test_resync_forgives_the_gap_it_announces(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            put("/cp/a", 3, "d3"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [["resync", "/cp/", 2], ["put", "/cp/a", 3]]),
        ])
        assert report.ok

    def test_start_rev_floor_respected(self):
        # deliveries begin above r0: revs 1..2 are before the watch
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            put("/cp/a", 3, "d3"),
            watch_start(1, "/cp/", 2),
            watch_ev(1, [["put", "/cp/a", 3]]),
        ])
        assert report.ok

    def test_watches_keyed_per_session(self):
        # same wid on two sessions stays two watches (client-local ids)
        report = check_history([
            put("/cp/a", 1, "d1"),
            watch_start(1, "/cp/", 0, cid="s1"),
            watch_start(1, "/cp/", 0, cid="s2"),
            watch_ev(1, [["put", "/cp/a", 1]], cid="s1"),
            watch_ev(1, [["put", "/cp/a", 1]], cid="s2"),
        ])
        assert report.ok and report.watch_deliveries == 2


class TestConsistencyInvariants:
    """The chaos invariants over a report: green needs a NON-VACUOUS
    history; the red drill's invariant wants violations."""

    def _green(self):
        return check_history([
            put("/cp/a", 1, "d1"),
            get("/cp/a", 1, 1, "d1"),
            watch_start(1, "/cp/", 0),
            watch_ev(1, [["put", "/cp/a", 1]]),
        ])

    def test_green_report_passes_all(self):
        report = self._green()
        assert inv.no_stale_reads(report).ok
        assert inv.monotonic_session_reads(report).ok
        assert inv.watch_gap_free(report).ok
        assert not inv.consistency_anomaly_reproduced(report).ok

    def test_empty_history_is_vacuous_red(self):
        report = check_history([])
        assert not inv.no_stale_reads(report).ok
        assert not inv.monotonic_session_reads(report).ok
        assert not inv.watch_gap_free(report).ok

    def test_violations_turn_red(self):
        report = check_history([
            put("/cp/a", 1, "d1"),
            put("/cp/a", 2, "d2"),
            get("/cp/a", 2, 1, "d1"),
        ])
        assert not inv.no_stale_reads(report).ok
        assert inv.consistency_anomaly_reproduced(report).ok


class TestChurnLive:
    """One real churn session against a primary+standby pair: the tape
    lands in the flight dir, the checker finds a non-vacuous consistent
    history, and the verdict record is written for the timeline."""

    def test_churn_history_checks_green(self, tmp_path):
        primary = StoreServer(
            host="127.0.0.1", port=0, data_dir=str(tmp_path / "p")
        ).start()
        standby = StoreServer(
            host="127.0.0.1", port=0, data_dir=str(tmp_path / "s"),
            follow=primary.endpoint, priority=1, failover_grace=30.0,
        ).start()
        deadline = time.time() + 15
        while time.time() < deadline and not standby._has_state:
            time.sleep(0.02)
        assert standby._has_state, "standby never bootstrapped"
        flight = str(tmp_path / "flight")
        churn = ConsistencyChurn(
            "%s,%s" % (primary.endpoint, standby.endpoint), flight,
            read_mode="standby",
        )
        try:
            time.sleep(2.0)
        finally:
            churn.stop()
            report = check_history(obs_events.read_segments(flight))
            cons.record_verdict(report, flight)
            primary.stop()
            standby.stop()
        assert report.ok, report.summary()
        assert report.reads > 5 and report.writes_acked > 5
        assert report.watch_deliveries > 5
        assert inv.no_stale_reads(report).ok
        verdicts = [
            e for e in obs_events.read_segments(flight)
            if e.get("event") == cons.VERDICT_EVENT
        ]
        assert len(verdicts) == 1 and verdicts[0]["ok"]


class TestOpTape:
    """The client-side tape itself: records land per completed op with
    the fields the checker keys on, and values are digests, not bytes."""

    def test_tape_records_and_digests(self, tmp_path):
        server = StoreServer(host="127.0.0.1", port=0).start()
        flight = str(tmp_path / "flight")
        client = StoreClient(
            server.endpoint, timeout=5.0, op_tape_dir=flight
        )
        try:
            rev = client.put("/cp/t", b"secret-payload")
            assert client.get("/cp/t") == b"secret-payload"
            client.range("/cp/")
        finally:
            client.close()
            server.stop()
        records = [
            e for e in obs_events.read_segments(flight)
            if e.get("event") == "store_op"
            # the client's connect-time endpoint-discovery range is taped
            # too; only the probe domain matters here
            and (e.get("k") or e.get("p", "")).startswith("/cp/")
        ]
        assert [r["op"] for r in records] == ["put", "get", "range"]
        p, g, r = records
        assert p["ok"] and p["r"] == rev and p["k"] == "/cp/t"
        assert g["mr"] == rev and g["d"] == p["d"]
        assert len(p["d"]) == 12  # md5 digest prefix, never the value
        assert "secret-payload" not in str(records)
        assert r["rows"] == [["/cp/t", rev, p["d"]]]
        assert {rec["cid"] for rec in records} == {p["cid"]}

    def test_untaped_client_records_nothing(self, tmp_path, monkeypatch):
        monkeypatch.delenv("EDL_STORE_OP_TAPE", raising=False)
        server = StoreServer(host="127.0.0.1", port=0).start()
        client = StoreClient(server.endpoint, timeout=5.0)
        try:
            client.put("/cp/t", b"v")
            assert client._tape is None
        finally:
            client.close()
            server.stop()

    def test_failed_op_taped_as_indeterminate(self, tmp_path):
        from edl_tpu.utils.exceptions import EdlStoreError

        server = StoreServer(host="127.0.0.1", port=0).start()
        flight = str(tmp_path / "flight")
        client = StoreClient(
            server.endpoint, timeout=2.0, reconnect=False,
            op_tape_dir=flight,
        )
        try:
            client.put("/cp/t", b"v")
            server.stop()
            with pytest.raises(EdlStoreError):
                client.put("/cp/t", b"w")
        finally:
            client.close()
        fails = [
            e for e in obs_events.read_segments(flight)
            if e.get("event") == "store_op" and not e.get("ok")
        ]
        assert len(fails) == 1
        assert fails[0]["op"] == "put" and fails[0]["err"]
