"""Conformance test: the native C++ master serves the same wire protocol
as the Python DataDispatcher, driven by the same DispatcherClient.

Builds ``native/`` with cmake+ninja on first run (skipped if no
toolchain); then replays the dispatcher behavior suite against the
binary: happy path, timeout re-queue with resume offset, strike-out.
"""

import shutil
import subprocess
import time

import pytest

from edl_tpu.data import DispatcherClient

NATIVE_DIR = __file__.rsplit("/", 2)[0] + "/native"


@pytest.fixture(scope="module")
def master_binary():
    if not (shutil.which("cmake") and shutil.which("ninja")):
        pytest.skip("no native toolchain")
    build = NATIVE_DIR + "/build"
    subprocess.run(
        ["cmake", "-B", build, "-G", "Ninja"],
        cwd=NATIVE_DIR, check=True, capture_output=True,
    )
    subprocess.run(
        ["ninja", "-C", build], cwd=NATIVE_DIR, check=True, capture_output=True
    )
    return build + "/edl_master"


@pytest.fixture()
def master(master_binary, request):
    args = getattr(request, "param", ["--task-timeout", "60"])
    proc = subprocess.Popen(
        [master_binary, "--port", "0", *args],
        stdout=subprocess.PIPE, text=True,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING "), line
    port = int(line.split()[1])
    yield "127.0.0.1:%d" % port
    proc.kill()
    proc.wait()


FILES = ["/data/part-%d" % i for i in range(4)]


class TestNativeMaster:
    def test_happy_path(self, master):
        c = DispatcherClient(master, "w0")
        assert c.add_dataset(FILES) == 4
        seen = []
        while True:
            resp = c.get_task()
            if resp.get("epoch_done"):
                break
            seen.append(resp["task"]["path"])
            assert c.task_done(resp["task"]["id"])
        assert sorted(seen) == sorted(FILES)
        state = c.state()
        assert state["done"] == 4 and state["todo"] == 0
        assert c.new_epoch(1)
        assert not c.new_epoch(1)  # idempotent
        assert c.state()["todo"] == 4
        c.close()

    @pytest.mark.parametrize(
        "master", [["--task-timeout", "0.3"]], indirect=True
    )
    def test_timeout_requeue_and_late_ack(self, master):
        w0 = DispatcherClient(master, "w0")
        w0.add_dataset(FILES[:1])
        task = w0.get_task()["task"]
        assert w0.report(task["id"], 7)
        time.sleep(1.2)
        w1 = DispatcherClient(master, "w1")
        resp = w1.get_task()
        assert resp["task"]["id"] == task["id"]
        assert resp["task"]["start_record"] == 7
        assert not w0.task_done(task["id"])  # late ack refused
        assert w1.task_done(task["id"])
        w0.close()
        w1.close()

    @pytest.mark.parametrize(
        "master", [["--task-timeout", "60", "--failure-max", "2"]], indirect=True
    )
    def test_strike_out(self, master):
        c = DispatcherClient(master, "w0")
        c.add_dataset(FILES[:1])
        for _ in range(2):
            resp = c.get_task()
            assert c.task_failed(resp["task"]["id"])
        assert c.get_task().get("epoch_done")
        assert c.state()["failed"] == 1
        c.close()

    def test_unknown_method_error(self, master):
        c = DispatcherClient(master, "w0")
        with pytest.raises(ConnectionError, match="unknown method"):
            c._call("bogus")
        c.close()

    def test_missing_field_is_error_not_crash(self, master):
        """A request lacking a required field gets a serialized error (like
        the Python twin) instead of null-deref'ing the daemon."""
        c = DispatcherClient(master, "w0")
        for method in ("new_epoch", "task_done", "task_failed", "report"):
            with pytest.raises(ConnectionError, match="missing required"):
                c._call(method)  # no epoch/t/rec params
        # daemon survived all four malformed requests
        assert c.state()["files"] == 0
        c.close()

    def test_large_dataset_over_array16_limit(self, master):
        """>65535 files forces array32/str payloads through the codec in
        both directions; a 16-bit-only packer would desync the stream."""
        c = DispatcherClient(master, "w0", timeout=60.0)
        many = ["/data/part-%06d" % i for i in range(70_000)]
        assert c.add_dataset(many) == 70_000
        assert c.state()["todo"] == 70_000
        resp = c.get_task()
        assert resp["task"]["path"] in ("/data/part-000000", many[0])
        c.close()


def test_msgpack_selftest(master_binary):
    """Native codec round-trips at every size-class boundary (str32/
    array32/map32 included)."""
    build = NATIVE_DIR + "/build"
    out = subprocess.run(
        [build + "/msgpack_selftest"], capture_output=True, text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "OK"


class TestLoaderAgainstNativeMaster:
    """Full worker-side loop (ElasticDataLoader + TxtFileSplitter) pulling
    from the NATIVE master: every record of every file consumed exactly
    once per epoch across two workers — the same guarantee the Python
    dispatcher suite proves, now on the C++ twin."""

    def test_exactly_once_two_workers(self, master, tmp_path):
        from edl_tpu.data import ElasticDataLoader, TxtFileSplitter

        files = []
        want = set()
        for i in range(3):
            p = tmp_path / ("part-%d.txt" % i)
            lines = ["f%d-rec%d" % (i, j) for j in range(5 + i)]
            p.write_text("".join(l + "\n" for l in lines))
            files.append(str(p))
            want.update(lines)

        c0 = DispatcherClient(master, "w0")
        assert c0.add_dataset(files) == 3

        got = []

        def drain(worker):
            client = DispatcherClient(master, worker)
            loader = ElasticDataLoader(client, TxtFileSplitter())
            for _file_idx, _rec_idx, record in loader.epoch():
                got.append(record.decode())
            client.close()

        import threading

        threads = [
            threading.Thread(target=drain, args=("w%d" % i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert sorted(got) == sorted(want), (len(got), len(want))
        assert c0.state()["done"] == 3
        c0.close()


class TestNativeMasterFailover:
    """Master-death failover on the C++ twin: snapshot progress from a
    dying master, boot a fresh one, restore, and resume mid-file — the
    behavior the reference's Go master only sketched (etcd Save/Load,
    pkg/master/etcd_client.go:99-161)."""

    def test_progress_snapshot_restores_into_fresh_master(
        self, master_binary, tmp_path
    ):
        def boot():
            proc = subprocess.Popen(
                [master_binary, "--port", "0", "--task-timeout", "60"],
                stdout=subprocess.PIPE, text=True,
            )
            line = proc.stdout.readline().strip()
            return proc, "127.0.0.1:%d" % int(line.split()[1])

        m1, ep1 = boot()
        try:
            c = DispatcherClient(ep1, "w0")
            assert c.add_dataset(["/f0", "/f1", "/f2"]) == 3
            # finish f-first, report partway through the second
            t1 = c.get_task()["task"]
            c.task_done(t1["id"])
            t2 = c.get_task()["task"]
            c.report(t2["id"], 7)
            snap = c.progress()
            assert sorted(snap["done"]) == [t1["file_idx"]]
            assert snap["offsets"] == {t2["file_idx"]: 7}
            c.close()
        finally:
            m1.kill()
            m1.wait()

        m2, ep2 = boot()
        try:
            c2 = DispatcherClient(ep2, "w1")
            assert c2.add_dataset(["/f0", "/f1", "/f2"]) == 3
            assert c2.set_progress(snap["epoch"], snap["offsets"], snap["done"])
            # the finished file never re-dispatches; the partial file
            # resumes at record 7; the untouched file starts at 0
            starts = {}
            while True:
                resp = c2.get_task()
                if resp.get("epoch_done"):
                    break
                task = resp["task"]
                starts[task["file_idx"]] = task["start_record"]
                c2.task_done(task["id"])
            assert t1["file_idx"] not in starts
            assert starts[t2["file_idx"]] == 7
            assert len(starts) == 2 and min(starts.values()) == 0
            assert c2.state()["done"] == 3
            c2.close()
        finally:
            m2.kill()
            m2.wait()
