"""Serving resilience plane (tier-1, no jax).

The construction-level guarantees: retry storms are impossible (the
fraction-of-primaries budget bounds secondaries no matter how the fleet
fails), hedges are budget-capped and metered, circuit breakers walk the
CLOSED/OPEN/HALF_OPEN machine with single-probe gating, and the
teacher-side admission test sheds with an explicit
:class:`EdlOverloadError` carrying the advertised queue state.

The ``serve_slo --smoke`` lane keeps the closed-loop bench harness from
rotting (same contract as ``store_bench --smoke``), and the checked-in
bench results are shape-guarded so a regenerated file cannot silently
drop the headline rollups ``edl-report`` trends.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

RESULTS = REPO / "bench_results" / "serve_slo_cpu_r19.json"

from edl_tpu.distill.resilience import (
    BreakerBoard,
    HedgePolicy,
    RetryBudget,
    hedged_call,
)
from edl_tpu.distill.serving import (
    EchoPredictBackend,
    PredictClient,
    PredictServer,
)
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.exceptions import EdlOverloadError


# -- retry budget: storms impossible by construction --------------------------


class TestRetryBudget:
    def test_total_outage_spends_ratio_of_primaries_plus_burst(self):
        """The Tail-at-Scale bound: under a TOTAL outage (every attempt
        fails, every failure wants a retry), secondaries never exceed
        ratio x primaries + burst — the storm is arithmetic, not
        policy, so no failure mode can unleash one."""
        budget = RetryBudget(ratio=0.25, burst=10.0)
        retries = 0
        n = 400
        for _ in range(n):
            budget.note_primary()
            while budget.try_spend():  # outage: retry until denied
                retries += 1
        assert retries <= 0.25 * n + 10.0
        # and the budget is not secretly zero: it spends what it earns
        assert retries >= 0.25 * n - 1

    def test_cold_budget_spends_only_the_burst(self):
        budget = RetryBudget(ratio=0.25, burst=10.0)
        spends = sum(1 for _ in range(100) if budget.try_spend())
        assert spends == 10

    def test_zero_ratio_disables_retries(self):
        budget = RetryBudget(ratio=0.0)
        budget.note_primary()
        assert not budget.try_spend()

    def test_denied_retries_are_metered(self):
        reg = obs_metrics.default_registry()
        counter = reg.get("edl_distill_retry_denied_total")
        before = counter.value()
        budget = RetryBudget(ratio=0.0)
        for _ in range(3):
            assert not budget.try_spend()
        assert counter.value() == before + 3


# -- hedge policy: budget-capped and metered ----------------------------------


class TestHedgePolicy:
    def test_cold_policy_never_hedges(self):
        policy = HedgePolicy(budget_ratio=0.1)
        assert policy.delay_s() is None  # < _MIN_SAMPLES latencies seen

    def test_delay_is_p95_with_floor(self):
        policy = HedgePolicy(budget_ratio=0.1, min_delay_ms=20.0)
        for _ in range(64):
            policy.note_latency(0.001)
        assert policy.delay_s() == pytest.approx(0.020)  # floored
        for _ in range(64):
            policy.note_latency(0.5)
        assert policy.delay_s() >= 0.4  # p95 follows the slow tail

    def test_hedges_capped_at_ratio_of_primaries_and_metered(self):
        """``edl_distill_hedges_total <= ratio x primaries + burst``
        always — the acceptance bound, asserted against the REAL
        counter, with an adversarial caller that wants to hedge every
        single request."""
        reg = obs_metrics.default_registry()
        counter = reg.get("edl_distill_hedges_total")
        before = counter.value()
        policy = HedgePolicy(budget_ratio=0.10, burst=5.0)
        n = 200
        granted = 0
        for _ in range(n):
            policy.note_primary()
            if policy.try_hedge():
                granted += 1
        assert granted <= 0.10 * n + 5.0
        assert granted >= 0.10 * n - 1  # the budget is live, not zero
        assert policy.hedges == granted
        assert counter.value() == before + granted


# -- hedged_call --------------------------------------------------------------


class TestHedgedCall:
    def _policy(self):
        policy = HedgePolicy(budget_ratio=1.0, burst=10.0)
        for _ in range(16):
            policy.note_latency(0.001)
            policy.note_primary()
        return policy

    def test_fast_primary_never_launches_backup(self):
        policy = self._policy()
        launched = []

        def backup_factory():
            launched.append(1)
            return lambda: "backup"

        out, backup_won, abandoned = hedged_call(
            lambda: "primary", 0.25, backup_factory, policy=policy
        )
        assert (out, backup_won, abandoned) == ("primary", False, False)
        assert not launched

    def test_slow_primary_loses_to_backup(self):
        policy = self._policy()
        release = threading.Event()

        def primary():
            release.wait(5.0)
            return "primary"

        try:
            out, backup_won, abandoned = hedged_call(
                primary, 0.02, lambda: (lambda: "backup"), policy=policy
            )
        finally:
            release.set()
        assert (out, backup_won) == ("backup", True)
        assert abandoned  # the primary is still in flight: desynced
        assert policy.wins >= 1

    def test_primary_failure_before_delay_raises(self):
        def primary():
            raise ConnectionError("boom")

        with pytest.raises(ConnectionError):
            hedged_call(
                primary, 0.25, lambda: (lambda: "backup"),
                policy=self._policy(),
            )

    def test_both_failing_raises(self):
        def fail():
            time.sleep(0.01)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            hedged_call(
                fail, 0.001, lambda: fail, policy=self._policy()
            )


# -- circuit breakers ---------------------------------------------------------


class TestBreakerBoard:
    def test_trips_after_consecutive_failures_and_half_open_probes(self):
        opened, closed = [], []
        board = BreakerBoard(
            failures=3, open_s=0.1,
            on_open=opened.append, on_close=closed.append,
        )
        ep = "t:1"
        for _ in range(2):
            board.record_failure(ep)
        assert board.admits(ep)  # 2 < 3: still CLOSED
        board.record_failure(ep)
        assert opened == [ep]
        assert not board.admits(ep)  # OPEN
        time.sleep(0.15)
        assert board.admits(ep)  # HALF_OPEN now
        board.starting(ep)  # THE probe
        assert not board.admits(ep)  # a second request must wait
        board.record_success(ep)
        assert closed == [ep]
        assert board.admits(ep)
        assert board.snapshot()[ep] == "closed"

    def test_failed_probe_reopens(self):
        board = BreakerBoard(failures=1, open_s=0.05)
        ep = "t:2"
        board.record_failure(ep)
        time.sleep(0.1)
        assert board.admits(ep)
        board.starting(ep)
        board.record_failure(ep)  # probe failed
        assert not board.admits(ep)
        assert board.snapshot()[ep] == "open"

    def test_success_resets_the_failure_streak(self):
        board = BreakerBoard(failures=3, open_s=60.0)
        ep = "t:3"
        for _ in range(10):  # never 3 CONSECUTIVE
            board.record_failure(ep)
            board.record_failure(ep)
            board.record_success(ep)
        assert board.admits(ep)

    def test_overloads_count_toward_the_trip(self):
        board = BreakerBoard(failures=2, open_s=60.0)
        ep = "t:4"
        board.record_failure(ep, overload=True)
        board.record_failure(ep, overload=True)
        assert not board.admits(ep)

    def test_open_gauge_tracks_state(self):
        reg = obs_metrics.default_registry()
        gauge = reg.get("edl_distill_breaker_open")
        board = BreakerBoard(failures=1, open_s=0.05)
        ep = "t:gauge"
        board.record_failure(ep)
        assert gauge.value(teacher=ep) == 1.0
        time.sleep(0.1)
        board.admits(ep)  # OPEN -> HALF_OPEN
        board.starting(ep)
        board.record_success(ep)
        assert gauge.value(teacher=ep) == 0.0


# -- teacher-side admission control -------------------------------------------


class _SlowBackend(EchoPredictBackend):
    """Echo with a service-time floor, so the queue can actually fill."""

    def __init__(self, service_s: float) -> None:
        self._service_s = service_s

    def __call__(self, feeds):
        time.sleep(self._service_s)
        return super().__call__(feeds)


class TestAdmissionControl:
    def _feeds(self):
        return {"x": np.ones((2, 4), np.float32)}

    def test_queue_full_sheds_with_advertised_state(self):
        server = PredictServer(
            _SlowBackend(0.2), port=0, queue_limit=1, slo_ms=0
        ).start()
        clients = [PredictClient(server.endpoint) for _ in range(3)]
        sheds, oks, errs = [], [], []

        def call(c):
            try:
                oks.append(c.predict(self._feeds()))
            except EdlOverloadError as exc:
                sheds.append(exc)
            except (ConnectionError, OSError) as exc:  # pragma: no cover
                errs.append(exc)

        try:
            threads = [
                threading.Thread(target=call, args=(c,)) for c in clients
            ]
            for t in threads:
                t.start()
                time.sleep(0.02)  # first in the door gets the slot
            for t in threads:
                t.join(timeout=10.0)
        finally:
            for c in clients:
                c.close()
            server.stop()
        assert not errs
        assert oks, "nobody got served"
        assert sheds, "3 concurrent calls vs queue_limit=1 never shed"
        exc = sheds[0]
        # the refusal carries the backlog the client should weigh
        assert exc.qdepth >= 1
        assert exc.est_wait_ms >= 0.0

    def test_doomed_deadline_is_shed_at_admission(self):
        """Once the EWMA knows a predict costs ~100 ms, a request with a
        5 ms remaining budget must be refused at admission — before the
        backend burns device time on an answer nobody will read."""
        server = PredictServer(
            _SlowBackend(0.1), port=0, queue_limit=8, slo_ms=0
        ).start()
        client = PredictClient(server.endpoint)
        try:
            client.predict(self._feeds())  # seeds the service-time EWMA
            with pytest.raises(EdlOverloadError):
                client.predict(self._feeds(), deadline_s=0.005)
        finally:
            client.close()
            server.stop()

    def test_responses_advertise_queue_state(self):
        server = PredictServer(EchoPredictBackend(), port=0).start()
        client = PredictClient(server.endpoint)
        try:
            client.predict(self._feeds())
            assert client.last_qdepth == 0  # alone in the queue
            assert client.last_wait_ms >= 0.0
        finally:
            client.close()
            server.stop()


# -- the bench harness --------------------------------------------------------


def test_serve_slo_smoke_lane():
    """``serve_slo --smoke``: 2 teachers, a nominal lane and an
    overloaded lane, <20 s — exits 0 only when every request got exactly
    one verdict, the nominal lane mostly served, the overload lane
    actually shed, and hedging stayed inside its budget (the bench's
    own asserts)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "serve_slo.py"), "--smoke"],
        capture_output=True, text=True, timeout=180,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["bench"] == "serve_slo"
    nominal, over = doc["results"][0], doc["results"][-1]
    assert nominal["lane"] == "nominal" and over["lane"] == "overload"
    assert sum(nominal["verdicts"].values()) == nominal["requests"]
    assert over["verdicts"]["shed"] > 0
    # the headline scalars regress.py gates on are present and coherent
    assert doc["serve_qps"] == nominal["serve_qps"] > 0
    assert doc["serve_p99_ms"] == nominal["serve_p99_ms"] > 0
    assert doc["serve_shed_pct"] == nominal["serve_shed_pct"]


def test_checked_in_results_shape():
    """The committed bench results carry both lanes and the headline
    rollups: nominal goodput ~= offered load (the fleet keeps up), the
    overload lane shed a real fraction while holding goodput, and zero
    requests were lost without a verdict in either lane."""
    doc = json.loads(RESULTS.read_text())
    assert doc["bench"] == "serve_slo"
    lanes = [r["lane"] for r in doc["results"]]
    assert lanes == ["nominal", "overload"]
    nominal, over = doc["results"]
    for lane in (nominal, over):
        assert sum(lane["verdicts"].values()) == lane["requests"]
    assert nominal["serve_qps"] >= 0.9 * doc["config"]["qps"]
    assert nominal["serve_p99_ms"] <= doc["config"]["slo_ms"]
    assert over["verdicts"]["shed"] > 0
    assert over["serve_qps"] > 0  # goodput held under overload
    for key in (
        "serve_qps", "serve_p50_ms", "serve_p99_ms",
        "serve_shed_pct", "serve_hedge_ratio",
        "overload_goodput_qps", "overload_shed_pct",
    ):
        assert key in doc, key
