"""Hyper-parameter re-adjustment on elastic resize.

The reference sketches this API in its aspirational test
(python/edl/tests/unittests/test_train.py:28-67:
``state.register_adjust_function``) and its README promises "adjust
hyper-parameters" on world-size change (reference README.md:96-151). Here
it is a small registry of callbacks invoked at every stage start with the
restored status and the new worker env.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from edl_tpu.checkpoint.manager import TrainStatus

AdjustFn = Callable[[Optional[TrainStatus], int], Dict[str, Any]]


class AdjustRegistry:
    """Collect adjust callbacks; merge their hyper-parameter overrides.

    Each callback gets ``(restored_status_or_None, new_world_size)`` and
    returns a dict of overrides; later registrations win on key conflicts.
    """

    def __init__(self) -> None:
        self._fns: List[AdjustFn] = []

    def register(self, fn: AdjustFn) -> AdjustFn:
        self._fns.append(fn)
        return fn

    def resolve(
        self, status: Optional[TrainStatus], world_size: int
    ) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for fn in self._fns:
            out.update(fn(status, world_size) or {})
        return out


def linear_scaled_lr(base_lr: float, base_world_size: int) -> AdjustFn:
    """Linear-scaling rule: lr grows with world size (Goyal et al. 2017) —
    the canonical adjustment the reference's elastic resize calls for."""

    def adjust(status: Optional[TrainStatus], world_size: int) -> Dict[str, Any]:
        return {"lr": base_lr * world_size / base_world_size}

    return adjust
