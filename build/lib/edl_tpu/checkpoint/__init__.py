from edl_tpu.checkpoint.manager import (
    CheckpointManager,
    TrainStatus,
    abstract_like,
)
from edl_tpu.checkpoint.adjust import AdjustRegistry, linear_scaled_lr

__all__ = [
    "CheckpointManager",
    "TrainStatus",
    "abstract_like",
    "AdjustRegistry",
    "linear_scaled_lr",
]
