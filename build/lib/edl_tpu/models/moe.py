"""Mixture-of-Experts layers: expert parallelism over the ``ep`` mesh axis.

Net-new versus the reference (no MoE/expert parallelism anywhere in its
tree — SURVEY §2 parallelism inventory), built the TPU-compiler way: the
classic dispatch/combine **einsum formulation** (Mesh-TensorFlow / GShard
lineage) instead of manual all-to-all calls. Expert weights carry a
leading ``[E, ...]`` axis sharded over ``ep``; tokens are dp-sharded;
the dispatch einsum contracts token and expert axes, so GSPMD inserts
the all-to-alls over ICI itself — no hand-written collectives, static
shapes throughout (capacity-bounded routing, drops past capacity).

Switch-style top-1 routing (Fedus et al.) by default, or GShard-style
top-2 (``top_k=2``: renormalized combine weights, choice-major capacity
queues so 1st choices claim slots before any 2nd choice), with the
standard auxiliary load-balancing loss surfaced through flax's ``sow``
into the ``"losses"`` collection — ``make_train_step(aux_losses=True)``
adds them to the objective.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class SwitchMoE(nn.Module):
    """Top-1 routed expert FFN bank (drop-past-capacity, static shapes).

    Input/output: ``[B, S, D]``. Expert weights: ``[E, ...]`` — shard the
    leading axis over ``ep`` (see ``MOE_EP_RULES``).
    """

    num_experts: int = 8
    d_ff: int = 2048
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    top_k: int = 1  # 1 = Switch routing; 2 = GShard-style top-2
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        e, k = self.num_experts, self.top_k
        capacity = max(1, int(self.capacity_factor * k * s / e))

        # -- routing (fp32 for numerics) --------------------------------
        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))                      # [B, S, E]
        probs = jax.nn.softmax(gate_logits, axis=-1)
        topk_prob, topk_idx = jax.lax.top_k(probs, k)  # [B, S, k]
        if k > 1:
            # renormalize over the selected experts (GShard combine
            # weights). NOT at k=1: Switch scales by the raw gate prob
            # (y = p_i(x) E_i(x)) — renormalizing would make the combine
            # weight a constant 1.0 and cut the router's task gradient.
            topk_prob = topk_prob / jnp.sum(topk_prob, axis=-1, keepdims=True)
        oh_k = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [B,S,k,E]

        # queue position per expert, CHOICE-MAJOR (GShard: all 1st choices
        # claim capacity before any 2nd choice), then drop past capacity
        oh_cm = jnp.transpose(oh_k, (0, 2, 1, 3)).reshape(b, k * s, e)
        pos_cm = jnp.cumsum(oh_cm, axis=1) * oh_cm    # [B, k*S, E], 1-based
        pos = jnp.transpose(
            pos_cm.reshape(b, k, s, e), (0, 2, 1, 3)
        )                                              # [B, S, k, E]
        keep = (pos > 0) & (pos <= capacity)
        pos0 = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)

        # dispatch tensor [B, S, E, C]: sum the per-choice slot one-hots
        dispatch_k = (
            keep[..., None]
            * jax.nn.one_hot(pos0, capacity, dtype=jnp.float32)
        )                                              # [B, S, k, E, C]
        dispatch = jnp.sum(dispatch_k, axis=2)         # [B, S, E, C]
        combine = jnp.sum(
            dispatch_k * topk_prob[..., None, None], axis=2
        )                                              # [B, S, E, C]

        # -- load-balancing aux loss (Switch eq. 4; first choice only) ---
        frac_tokens = jnp.mean(oh_k[:, :, 0], axis=(0, 1))
        frac_probs = jnp.mean(probs, axis=(0, 1))
        aux = self.aux_weight * e * jnp.sum(frac_tokens * frac_probs)
        self.sow("losses", "moe_aux", aux)

        # -- dispatch -> expert FFN -> combine (all einsums; GSPMD turns
        # the token<->expert contractions into ep all-to-alls) -----------
        xd = x.astype(self.dtype)
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(self.dtype), xd)

        wi = self.param(
            "wi", nn.initializers.lecun_normal(), (e, d, self.d_ff), jnp.float32
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(), (e, self.d_ff, d), jnp.float32
        )
        h = jnp.einsum("ebcd,edf->ebcf", expert_in, wi.astype(self.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h, wo.astype(self.dtype))

        out = jnp.einsum(
            "bsec,ebcd->bsd", combine.astype(self.dtype), expert_out
        )
        return out.astype(x.dtype)


from jax.sharding import PartitionSpec as P  # noqa: E402

# Expert-parallel sharding rules: expert banks split their leading [E] axis
# over ``ep``; the router stays replicated.
MOE_EP_RULES = [
    (r".*/moe/w[io]", P("ep", None, None)),
]
