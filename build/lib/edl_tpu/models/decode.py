"""Autoregressive decoding for :class:`TransformerLM`.

Net-new surface versus the reference (which has no LMs): a KV-cached
greedy decode loop, TPU-shaped — the per-token step has fully static
shapes (cache length fixed at ``max_decode_len``, validity masked by the
running index), so the whole generation is ONE compiled ``lax.scan``, no
per-position recompiles. With grouped-query models the cache is stored at
``num_kv_heads`` width: the ``num_heads/num_kv_heads`` cache-byte saving
GQA exists for is realized here.

Usage::

    tokens = greedy_generate(model, params, prompt, max_new_tokens=32)
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from edl_tpu.models.transformer import TransformerLM


def decode_model(model: TransformerLM, max_decode_len: int) -> TransformerLM:
    """The decode-mode twin of a trained model (same params tree)."""
    return dataclasses.replace(
        model, decode=True, max_decode_len=max_decode_len,
        # kernels want [B, H, T, D] batches; the cached step is a plain
        # masked einsum, so the training-side attention_fn is unused
        attention_fn=None,
    )


def init_cache(model: TransformerLM, batch: int, max_decode_len: int):
    """Zeroed KV cache matching the model (grouped width under GQA).

    Structure comes from ``eval_shape`` — no parameters are materialized
    and nothing executes (a real ``init`` would also absorb one phantom
    token into the cache it returns)."""
    dm = decode_model(model, max_decode_len)
    shapes = jax.eval_shape(
        lambda: dm.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch, 1), jnp.int32),
            positions=jnp.zeros((batch, 1), jnp.int32),
        )
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def greedy_generate(
    model: TransformerLM,
    params,
    prompt: jax.Array,          # [B, P] int32
    max_new_tokens: int,
    max_decode_len: int | None = None,
) -> jax.Array:
    """Greedy decode: returns ``[B, P + max_new_tokens]`` tokens.

    Two compiled programs: one BULK PREFILL pass over the whole prompt
    (the cache fills in a single MXU-friendly call) and one single-token
    step scanned ``max_new_tokens`` times.
    """
    b, plen = prompt.shape
    if plen < 1:
        raise ValueError("prompt must hold at least one token")
    total = plen + max_new_tokens
    cap = max_decode_len or total
    if cap < total:
        raise ValueError(
            "max_decode_len %d < prompt+new %d" % (cap, total)
        )
    if max_new_tokens <= 0:
        return prompt
    dm = decode_model(model, cap)
    cache = init_cache(model, b, cap)

    logits, updated = dm.apply(
        {"params": params, "cache": cache},
        prompt,
        positions=jnp.broadcast_to(jnp.arange(plen)[None, :], (b, plen)),
        mutable=["cache"],
    )
    first = jnp.argmax(logits[:, -1, :], axis=-1).astype(prompt.dtype)

    def step(carry, i):
        cache, tok = carry
        logits, updated = dm.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((b, 1), i, jnp.int32),
            mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(prompt.dtype)
        return (updated["cache"], nxt), tok

    (_, last), emitted = jax.lax.scan(
        step, (updated["cache"], first), plen + jnp.arange(max_new_tokens - 1)
    )
    return jnp.concatenate([prompt, emitted.T, last[:, None]], axis=1)
