"""ResNet-vd family in Flax — the flagship collective-training model.

Capability parity with the reference's benchmark workloads: ResNet50
(example/collective/resnet50/train_with_fleet.py) and ResNet50_vd — the
student of the distillation benchmark and the model of every baseline row
(reference README.md:68-72, 144-147).

The *vd* ("bag of tricks", He et al. 2019) differences from vanilla
ResNet, implemented as in the paper (not ported from Paddle code):
  - deep stem: three 3x3 convs (stride 2 on the first) replacing the 7x7;
  - downsample shortcuts: stride-2 average-pool then 1x1 stride-1 conv, so
    no activations are discarded by strided 1x1 convs.

TPU notes: NHWC layouts (XLA:TPU native), bf16 compute with fp32
parameters/batch-norm statistics by default (the TPU replacement for the
reference's AMP/fp16 flags, train_with_fleet.py:68-73), and all convs are
static-shaped so they tile cleanly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckVd(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with the vd avg-pool downsample."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        # final BN of each block: scale init handled by norm factory
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            if self.strides > 1:  # vd trick: pool first, then 1x1 stride-1
                residual = nn.avg_pool(
                    residual,
                    (self.strides, self.strides),
                    strides=(self.strides, self.strides),
                    padding="SAME",
                )
            residual = self.conv(self.filters * 4, (1, 1))(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlockVd(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            if self.strides > 1:
                residual = nn.avg_pool(
                    residual,
                    (self.strides, self.strides),
                    strides=(self.strides, self.strides),
                    padding="SAME",
                )
            residual = self.conv(self.filters, (1, 1))(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet-vd. ``stage_sizes``: blocks per stage, e.g. (3,4,6,3)=50."""

    stage_sizes: Sequence[int]
    block: Callable = BottleneckVd
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # recompute each residual block's activations in the backward instead
    # of saving them: ResNet50_vd training on v5e is HBM-BOUND (measured
    # arithmetic intensity ~80 flops/byte, roofline ceiling 0.331 — see
    # BENCH_r04), so trading recompute FLOPs for activation traffic can
    # RAISE throughput, not just cut memory
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,  # compute dtype; stats/params stay fp32
        )
        x = x.astype(self.dtype)
        # vd deep stem
        x = conv(self.width // 2, (3, 3), strides=(2, 2))(x)
        x = nn.relu(norm()(x))
        x = conv(self.width // 2, (3, 3))(x)
        x = nn.relu(norm()(x))
        x = conv(self.width, (3, 3))(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block = nn.remat(self.block) if self.remat else self.block
        # explicit names matching the un-rematted auto-names: nn.remat
        # renames the module class (Checkpoint<Block>), which would fork
        # the param paths and make remat=True checkpoints incompatible
        block_name = getattr(self.block, "__name__", "Block")
        index = 0
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block_idx in range(num_blocks):
                strides = 2 if stage > 0 and block_idx == 0 else 1
                x = block(
                    filters=self.width * 2**stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name="%s_%d" % (block_name, index),
                )(x)
                index += 1

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class BottleneckX(nn.Module):
    """ResNeXt bottleneck: grouped 3x3 (``cardinality`` groups) between
    1x1 projections, vd-style avg-pool downsample shortcut.

    Grouped convolutions map to ``feature_group_count`` on
    ``lax.conv_general_dilated``, which XLA:TPU tiles onto the MXU as a
    batch of small matmuls — no per-group Python loop.
    """

    filters: int  # channels of the grouped 3x3 conv
    out_filters: int
    strides: int
    cardinality: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(
            self.filters,
            (3, 3),
            strides=(self.strides, self.strides),
            feature_group_count=self.cardinality,
        )(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.out_filters, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            if self.strides > 1:
                residual = nn.avg_pool(
                    residual,
                    (self.strides, self.strides),
                    strides=(self.strides, self.strides),
                    padding="SAME",
                )
            residual = self.conv(self.out_filters, (1, 1))(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNeXt(nn.Module):
    """ResNeXt (Xie et al. 2017) with the vd stem/shortcuts.

    The distillation benchmark's TEACHER is ResNeXt101_32x16d_wsl
    (reference README.md:68-72, example/distill/resnet50 — served via
    Paddle Serving); here it is an in-framework Flax model served by
    ``edl_tpu.distill.serving.JaxPredictBackend`` or fused into a
    co-located student step (tools/colocated_distill.py).
    """

    stage_sizes: Sequence[int]
    cardinality: int = 32
    base_width: int = 16  # group width at stage 0: 32x16d
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME")
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(32, (3, 3), strides=(2, 2))(x)
        x = nn.relu(norm()(x))
        x = conv(32, (3, 3))(x)
        x = nn.relu(norm()(x))
        x = conv(64, (3, 3))(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for stage, num_blocks in enumerate(self.stage_sizes):
            group_width = self.cardinality * self.base_width * 2**stage
            for block_idx in range(num_blocks):
                x = BottleneckX(
                    filters=group_width,
                    out_filters=256 * 2**stage,
                    strides=2 if stage > 0 and block_idx == 0 else 1,
                    cardinality=self.cardinality,
                    conv=conv,
                    norm=norm,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNeXt101_32x16d = partial(ResNeXt, stage_sizes=(3, 4, 23, 3), base_width=16)
ResNeXt101_32x8d = partial(ResNeXt, stage_sizes=(3, 4, 23, 3), base_width=8)
ResNeXt50_32x4d = partial(ResNeXt, stage_sizes=(3, 4, 6, 3), base_width=4)

ResNet18_vd = partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlockVd)
ResNet34_vd = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlockVd)
ResNet50_vd = partial(ResNet, stage_sizes=(3, 4, 6, 3))
ResNet101_vd = partial(ResNet, stage_sizes=(3, 4, 23, 3))
ResNet152_vd = partial(ResNet, stage_sizes=(3, 8, 36, 3))
