"""CTR / recommendation model family: embedding-heavy DP, TPU-first.

Capability parity with the reference's CTR parameter-server example
(reference example/ctr/ctr/train.py:99-107, 237-270 — a wide&deep-style
CTR network trained under Paddle's pserver/trainer transpiler). Per
SURVEY §2 ("Parameter-server" row) the PS architecture is re-scoped for
TPU: there are no parameter-server processes — the embedding tables are
*sharded over the device mesh* (vocab axis on ``mp``) and XLA inserts the
gather/scatter collectives, so the "PS" is the mesh itself.

TPU-first choices:
- ONE fused embedding table for all sparse fields (ids are pre-offset by
  the data pipeline into a shared hashed vocab): a single large batched
  gather instead of F small per-field lookups — one HBM-friendly access
  pattern, one collective, no tiny ops.
- FM second-order interaction (sum-square minus square-sum) and the deep
  MLP are pure batched matmul/elementwise — MXU-dominated, bf16 compute
  with fp32 params.
- Everything static-shaped: ``num_fields`` is a model constant, dense and
  sparse widths are fixed, so the whole step jits into one program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# Embedding tables shard the vocab axis over ``mp`` (model-parallel axis);
# compose with fsdp/dp meshes via shard_params_by_rules, which drops axes
# absent from the mesh.
CTR_EMBEDDING_RULES: List[Tuple[str, P]] = [
    (r".*/embedding/embedding", P("mp", None)),  # [V, D] vocab-sharded
    (r".*/wide/embedding", P("mp", None)),       # [V, 1] first-order term
]


class DeepFM(nn.Module):
    """DeepFM-style CTR model: wide (first-order) + FM (second-order
    interactions) + deep MLP over fused field embeddings and dense
    features. Returns logits ``[B]``.
    """

    vocab_size: int = 1_000_000
    embed_dim: int = 16
    num_fields: int = 26
    dense_features: int = 13
    mlp_dims: Sequence[int] = (256, 128, 64)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, inputs: Tuple[jax.Array, jax.Array]) -> jax.Array:
        """``inputs = (sparse_ids, dense)``: int32 [B, num_fields] into the
        shared vocab + float [B, dense_features]. A single pytree argument
        so the model drops into ``create_state``/``make_train_step``
        unchanged."""
        sparse_ids, dense = inputs
        emb_init = nn.initializers.normal(stddev=1.0 / self.embed_dim**0.5)
        table = nn.Embed(
            self.vocab_size, self.embed_dim,
            embedding_init=emb_init, name="embedding",
        )
        wide = nn.Embed(
            self.vocab_size, 1,
            embedding_init=nn.initializers.zeros, name="wide",
        )

        e = table(sparse_ids)                      # [B, F, D] (fp32 params)
        e = e.astype(self.dtype)
        # FM second-order: 0.5 * sum_d((Σ_f e)² - Σ_f e²) — all batched
        # elementwise/reduce, no [F, F] pair materialisation.
        s = jnp.sum(e, axis=1)                     # [B, D]
        fm = 0.5 * jnp.sum(s * s - jnp.sum(e * e, axis=1), axis=-1)  # [B]

        first_order = jnp.sum(wide(sparse_ids)[..., 0], axis=1)      # [B]

        x = jnp.concatenate(
            [e.reshape(e.shape[0], -1), dense.astype(self.dtype)], axis=-1
        )
        dense_layer = partial(nn.Dense, use_bias=True, dtype=self.dtype)
        for i, width in enumerate(self.mlp_dims):
            x = nn.relu(dense_layer(width, name="mlp_%d" % i)(x))
        deep = dense_layer(1, name="mlp_out")(x)[..., 0]             # [B]

        return (first_order + fm + deep).astype(jnp.float32)


def binary_cross_entropy_loss(
    logits: jax.Array, labels: jax.Array
) -> Tuple[jax.Array, dict]:
    """Loss head for :func:`edl_tpu.train.make_train_step`: sigmoid BCE
    with accuracy, for CTR-style binary targets."""
    labels_f = labels.astype(jnp.float32)
    loss = jnp.mean(
        jnp.maximum(logits, 0) - logits * labels_f
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    accuracy = jnp.mean((logits > 0) == (labels_f > 0.5))
    return loss, {"accuracy": accuracy}
