"""Small dense models: linear regression and MLP.

Capability parity with the reference's minimum end-to-end example
(``example/fit_a_line`` — 13-feature Boston-housing linear regression),
which SURVEY §7.3 designates the first demo-able slice.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class LinearRegression(nn.Module):
    """y = xW + b; the fit_a_line model."""

    features: int = 1

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.features)(x)


class MLP(nn.Module):
    hidden: Sequence[int] = (64, 64)
    features: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.features, dtype=self.dtype)(x)
