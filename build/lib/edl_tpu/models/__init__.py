from edl_tpu.models.ctr import CTR_EMBEDDING_RULES, DeepFM, binary_cross_entropy_loss
from edl_tpu.models.mlp import MLP, LinearRegression
from edl_tpu.models.moe import MOE_EP_RULES, SwitchMoE
from edl_tpu.models.resnet import (
    ResNet,
    ResNet50_vd,
    ResNeXt,
    ResNeXt50_32x4d,
    ResNeXt101_32x16d,
)
from edl_tpu.models.decode import greedy_generate, init_cache
from edl_tpu.models.transformer import TransformerLM

__all__ = [
    "MLP",
    "LinearRegression",
    "ResNet",
    "ResNet50_vd",
    "ResNeXt",
    "ResNeXt50_32x4d",
    "ResNeXt101_32x16d",
    "TransformerLM",
    "greedy_generate",
    "init_cache",
    "DeepFM",
    "CTR_EMBEDDING_RULES",
    "binary_cross_entropy_loss",
    "SwitchMoE",
    "MOE_EP_RULES",
]
