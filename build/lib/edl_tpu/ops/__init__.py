"""Pallas TPU kernels and their reference implementations.

The compute-path hot ops of the framework. Each op ships a pure-jnp
reference (differentiable, runs anywhere) and, where it pays, a Pallas
TPU kernel selected automatically on TPU backends (interpret mode keeps
the kernels testable on CPU).

Net-new capability versus the reference system, which has no kernels at
all (SURVEY §1: "EDL contains no compute kernels"): the task charter makes
long-context attention + distributed compute first-class here.
"""

from edl_tpu.ops.attention import attention, attention_reference, flash_attention

__all__ = ["attention", "attention_reference", "flash_attention"]
