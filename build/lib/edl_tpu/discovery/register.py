"""Generic server-registration CLI.

Capability parity with the reference's ``python -m edl.discovery.register``
(python/edl/discovery/register.py:101-143): wait until a server's port
answers, then register its endpoint under a service name and heartbeat
until terminated. Works for any service; distillation teachers use the
``distill/teachers/`` namespace via ``--teacher``.

    python -m edl_tpu.discovery.register --store 127.0.0.1:2379 \
        --job_id distill --service teacher --teacher --endpoint HOST:PORT
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Optional, Sequence

from edl_tpu.discovery.registry import Registry
from edl_tpu.store.client import StoreClient
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import wait_until_alive

logger = get_logger("discovery.register")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.discovery.register",
        description="register a live endpoint under a service name",
    )
    parser.add_argument("--store", required=True, help="store HOST:PORT")
    parser.add_argument("--job_id", required=True)
    parser.add_argument("--service", required=True)
    parser.add_argument("--endpoint", required=True, help="server HOST:PORT")
    parser.add_argument("--value", default="1")
    parser.add_argument("--ttl", type=float, default=10.0)
    parser.add_argument(
        "--wait_alive", type=float, default=60.0,
        help="seconds to wait for the endpoint's port to answer",
    )
    parser.add_argument(
        "--teacher", action="store_true",
        help="register in the distill teacher namespace",
    )
    args = parser.parse_args(argv)

    if not wait_until_alive(args.endpoint, timeout=args.wait_alive):
        logger.error("endpoint %s never came alive", args.endpoint)
        return 1

    service = args.service
    if args.teacher:
        from edl_tpu.distill.discovery import TEACHER_SERVICE

        service = TEACHER_SERVICE % args.service

    client = StoreClient(args.store)
    registry = Registry(client, args.job_id)
    reg = registry.register(
        service, args.endpoint, args.value.encode(), ttl=args.ttl
    )
    logger.info(
        "registered %s under %s/%s", args.endpoint, args.job_id, service
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    reg.stop(delete=True)
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
