from edl_tpu.discovery.consistent_hash import ConsistentHash
from edl_tpu.discovery.registry import Registry, ServerMeta, ServiceWatch

__all__ = ["ConsistentHash", "Registry", "ServerMeta", "ServiceWatch"]
