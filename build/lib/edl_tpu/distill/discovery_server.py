"""Discovery/balance daemon CLI.

Capability parity with the reference's ``python -m
edl.distill.discovery_server`` (python/edl/distill/discovery_server.py:50,
63-94): hosts the BalanceTable(s) assigning teachers to student clients.
Run replicas with distinct ``--balancer_id``s and they shard service
names by consistent hash (≙ reference balance_table.py:376-391).

    python -m edl_tpu.distill.discovery_server \
        --store 127.0.0.1:2379 --job_id distill --services teacher
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Optional, Sequence

from edl_tpu.distill.discovery import DiscoveryService
from edl_tpu.utils.log import get_logger

logger = get_logger("distill.discovery_server")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.distill.discovery_server",
        description="teacher<->student balance daemon",
    )
    parser.add_argument("--store", required=True, help="store HOST:PORT")
    parser.add_argument("--job_id", default="distill")
    parser.add_argument(
        "--services", default="teacher", help="comma-separated service names"
    )
    parser.add_argument("--balancer_id", default=None)
    parser.add_argument("--ttl", type=float, default=10.0)
    args = parser.parse_args(argv)

    service = DiscoveryService(
        args.store,
        args.job_id,
        [s for s in args.services.split(",") if s],
        balancer_id=args.balancer_id,
        ttl=args.ttl,
    )
    logger.info(
        "discovery server up (job=%s services=%s)", args.job_id, args.services
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    service.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
