"""Numpy arrays over the wire protocol.

The reference moves teacher predictions as Paddle-Serving feed/fetch
ndarray maps (python/edl/distill/distill_worker.py:262-291); here arrays
ride the same msgpack frames as everything else, tagged so decode is
unambiguous. Contiguous bytes only — no pickling, so frames are safe to
exchange with the native C++ runtime.
"""

from __future__ import annotations

import numpy as np

_ND_KEY = "__nd__"


def encode_ndarray(arr: np.ndarray) -> dict:
    arr = np.ascontiguousarray(arr)
    return {
        _ND_KEY: True,
        "dtype": arr.dtype.str,
        "shape": list(arr.shape),
        "data": arr.tobytes(),
    }


def decode_ndarray(obj: dict) -> np.ndarray:
    return np.frombuffer(obj["data"], dtype=np.dtype(obj["dtype"])).reshape(
        obj["shape"]
    )


def is_encoded_ndarray(obj) -> bool:
    return isinstance(obj, dict) and obj.get(_ND_KEY) is True


def encode_tree(obj):
    """Recursively encode ndarrays inside dicts/lists/tuples."""
    if isinstance(obj, np.ndarray):
        return encode_ndarray(obj)
    if isinstance(obj, (list, tuple)):
        return [encode_tree(x) for x in obj]
    if isinstance(obj, dict):
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, (np.generic,)):
        return obj.item()
    return obj


def decode_tree(obj):
    if is_encoded_ndarray(obj):
        return decode_ndarray(obj)
    if isinstance(obj, list):
        return [decode_tree(x) for x in obj]
    if isinstance(obj, dict):
        return {k: decode_tree(v) for k, v in obj.items()}
    return obj


# -- zero-copy attachment refs (EDL2 frames) --------------------------------

_REF_KEY = "__ndref__"


def encode_tree_zc(obj):
    """Like :func:`encode_tree`, but arrays become offset refs into an
    attachment list of memoryviews (never copied): returns
    ``(encoded, attachments)`` for :func:`edl_tpu.rpc.wire.pack_frame_buffers`.
    """
    attachments: list = []
    offset = [0]

    def walk(node):
        if isinstance(node, np.ndarray):
            arr = np.ascontiguousarray(node)
            # zero-size arrays can't be cast ("zeros in shape or strides")
            view = (
                memoryview(arr).cast("B") if arr.size else memoryview(b"")
            )
            ref = {
                _REF_KEY: True,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "off": offset[0],
                "nbytes": view.nbytes,
            }
            attachments.append(view)
            offset[0] += view.nbytes
            return ref
        if isinstance(node, (list, tuple)):
            return [walk(x) for x in node]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, np.generic):
            return node.item()
        return node

    return walk(obj), attachments


def resolve_ndrefs(obj, att_region: memoryview):
    """Materialize refs produced by :func:`encode_tree_zc` as zero-copy
    (read-only) arrays over the received frame buffer."""
    if isinstance(obj, dict):
        if obj.get(_REF_KEY) is True:
            data = att_region[obj["off"] : obj["off"] + obj["nbytes"]]
            return np.frombuffer(data, dtype=np.dtype(obj["dtype"])).reshape(
                obj["shape"]
            )
        return {k: resolve_ndrefs(v, att_region) for k, v in obj.items()}
    if isinstance(obj, list):
        return [resolve_ndrefs(x, att_region) for x in obj]
    return obj
