from edl_tpu.rpc.wire import FrameReader, pack_frame, unpack_payload

__all__ = ["FrameReader", "pack_frame", "unpack_payload"]
