"""Op-latency timeline tracer, env-gated.

Capability parity with the reference's ``_TimeLine`` distill profiler
(python/edl/distill/timeline.py:19-44): per-pid op-latency lines to stderr
when ``EDL_TIMELINE=1`` (the reference's env was ``DISTILL_READER_PROFILE``),
a zero-cost no-op otherwise. Used at queue get/put and RPC boundaries of the
distill pipeline and the data service.
"""

from __future__ import annotations

import os
import sys
import time


class _RealTimeline:
    __slots__ = ("_pid", "_t0")

    def __init__(self) -> None:
        self._pid = os.getpid()
        self._t0 = time.time()

    def reset(self) -> None:
        self._t0 = time.time()

    def record(self, op: str, **extra) -> None:
        now = time.time()
        fields = "".join(" %s=%s" % kv for kv in sorted(extra.items()))
        sys.stderr.write(
            "[timeline] pid=%d op=%s span=%.6f ts=%.6f%s\n"
            % (self._pid, op, now - self._t0, now, fields)
        )
        self._t0 = now


class _NopTimeline:
    __slots__ = ()

    def reset(self) -> None:
        pass

    def record(self, op: str, **extra) -> None:
        pass


def make_timeline():
    """Return a tracer; real when EDL_TIMELINE=1 else a no-op."""
    if os.environ.get("EDL_TIMELINE", "0") == "1":
        return _RealTimeline()
    return _NopTimeline()
