from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import find_free_ports, get_host_ip, is_server_alive

__all__ = ["get_logger", "find_free_ports", "get_host_ip", "is_server_alive"]
