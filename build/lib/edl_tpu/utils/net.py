"""Network helpers: free ports, host IP, TCP liveness probe.

Capability parity with the reference's ``find_free_ports``
(python/edl/utils/utils.py:139), host-ip discovery, and the TCP connect
probe ``is_server_alive`` (python/edl/discovery/server_alive.py:19) whose
local address doubles as client-identity material.
"""

from __future__ import annotations

import socket
from contextlib import closing
from typing import List, Optional, Tuple


def find_free_ports(num: int = 1) -> List[int]:
    """Reserve ``num`` distinct currently-free TCP ports.

    The sockets are opened simultaneously so the kernel cannot hand the
    same port out twice, then all are closed.
    """
    socks = []
    ports = []
    try:
        for _ in range(num):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def get_host_ip() -> str:
    """Best-effort non-loopback IP of this host (no packets are sent)."""
    with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"


def split_endpoint(endpoint: str) -> Tuple[str, int]:
    ip, port = endpoint.rsplit(":", 1)
    return ip, int(port)


def wait_until_alive(
    endpoint: str, timeout: float = 60.0, interval: float = 0.3
) -> bool:
    """Poll :func:`is_server_alive` until ``endpoint`` answers or
    ``timeout`` elapses. Returns whether the endpoint came alive."""
    import time

    deadline = time.time() + timeout
    while True:
        alive, _ = is_server_alive(endpoint)
        if alive:
            return True
        if time.time() > deadline:
            return False
        time.sleep(interval)


def is_server_alive(
    endpoint: str, timeout: float = 1.5
) -> Tuple[bool, Optional[str]]:
    """TCP-connect probe. Returns ``(alive, local_addr_of_probe)``.

    ``local_addr`` ("ip:port" of our side of the probe connection) is
    returned so callers can derive a client identity from it, as the
    reference does (server_alive.py:19-33).
    """
    ip, port = split_endpoint(endpoint)
    try:
        with closing(socket.create_connection((ip, port), timeout=timeout)) as s:
            lip, lport = s.getsockname()[:2]
            return True, "%s:%d" % (lip, lport)
    except OSError:
        return False, None
