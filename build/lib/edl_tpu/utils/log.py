"""Uniform structured logging for every edl_tpu process.

Capability parity: the reference gives all of its services one root-logger
format ``[LEVEL time file:line]`` (reference python/edl/utils/utils.py:28-38).
Here each component asks for a named child logger instead of mutating the
root logger, so embedding applications keep control of their own logging.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "[%(levelname)s %(asctime)s %(name)s %(filename)s:%(lineno)d] %(message)s"

_configured = False


def _configure_base() -> None:
    global _configured
    if _configured:
        return
    base = logging.getLogger("edl_tpu")
    if not base.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        base.addHandler(handler)
    base.setLevel(os.environ.get("EDL_LOG_LEVEL", "INFO").upper())
    base.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    """Return the ``edl_tpu.<name>`` logger, configuring the base once."""
    _configure_base()
    if name.startswith("edl_tpu"):
        return logging.getLogger(name)
    return logging.getLogger("edl_tpu." + name)
