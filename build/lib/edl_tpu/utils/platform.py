"""Platform pinning for the axon-tunnel environment.

The axon sitecustomize registers the remote-TPU backend at interpreter
start and re-pins the platform, so the ``JAX_PLATFORMS=cpu`` env var
alone is not enough: probing the tunnel while it is down HANGS. Every
process that honors an explicit CPU request calls :func:`maybe_pin_cpu`
once, after importing jax and before first backend use.
"""

from __future__ import annotations

import os


def maybe_pin_cpu() -> bool:
    """Pin jax to CPU iff the caller asked for it via JAX_PLATFORMS=cpu.
    Safe to call when backends are already initialized (no-op then).
    Returns True when the pin applied."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return False
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception:  # backends already initialized — use as-is
        return False
