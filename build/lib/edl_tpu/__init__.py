"""edl_tpu — a TPU-native elastic deep-learning framework.

A ground-up JAX/XLA re-design of the capabilities of wangxicoding/edl
(elastic collective training + elastic knowledge distillation):

- ``edl_tpu.store``      — built-in coordination store (lease/watch KV; the
  role etcd/redis play in the reference).
- ``edl_tpu.discovery``  — service registry, consistent hashing, liveness.
- ``edl_tpu.cluster``    — job environment and elastic-cluster data model.
- ``edl_tpu.launch``     — the elastic launcher: rank election, stage
  fencing, barriers, process supervision, stop-resume elasticity.
- ``edl_tpu.parallel``   — device meshes, sharding rules, collectives,
  sequence/context parallelism.
- ``edl_tpu.train``      — trainer loop: pjit train steps, bf16, remat.
- ``edl_tpu.checkpoint`` — sharded checkpoint/resume across topology change.
- ``edl_tpu.data``       — deterministic elastic data sharding service.
- ``edl_tpu.distill``    — elastic knowledge-distillation service layer.
- ``edl_tpu.models``     — model families (MLP, ResNet, Transformer, CTR).
- ``edl_tpu.ops``        — Pallas TPU kernels.

The compute path is JAX (jit/pjit/shard_map over ``jax.sharding.Mesh``,
collectives over ICI/DCN); the control plane is a framed-TCP protocol shared
by the Python and native C++ runtimes. Heavy deps (jax, orbax) are imported
lazily by the subpackages that need them so control-plane processes stay
lightweight.
"""

__version__ = "0.1.0"

__all__ = ["__version__"]
