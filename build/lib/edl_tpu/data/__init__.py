"""Deterministic elastic data layer.

Finishes what the reference only sketched (its distributed data layer is
WIP/non-functional — SURVEY §2 C21: undefined names, excluded from ctest):

- ``dataset``    — file-list datasets and record splitters
  (≙ python/edl/collective/dataset.py ``FileSplitter/TxtFileSplitter``).
- ``checkpoint`` — per-(file, record) progress for exact mid-epoch resume
  (≙ the ``DataCheckpoint`` sketch, python/edl/collective/data_reader.py:63-84).
- ``dispatcher`` — leader-hosted task-queue dispatch service
  (todo/pending/done/failed with timeout+retry, state snapshot for
  failover — the full behavior of the reference's legacy Go master,
  pkg/master/service.go:23-35, re-built on the edl_tpu wire protocol;
  the native C++ twin lives in ``native/master``).
- ``loader``     — the worker-side iterator: pulls shards from the
  dispatcher, yields batches, records progress.
- ``prefetch``   — fixed-shape batching (pad+mask, XLA static shapes) and
  host->device prefetch with bounded in-flight transfers (net-new: the
  reference has no device-feed stage at all).
"""

from edl_tpu.data.dataset import FileListDataset, FileSplitter, TxtFileSplitter
from edl_tpu.data.checkpoint import DataCheckpoint
from edl_tpu.data.dispatcher import (
    DISPATCH_SERVICE,
    DataDispatcher,
    DataTask,
    DispatcherClient,
    discover_dispatcher,
    publish_dispatcher,
)
from edl_tpu.data.loader import ElasticDataLoader
from edl_tpu.data.prefetch import batched, prefetch_to_device, shuffled

__all__ = [
    "DISPATCH_SERVICE",
    "discover_dispatcher",
    "publish_dispatcher",
    "FileListDataset",
    "FileSplitter",
    "TxtFileSplitter",
    "DataCheckpoint",
    "DataDispatcher",
    "DispatcherClient",
    "DataTask",
    "ElasticDataLoader",
    "batched",
    "prefetch_to_device",
    "shuffled",
]
