"""Per-(file, record) data progress for exact mid-epoch resume.

The reference explicitly defers this ("step-level checkpointing is future
work", doc/fault_tolerance.md:27-28) and leaves only a broken sketch
(``DataCheckpoint``, python/edl/collective/data_reader.py:63-84). Here it
is finished: progress is a map ``file_idx -> next unread record`` plus the
epoch number, JSON-serializable so it rides inside the model checkpoint's
``TrainStatus.meta`` — one atomic save covers both model and data state.
"""

from __future__ import annotations

import json
from typing import Dict, Optional


class DataCheckpoint:
    def __init__(
        self,
        epoch: int = 0,
        offsets: Optional[Dict[int, int]] = None,
        done_files: Optional[list] = None,
    ) -> None:
        self.epoch = epoch
        self.offsets: Dict[int, int] = dict(offsets or {})
        self.done_files = set(done_files or ())

    def record_progress(self, file_idx: int, next_record: int) -> None:
        self.offsets[file_idx] = next_record

    def file_done(self, file_idx: int) -> None:
        self.offsets.pop(file_idx, None)
        self.done_files.add(file_idx)

    def start_offset(self, file_idx: int) -> int:
        return self.offsets.get(file_idx, 0)

    def is_file_done(self, file_idx: int) -> bool:
        return file_idx in self.done_files

    def next_epoch(self) -> None:
        self.epoch += 1
        self.offsets.clear()
        self.done_files.clear()

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "offsets": {str(k): v for k, v in self.offsets.items()},
            "done_files": sorted(self.done_files),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DataCheckpoint":
        return cls(
            epoch=d.get("epoch", 0),
            offsets={int(k): v for k, v in d.get("offsets", {}).items()},
            done_files=d.get("done_files", ()),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str) -> "DataCheckpoint":
        return cls.from_dict(json.loads(s))
