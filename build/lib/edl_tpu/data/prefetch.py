"""Fixed-shape batching + host->device prefetch for the input pipeline.

The piece between ``ElasticDataLoader``'s raw-record stream and a jitted
train step. The reference leaves batching to Paddle's reader decorators
(example/collective/resnet50/train_with_fleet.py:458-464) and has no
device-feed stage at all (data loading and GPU compute serialize unless
Paddle's double-buffer flag is set). On TPU the rules are stricter and
the win is bigger:

  - XLA wants STATIC shapes: every batch must be exactly ``batch_size``,
    so the ragged final batch is padded and carries a validity mask the
    loss can apply (never a smaller array — that would retrace/recompile).
  - HBM should never wait on the host: ``prefetch_to_device`` keeps
    ``depth`` batches in flight, transferring batch N+1 (and N+2) while
    the step consumes batch N, with an optional ``jax.sharding.Sharding``
    so dp-sharded batches land directly on their mesh slices.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import numpy as np

__all__ = ["batched", "prefetch_to_device", "shuffled"]


def shuffled(records: Iterable[Any], buffer_size: int, seed: int) -> Iterator[Any]:
    """Streaming shuffle through a bounded reservoir (tf.data-style).

    Deterministic for a given ``seed`` — pass an epoch-derived seed to
    keep the reference's ``pass_id_as_seed`` reproducible-order contract
    (train_with_fleet.py:458-464) while decorrelating batches. O(buffer)
    memory however long the stream."""
    if buffer_size < 1:
        raise ValueError("buffer_size must be >= 1")
    rng = np.random.RandomState(seed)
    buf: list = []
    for rec in records:
        if len(buf) < buffer_size:
            buf.append(rec)
            continue
        idx = rng.randint(buffer_size)
        out, buf[idx] = buf[idx], rec
        yield out
    rng.shuffle(buf)
    yield from buf


def batched(
    records: Iterable[Any],
    batch_size: int,
    collate: Optional[Callable[[list], Any]] = None,
    drop_remainder: bool = False,
) -> Iterator[Tuple[Any, np.ndarray]]:
    """Group a record stream into fixed-size batches.

    Yields ``(batch, mask)`` where ``mask`` is a ``(batch_size,)`` bool
    array — all True except on a padded final batch, whose tail repeats
    the last real record (values are valid arrays, mask tells the loss
    which rows count). ``collate`` turns the list of records into the
    batch structure (default: ``np.stack`` of per-record arrays, or a
    tuple of stacked fields when records are tuples).
    """
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    collate = collate or _default_collate
    buf: list = []
    for rec in records:
        buf.append(rec)
        if len(buf) == batch_size:
            yield collate(buf), np.ones((batch_size,), bool)
            buf = []
    if buf and not drop_remainder:
        mask = np.zeros((batch_size,), bool)
        mask[: len(buf)] = True
        while len(buf) < batch_size:
            buf.append(buf[-1])
        yield collate(buf), mask


def _default_collate(records: list):
    first = records[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([np.asarray(r[i]) for r in records])
            for i in range(len(first))
        )
    return np.stack([np.asarray(r) for r in records])


class _Stop:
    pass


def prefetch_to_device(
    batches: Iterable[Any],
    depth: int = 2,
    sharding=None,
) -> Iterator[Any]:
    """Iterate ``batches`` with ``depth`` device transfers in flight.

    A daemon thread pulls host batches and ``jax.device_put``s them
    (honouring ``sharding`` when given — e.g. ``NamedSharding(mesh,
    P("dp"))`` to scatter the leading axis across the dp mesh axis), so
    the transfer of the next batch overlaps the step on the current one.
    Exceptions in the source iterator are re-raised at the consuming
    call site. Staging HBM is bounded at ``depth + 1`` device batches:
    the queue holds at most ``depth`` and the feeder stages the next
    batch before blocking on the queue reservation.
    """
    import jax

    if depth < 1:
        raise ValueError("depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    err: collections.deque = collections.deque(maxlen=1)
    stop = threading.Event()  # consumer gone: unblock + stop the feeder

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.device_put, batch)
        # local-rows semantics on cross-process meshes (each process
        # contributes its own rows of the global batch)
        from edl_tpu.parallel.mesh import device_put_local_rows

        return jax.tree.map(
            lambda a: device_put_local_rows(a, sharding), batch
        )

    def feeder():
        try:
            for b in batches:
                staged = put(b)
                while not stop.is_set():
                    try:
                        q.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return  # abandoned mid-epoch: drop staged batches
        except BaseException as exc:  # re-raised consumer-side
            err.append(exc)
        finally:
            while not stop.is_set():  # deliver _Stop unless abandoned
                try:
                    q.put(_Stop, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=feeder, daemon=True, name="edl-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _Stop:
                if err:
                    raise err.popleft()
                return
            yield item
    finally:
        # runs on break/exception/GeneratorExit too: without it the
        # feeder blocks in q.put forever, pinning `depth` device batches
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
