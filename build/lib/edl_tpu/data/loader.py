"""Worker-side elastic data loader.

The consumer the reference's WIP ``DistributedDataReader``
(python/edl/collective/data_reader.py:101) was meant to be: pull file
tasks from the dispatcher, stream records, report progress so a
re-dispatched task resumes at the exact record, ack done/failed.

Yields ``(file_idx, record_idx, record_bytes)`` triples; batching and
decoding are the caller's (model input pipeline's) job — on TPU the input
pipeline should hand XLA fixed-shape device batches, so the raw-record
stream stays framework-agnostic here.
"""

from __future__ import annotations

import time
from typing import Iterator, Tuple

from edl_tpu.data.dataset import FileSplitter
from edl_tpu.data.dispatcher import DispatcherClient
from edl_tpu.utils.log import get_logger

logger = get_logger("data.loader")


class ElasticDataLoader:
    def __init__(
        self,
        client: DispatcherClient,
        splitter: FileSplitter,
        report_every: int = 256,
        poll_interval: float = 0.2,
    ) -> None:
        self._client = client
        self._splitter = splitter
        self._report_every = report_every
        self._poll = poll_interval

    def epoch(self) -> Iterator[Tuple[int, int, bytes]]:
        """Stream this worker's share of the epoch, task by task."""
        while True:
            resp = self._client.get_task()
            if resp.get("epoch_done"):
                return
            if resp.get("wait"):
                time.sleep(self._poll)
                continue
            task = resp["task"]
            task_id, file_idx = task["id"], task["file_idx"]
            start = task["start_record"]
            emitted = 0
            try:
                for rec_idx, record in self._splitter.split(task["path"]):
                    if rec_idx < start:
                        continue
                    yield file_idx, rec_idx, record
                    emitted += 1
                    if emitted % self._report_every == 0:
                        self._client.report(task_id, rec_idx + 1)
            except OSError as exc:
                logger.warning("task %d read failed: %s", task_id, exc)
                self._client.task_failed(task_id)
                continue
            self._client.task_done(task_id)
