"""File-list datasets and record splitters.

Capability parity with the reference's dataset layer
(python/edl/collective/dataset.py:19-48 ``FileSplitter/TxtFileSplitter``):
a dataset is a list of files; a splitter turns one file into numbered
records, so any (file, record) pair addresses one sample — the unit of
the data checkpoint.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Tuple


class FileSplitter:
    """Iterate ``(record_idx, record_bytes)`` pairs of one file."""

    def split(self, path: str) -> Iterator[Tuple[int, bytes]]:
        raise NotImplementedError

    def count(self, path: str) -> int:
        return sum(1 for _ in self.split(path))


class TxtFileSplitter(FileSplitter):
    """One record per line, newline stripped (≙ reference dataset.py:36)."""

    def split(self, path: str) -> Iterator[Tuple[int, bytes]]:
        with open(path, "rb") as f:
            for idx, line in enumerate(f):
                yield idx, line.rstrip(b"\r\n")


class FileListDataset:
    """An ordered list of data files + the splitter that reads them."""

    def __init__(self, files: Iterable[str], splitter: FileSplitter) -> None:
        self.files: List[str] = [os.fspath(f) for f in files]
        self.splitter = splitter

    @classmethod
    def from_file_list(
        cls, list_path: str, splitter: FileSplitter, base_dir: str = ""
    ) -> "FileListDataset":
        """Read a file whose lines are data-file paths (the reference's
        file-list convention, utils.py:41)."""
        files = []
        with open(list_path, "r") as f:
            for line in f:
                line = line.strip()
                if line:
                    files.append(os.path.join(base_dir, line))
        return cls(files, splitter)

    def read_file(
        self, file_idx: int, start_record: int = 0
    ) -> Iterator[Tuple[int, bytes]]:
        for idx, rec in self.splitter.split(self.files[file_idx]):
            if idx >= start_record:
                yield idx, rec

    def __len__(self) -> int:
        return len(self.files)
