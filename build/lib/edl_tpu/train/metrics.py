"""Streaming, jit-friendly evaluation metrics.

The reference's CTR workload reports AUC via Paddle's fluid AUC op
(reference example/ctr/ctr/train.py — ``fluid.layers.auc``). The TPU
equivalent must accumulate *inside* jitted steps across a sharded eval
stream, so it is a fixed-size bucketed accumulator: static shapes, pure
updates, mergeable across devices/hosts with a plain sum (``psum`` or a
host-side add after all-reduce of the histograms).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AUCState(NamedTuple):
    """Histograms of predicted probability by class; sum across devices
    (or hosts) to merge partial states."""

    pos: jax.Array  # [num_buckets] count of positives per score bucket
    neg: jax.Array  # [num_buckets] count of negatives per score bucket


def auc_init(num_buckets: int = 2048) -> AUCState:
    return AUCState(
        pos=jnp.zeros((num_buckets,), jnp.float32),
        neg=jnp.zeros((num_buckets,), jnp.float32),
    )


def auc_update(state: AUCState, logits: jax.Array, labels: jax.Array) -> AUCState:
    """Accumulate a batch. Pure + static-shaped: safe inside jit/scan."""
    n = state.pos.shape[0]
    prob = jax.nn.sigmoid(logits.reshape(-1))
    bucket = jnp.clip((prob * n).astype(jnp.int32), 0, n - 1)
    is_pos = labels.reshape(-1).astype(jnp.float32)
    pos = state.pos.at[bucket].add(is_pos)
    neg = state.neg.at[bucket].add(1.0 - is_pos)
    return AUCState(pos=pos, neg=neg)


def auc_compute(state: AUCState) -> jax.Array:
    """Trapezoidal AUC over the bucketed ROC curve.

    Within-bucket ties contribute half (the trapezoid), matching the
    standard rank-statistic treatment of tied scores.
    """
    total_pos = jnp.maximum(jnp.sum(state.pos), 1e-12)
    total_neg = jnp.maximum(jnp.sum(state.neg), 1e-12)
    # sweep buckets from high score to low: cumulative TP / FP
    pos = state.pos[::-1]
    neg = state.neg[::-1]
    tp = jnp.cumsum(pos)
    fp = jnp.cumsum(neg)
    tpr = tp / total_pos
    fpr = fp / total_neg
    tpr0 = jnp.concatenate([jnp.zeros((1,)), tpr[:-1]])
    fpr0 = jnp.concatenate([jnp.zeros((1,)), fpr[:-1]])
    return jnp.sum((fpr - fpr0) * (tpr + tpr0) / 2.0)


def auc_merge(a: AUCState, b: AUCState) -> AUCState:
    return AUCState(pos=a.pos + b.pos, neg=a.neg + b.neg)
