"""Gradient compression: DGC-style top-k sparsification with error feedback.

The reference exposes Deep Gradient Compression as a passthrough flag
whose implementation lives in Paddle (reference
example/collective/resnet50/train_with_fleet.py:98,106-146 ``--use_dgc``;
SURVEY §2 parallelism table: "flag only, impl in Paddle"). Here it is an
``optax`` gradient transformation, so it composes with any optimizer and
any sharding:

    tx = optax.chain(topk_compression(0.01), optax.sgd(lr, momentum=0.9))

Semantics follow Lin et al. 2018 (DGC) minus the network side: each step
keeps only the top ``ratio`` fraction of gradient entries per tensor (by
magnitude), and the residual (what was dropped) is accumulated locally
and added back the next step — error feedback, which is what makes
aggressive sparsification converge.

TPU honesty note: on ICI, XLA's fused all-reduce of the DENSE gradient is
usually faster than gather-scatter of sparse values, so this transform
applies compression AFTER the mesh all-reduce (it sees the averaged
gradient a jitted step computes). What it preserves is the OPTIMIZATION
behavior of DGC training (sparse updates + error feedback) — useful for
parity experiments and for DCN-crossing setups where update traffic,
checkpoint deltas, or host offload benefit from sparsity. Everything is
static-shaped (jnp.percentile threshold, no dynamic gathers), so it jits
cleanly on TPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["topk_compression", "TopKState"]


class TopKState(NamedTuple):
    residual: optax.Updates  # error-feedback accumulator, same tree as params


class _Pair(NamedTuple):
    """Internal (kept, residual) marker type. A dedicated class (not a
    bare tuple) so the extraction is_leaf predicate can never fire on
    container tuples/NamedTuples inside the USER's params tree."""

    kept: object
    resid: object


def topk_compression(ratio: float = 0.01) -> optax.GradientTransformation:
    """Keep the top ``ratio`` of entries per tensor; bank the rest.

    ``ratio`` in (0, 1]. Tensors with fewer than ``1/ratio`` elements are
    passed through dense (biases and norms are tiny and sign-critical —
    the DGC paper likewise exempts them).
    """
    if not 0.0 < ratio <= 1.0:
        raise ValueError("ratio must be in (0, 1], got %r" % (ratio,))

    def init(params):
        return TopKState(
            residual=jax.tree.map(jnp.zeros_like, params)
        )

    def update(updates, state, params=None):
        del params

        def compress(g, r):
            g = g + r  # error feedback: add back what was dropped before
            if ratio >= 1.0 or g.size < int(1.0 / ratio):
                return g, jnp.zeros_like(g)
            q = 100.0 * (1.0 - ratio)
            # static-shaped threshold selection: percentile of |g|
            thresh = jnp.percentile(jnp.abs(g), q)
            mask = (jnp.abs(g) >= thresh).astype(g.dtype)
            kept = g * mask
            return kept, g - kept

        is_pair = lambda x: isinstance(x, _Pair)  # noqa: E731
        flat = jax.tree.map(
            lambda g, r: _Pair(*compress(g, r)), updates, state.residual
        )
        kept = jax.tree.map(lambda p: p.kept, flat, is_leaf=is_pair)
        resid = jax.tree.map(lambda p: p.resid, flat, is_leaf=is_pair)
        return kept, TopKState(residual=resid)

    return optax.GradientTransformation(init, update)
