"""Learning-rate schedules with elastic world-size scaling.

The reference's example exposes ``--lr_strategy piecewise_decay |
cosine_decay`` built on epoch boundaries (reference
example/collective/resnet50/train_with_fleet.py:150-210 ``lr_strategy``
branches) and combines them with the linear-scaling rule when the job
resizes. Here the same two families are optax schedules parameterized by
steps-per-epoch, plus factories that plug into ``AdjustRegistry`` /
``ElasticTrainer``'s optimizer-factory form so the peak lr rescales with
the CURRENT world size on every elastic restart.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import optax

__all__ = [
    "piecewise_decay",
    "warmup_cosine",
    "scaled_schedule_factory",
]


def piecewise_decay(
    base_lr: float,
    steps_per_epoch: int,
    boundaries_epochs: Sequence[int] = (30, 60, 90),
    decay: float = 0.1,
) -> optax.Schedule:
    """Step decay at epoch boundaries (the reference's default ResNet
    strategy: /10 at epochs 30/60/90)."""
    return optax.piecewise_constant_schedule(
        base_lr,
        {int(e * steps_per_epoch): decay for e in boundaries_epochs},
    )


def warmup_cosine(
    base_lr: float,
    steps_per_epoch: int,
    total_epochs: int,
    warmup_epochs: int = 5,
    end_lr: float = 0.0,
) -> optax.Schedule:
    """Linear warmup then cosine decay to ``end_lr`` (the reference's
    ``cosine_decay`` strategy with the warmup its large-batch runs use)."""
    warmup = int(warmup_epochs * steps_per_epoch)
    total = max(int(total_epochs * steps_per_epoch), warmup + 1)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=base_lr,
        warmup_steps=max(warmup, 1),
        decay_steps=total,
        end_value=end_lr,
    )


def scaled_schedule_factory(
    make_schedule: Callable[[float], optax.Schedule],
    make_tx: Optional[Callable[[optax.Schedule], optax.GradientTransformation]] = None,
):
    """Build an ``ElasticTrainer`` optimizer factory whose peak lr comes
    from the AdjustRegistry overrides (e.g. ``linear_scaled_lr``):

        adjusts.register(linear_scaled_lr(0.1, base_world_size=8))
        trainer = ElasticTrainer(
            model,
            scaled_schedule_factory(
                lambda lr: warmup_cosine(lr, steps_per_epoch, epochs),
            ),
            ...,  adjusts=adjusts)

    On every elastic restart the factory is re-invoked with the overrides
    resolved for the NEW world size, so the whole schedule re-peaks at
    the rescaled lr — the reference's resize contract, applied to full
    schedules instead of a constant.
    """
    make_tx = make_tx or (lambda sched: optax.sgd(sched, momentum=0.9))

    def factory(overrides: Dict) -> optax.GradientTransformation:
        lr = overrides.get("lr")
        if lr is None:
            raise ValueError(
                "scaled_schedule_factory needs an 'lr' override — register "
                "linear_scaled_lr (or similar) on the AdjustRegistry"
            )
        return make_tx(make_schedule(float(lr)))

    return factory
