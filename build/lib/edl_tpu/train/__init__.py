from edl_tpu.train.context import (
    current_env,
    enable_compilation_cache,
    init,
    warm_only,
    worker_barrier,
)
from edl_tpu.train.compression import topk_compression
from edl_tpu.train.loop import ElasticTrainer
from edl_tpu.train.schedules import (
    piecewise_decay,
    scaled_schedule_factory,
    warmup_cosine,
)
from edl_tpu.train.metrics import (
    AUCState,
    auc_compute,
    auc_init,
    auc_merge,
    auc_update,
)
from edl_tpu.train.step import (
    TrainState,
    create_state,
    cross_entropy_loss,
    make_cross_entropy_loss,
    make_eval_step,
    make_kd_loss,
    make_masked_train_step,
    make_train_step,
    mse_loss,
)

__all__ = [
    "init",
    "enable_compilation_cache",
    "current_env",
    "ElasticTrainer",
    "topk_compression",
    "piecewise_decay",
    "warmup_cosine",
    "scaled_schedule_factory",
    "warm_only",
    "worker_barrier",
    "TrainState",
    "create_state",
    "make_train_step",
    "make_masked_train_step",
    "make_eval_step",
    "cross_entropy_loss",
    "make_cross_entropy_loss",
    "make_kd_loss",
    "mse_loss",
    "AUCState",
    "auc_init",
    "auc_update",
    "auc_compute",
    "auc_merge",
]
