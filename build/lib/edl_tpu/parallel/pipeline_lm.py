"""Pipeline-parallel TransformerLM: embed → block stages → head.

Stage-splits :class:`~edl_tpu.models.transformer.TransformerLM` over the
``pp`` mesh axis using the GPipe schedule in
:mod:`edl_tpu.parallel.pipeline`:

- the **embedding** runs on rank 0 only (``first_fn`` under ``lax.cond``),
  turning int tokens into the circulating ``[mb, T, D]`` activation;
- the **transformer blocks** are grouped into ``PP`` equal stages; each
  stage's ``L/PP`` blocks are applied by a ``lax.scan`` over their stacked
  params (weights live sharded ``[PP, L/PP, ...]`` on the ``pp`` axis);
- the **final norm + lm_head** run on the last rank only. For training,
  :func:`pipeline_lm_loss` folds the cross-entropy into the last stage so
  only per-example loss scalars ever leave the pipeline — no logits
  broadcast at all.

Net-new capability versus the reference (SURVEY §2: no pipeline
parallelism anywhere in its tree). Combine with ``batch_axis="dp"`` for
dp×pp meshes; grads for the replicated embed/head params are psum'ed
across ranks by the shard_map transpose automatically.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from edl_tpu.models.transformer import (
    Block,
    LMHead,
    RMSNorm,
    TransformerLM,
    _remat_policy,
)
from edl_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


class LMStageParams(NamedTuple):
    """TransformerLM params rearranged for pipeline execution."""

    embed: Any  # {'embedding': [V, D]} — replicated; used by rank 0
    body: Any   # block pytree stacked [PP, L/PP, ...] — shard over pp
    head: Any   # {'ln_f': ..., 'lm_head': ...} — replicated; last rank


def _check_model(model: TransformerLM, pp: int) -> int:
    if model.num_experts > 0:
        raise ValueError(
            "pipeline parallelism requires homogeneous (dense) blocks; "
            "MoE layers change the per-layer param structure"
        )
    if model.num_layers % pp:
        raise ValueError(
            "num_layers %d not divisible by pp %d" % (model.num_layers, pp)
        )
    return model.num_layers // pp


def split_lm_params(model: TransformerLM, params, pp: int) -> LMStageParams:
    """Rearrange a flat TransformerLM param dict (``state.params``) into
    pipeline form: blocks double-stacked ``[PP, L/PP, ...]``."""
    lps = _check_model(model, pp)
    layers = [params["layer_%d" % i] for i in range(model.num_layers)]
    stages = []
    for s in range(pp):
        group = layers[s * lps:(s + 1) * lps]
        stages.append(jax.tree.map(lambda *ls: jnp.stack(ls, axis=0), *group))
    return LMStageParams(
        embed=params["embed"],
        body=stack_stage_params(stages),
        head={"ln_f": params["ln_f"], "lm_head": params["lm_head"]},
    )


def merge_lm_params(model: TransformerLM, split: LMStageParams):
    """Inverse of :func:`split_lm_params` (checkpoint/eval interop)."""
    pp = jax.tree.leaves(split.body)[0].shape[0]
    lps = _check_model(model, pp)
    out = {
        "embed": split.embed,
        "ln_f": split.head["ln_f"],
        "lm_head": split.head["lm_head"],
    }
    for i in range(model.num_layers):
        s, j = divmod(i, lps)
        out["layer_%d" % i] = jax.tree.map(
            lambda leaf, s=s, j=j: leaf[s, j], split.body
        )
    return out


def _make_fns(model: TransformerLM):
    block = Block(
        model.num_heads, model.d_ff, model.dtype, model.attention_fn,
        num_kv_heads=model.num_kv_heads,
    )
    embed_mod = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    norm = RMSNorm()
    head_mod = LMHead(model.vocab_size)

    def apply_block(bp, h, positions):
        return block.apply({"params": bp}, h, positions)

    if model.remat:
        # same policy contract as the single-device path (nn.remat in
        # TransformerLM.__call__): save_flash keeps the attention
        # kernel's out+lse across the backward
        apply_block = jax.checkpoint(
            apply_block, policy=_remat_policy(model.remat_policy)
        )

    def body_fn(stage_params, h):
        positions = jnp.broadcast_to(
            jnp.arange(h.shape[1])[None, :], h.shape[:2]
        )

        def one(carry, bp):
            return apply_block(bp, carry, positions), None

        h, _ = jax.lax.scan(one, h, stage_params)
        return h

    def first_fn(ep, tokens):
        return embed_mod.apply({"params": ep}, tokens)

    def head_fn(hp, h):
        h = norm.apply({"params": hp["ln_f"]}, h)
        return head_mod.apply({"params": hp["lm_head"]}, h)

    return body_fn, first_fn, head_fn


def pipeline_lm_logits(
    model: TransformerLM,
    split: LMStageParams,
    tokens: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Forward pass → logits ``[B, T, V]`` (eval path; the full logits
    tensor is broadcast from the last rank — prefer
    :func:`pipeline_lm_loss` for training)."""
    body_fn, first_fn, head_fn = _make_fns(model)
    return pipeline_apply(
        body_fn, split.body, tokens, mesh, num_microbatches, axis=axis,
        first_fn=first_fn, first_params=split.embed,
        last_fn=head_fn, last_params=split.head,
        batch_axis=batch_axis,
    )


def _make_last_loss(head_fn):
    """Per-example next-token CE on the last rank — THE loss definition
    both the GPipe path and the 1F1B path must share."""

    def last_loss(hp, h, tgt):
        logits = head_fn(hp, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt
        ).mean(axis=-1)  # [mb]

    return last_loss


def pipeline_lm_loss(
    model: TransformerLM,
    split: LMStageParams,
    tokens: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Mean next-token cross-entropy, computed INSIDE the pipeline: the
    last rank projects to logits and reduces them to a per-example loss,
    so the only cross-stage traffic is activations + [mb] scalars."""
    body_fn, first_fn, head_fn = _make_fns(model)
    last_loss = _make_last_loss(head_fn)

    per_example = pipeline_apply(
        body_fn, split.body, tokens, mesh, num_microbatches, axis=axis,
        first_fn=first_fn, first_params=split.embed,
        last_fn=last_loss, last_params=split.head, last_aux=targets,
        batch_axis=batch_axis,
    )
    return per_example.mean()


def pipeline_lm_1f1b_grads(
    model: TransformerLM,
    split: LMStageParams,
    tokens: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: Optional[str] = None,
):
    """(loss, grads-as-LMStageParams) via the memory-bounded 1F1B schedule
    (:mod:`edl_tpu.parallel.pipeline_1f1b`) — same numbers as
    ``jax.value_and_grad`` over :func:`pipeline_lm_loss`, but peak live
    activations stay ~PP per device instead of growing with the
    microbatch count."""
    from edl_tpu.parallel.pipeline_1f1b import pipeline_1f1b_loss_and_grads

    body_fn, first_fn, head_fn = _make_fns(model)
    last_loss = _make_last_loss(head_fn)

    loss, (d_body, d_first, d_last) = pipeline_1f1b_loss_and_grads(
        body_fn, split.body, tokens, mesh, num_microbatches,
        first_fn=first_fn, first_params=split.embed,
        last_loss_fn=last_loss, last_params=split.head,
        last_aux=targets, axis=axis, batch_axis=batch_axis,
    )
    return loss, LMStageParams(embed=d_first, body=d_body, head=d_last)
