"""Ulysses-style all-to-all sequence parallelism over the ``sp`` axis.

The second of the two standard long-context strategies (the task charter
makes both first-class; the reference has neither — SURVEY §5): where
ring attention (``edl_tpu.parallel.ring``) keeps the sequence sharded and
rotates KV around the ring, Ulysses (DeepSpeed-Ulysses, Jacobs et al.
2023 — public recipe, re-implemented here on XLA collectives) RESHARDS
with two ``lax.all_to_all``s: sequence-sharded ``[B, H, T/sp, D]``
becomes head-sharded ``[B, H/sp, T, D]``, each device runs EXACT local
attention over the full sequence on its head group (through the Pallas
flash kernel), and a second all-to-all restores sequence sharding.

Trade-offs vs the ring: communication is two all-to-alls of activation
size (independent of sequence length per hop) instead of ``sp`` KV
rotations, attention itself needs no online-softmax merging (exact, any
mask), but head count bounds the parallelism (``H % sp == 0``) and peak
memory holds the full-sequence scores blockwise per head group.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from edl_tpu.ops.attention import flash_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Callable = flash_attention,
) -> jax.Array:
    """Call under shard_map with ``q, k, v`` holding this device's
    sequence shard ``[B, H, T_local, D]``; returns the same shard of the
    attention output."""
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % sp:
        raise ValueError(
            "ulysses needs heads %% sp == 0 (got H=%d, sp=%d); use ring "
            "attention for head counts the mesh can't divide" % (h, sp)
        )
    # seq-sharded -> head-sharded: split H into sp groups, gather T.
    # all_to_all concatenates by source index, and source i holds sequence
    # shard i, so the gathered axis comes out in global sequence order.
    reshard = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1,
        concat_axis=2, tiled=True,
    )
    out = attn_fn(
        reshard(q), reshard(k), reshard(v), causal=causal, scale=scale
    )  # [B, H/sp, T, D] — exact attention, full sequence, my head group
    # head-sharded -> seq-sharded (the transpose collective; autodiff of
    # all_to_all is the reverse all_to_all, so grads reshard for free)
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    sp_axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    scale: Optional[float] = None,
    attn_fn: Callable = flash_attention,
) -> jax.Array:
    """jit-compatible wrapper mirroring ``ring_attention_sharded``:
    ``[B, H, T, D]`` global arrays, batch over ``dp_axis``, sequence over
    ``sp_axis``; ``attn_fn`` is the local kernel on every path (including
    the sp == 1 passthrough)."""
    from edl_tpu.parallel.mesh import sharded_seq_attention

    return sharded_seq_attention(
        functools.partial(
            ulysses_attention, axis_name=sp_axis, causal=causal,
            scale=scale, attn_fn=attn_fn,
        ),
        functools.partial(attn_fn, causal=causal, scale=scale),
        q, k, v, mesh, sp_axis=sp_axis, dp_axis=dp_axis,
    )
