from edl_tpu.parallel.mesh import (
    batch_sharding,
    device_put_global,
    device_put_local_rows,
    make_hybrid_mesh,
    make_mesh,
    replicated,
    shard_batch,
    shard_params_fsdp,
)
from edl_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_efficiency,
    stack_stage_params,
)
from edl_tpu.parallel.pipeline_1f1b import pipeline_1f1b_loss_and_grads
from edl_tpu.parallel.pipeline_lm import (
    LMStageParams,
    merge_lm_params,
    pipeline_lm_1f1b_grads,
    pipeline_lm_logits,
    pipeline_lm_loss,
    split_lm_params,
)
from edl_tpu.parallel.ring import ring_attention, ring_attention_sharded
from edl_tpu.parallel.ulysses import ulysses_attention, ulysses_attention_sharded
from edl_tpu.parallel.sharding_rules import (
    TRANSFORMER_TP_RULES,
    shard_params_by_rules,
    spec_for_path,
)

__all__ = [
    "device_put_global",
    "device_put_local_rows",
    "make_hybrid_mesh",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params_fsdp",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "pipeline_apply",
    "pipeline_efficiency",
    "stack_stage_params",
    "LMStageParams",
    "split_lm_params",
    "merge_lm_params",
    "pipeline_lm_logits",
    "pipeline_lm_loss",
    "pipeline_lm_1f1b_grads",
    "pipeline_1f1b_loss_and_grads",
    "TRANSFORMER_TP_RULES",
    "shard_params_by_rules",
    "spec_for_path",
]
