"""``edl-status``: live cluster/job inspector over the store keyspace.

The reference ships protobuf pretty-printers and per-daemon log greps as
its only visibility into a running job (SURVEY §2 C20 utils; §5 "no
metrics export, no dashboards"). Here the entire control plane lives in
one store keyspace (``/{job_id}/{service}/...``), so one range scan can
render the whole job: cluster generation + pods with ranks, live
resources, drain fencing, registered teachers, job status.

    edl-status --store 127.0.0.1:2379 --job_id rn50 [--json] [--watch N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Tuple

from edl_tpu.store.client import StoreClient


def collect(client: StoreClient, job_id: str) -> Dict[str, List[Tuple[str, str]]]:
    """Group every key under the job by service segment."""
    prefix = "/%s/" % job_id
    kvs, _rev = client.range(prefix)
    services: Dict[str, List[Tuple[str, str]]] = {}
    for key, value, _cr, _mr in kvs:
        rest = key[len(prefix):]
        service, _, name = rest.partition("/")
        try:
            text = value.decode("utf-8", "replace")
        except AttributeError:
            text = str(value)
        services.setdefault(service, []).append((name, text))
    return services


def _fmt_pod(payload: str) -> str:
    try:
        pod = json.loads(payload)
    except ValueError:
        return payload[:60]
    if not isinstance(pod, dict):  # valid JSON scalar: render raw
        return payload[:60]
    return "%s @%s gpus/chips=%s stage=%s" % (
        str(pod.get("pod_id", "?"))[:12],
        pod.get("addr", "?"),
        len(pod.get("workers", pod.get("trainers", []))) or pod.get("num_workers", "?"),
        str(pod.get("stage", ""))[:12],
    )


def render(services: Dict[str, List[Tuple[str, str]]]) -> str:
    lines: List[str] = []
    cluster = dict(services.get("cluster", []))
    if "current" in cluster:
        try:
            cur = json.loads(cluster["current"])
            pods = cur.get("pods", [])
            lines.append(
                "cluster: stage=%s pods=%d world_size=%s"
                % (
                    str(cur.get("stage", "?"))[:12],
                    len(pods),
                    cur.get("world_size", sum(len(p.get("workers", [])) for p in pods)),
                )
            )
        except ValueError:
            lines.append("cluster: %s" % cluster["current"][:80])
    for svc, title, fmt in (
        ("pod_rank", "ranks", _fmt_pod),
        ("pod_resource", "live pods", _fmt_pod),
    ):
        entries = services.get(svc, [])
        if entries:
            lines.append("%s (%d):" % (title, len(entries)))
            for name, payload in sorted(entries):
                lines.append("  %-6s %s" % (name, fmt(payload)))
    drain = services.get("drain", [])
    if drain:
        lines.append("drain: %s" % ", ".join("%s=%s" % (n, v[:24]) for n, v in drain))
    job = dict(services.get("job", []))
    if job:
        lines.append("job: %s" % ", ".join("%s=%s" % kv for kv in sorted(job.items())))
    # anything else (teachers, barriers, balance tables, ...) generically
    known = {"cluster", "pod_rank", "pod_resource", "drain", "job"}
    for svc in sorted(services):
        if svc in known:
            continue
        entries = services[svc]
        lines.append("%s (%d):" % (svc, len(entries)))
        for name, payload in sorted(entries)[:20]:
            lines.append("  %-24s %s" % (name, payload[:60]))
        if len(entries) > 20:
            lines.append("  ... %d more" % (len(entries) - 20))
    return "\n".join(lines) if lines else "(no keys for this job)"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl-status", description=__doc__)
    parser.add_argument("--store", required=True, help="host:port")
    parser.add_argument("--job_id", required=True)
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--watch", type=float, default=0.0,
        help="re-render every N seconds until interrupted",
    )
    parser.add_argument(
        "--dispatcher", default=None, metavar="HOST:PORT",
        help="also query a data-dispatcher/master daemon for task-queue "
        "state (todo/pending/done/failed, epoch)",
    )
    args = parser.parse_args(argv)
    client = StoreClient(args.store, timeout=10.0)
    try:
        while True:
            services = collect(client, args.job_id)
            dispatch = None
            if args.dispatcher:
                from edl_tpu.data import DispatcherClient

                dc = None
                try:
                    dc = DispatcherClient(
                        args.dispatcher, "edl-status", timeout=10.0
                    )
                    dispatch = dc.state()
                except Exception as exc:  # render what we can
                    dispatch = {"error": str(exc)}
                finally:
                    if dc is not None:
                        dc.close()
            if args.json:
                blob = {s: dict(kv) for s, kv in services.items()}
                if dispatch is not None:
                    blob["dispatcher"] = dispatch
                print(json.dumps(blob, sort_keys=True))
            else:
                print(render(services))
                if dispatch is not None:
                    print(
                        "dispatcher: "
                        + ", ".join(
                            "%s=%s" % kv for kv in sorted(dispatch.items())
                        )
                    )
            if not args.watch:
                return 0
            time.sleep(args.watch)
            if not args.json:
                print("---")
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
