from edl_tpu.cluster.model import Cluster, Pod, Worker
from edl_tpu.cluster.job_env import JobEnv, WorkerEnv

__all__ = ["Cluster", "Pod", "Worker", "JobEnv", "WorkerEnv"]
