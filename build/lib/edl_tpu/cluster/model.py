"""Elastic-cluster data model: Pod, Worker, Cluster.

Capability parity with the reference's cluster model
(python/edl/utils/cluster.py:44-420 — Pod/Trainer/Cluster with JSON serde,
uuid pod ids distinct from ranks, stage uuids, global-rank assignment,
equality-based change detection), re-scoped for TPU:

- a *Pod* is one TPU host (TPU-VM worker). Where the reference fans out one
  trainer process per GPU (cluster.py:238), JAX wants exactly one process
  per host, so a pod normally carries ONE worker owning all local chips;
  ``nproc`` > 1 exists for CPU-simulated elasticity tests.
- a *Worker* is one spawned training process: global rank, rank in pod,
  endpoint, device count.
- a *Cluster* is the rank-ordered pod list stamped with a *stage* uuid (the
  fencing token bumped by the leader on every membership change, reference
  register.py:135) — plus the JAX coordinator endpoint derived from rank 0,
  which ``jax.distributed.initialize`` consumes where the reference's
  trainers consume ``PADDLE_TRAINER_ENDPOINTS`` for NCCL bootstrap.
"""

from __future__ import annotations

import json
import uuid
from dataclasses import dataclass, field
from typing import List, Optional


def new_uuid() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class Worker:
    endpoint: str  # ip:port reserved for the worker process (jax coordinator/debug)
    global_rank: int = -1
    rank_in_pod: int = 0
    num_devices: int = 1

    def to_dict(self) -> dict:
        return {
            "endpoint": self.endpoint,
            "global_rank": self.global_rank,
            "rank_in_pod": self.rank_in_pod,
            "num_devices": self.num_devices,
        }

    @staticmethod
    def from_dict(d: dict) -> "Worker":
        return Worker(
            endpoint=d["endpoint"],
            global_rank=d["global_rank"],
            rank_in_pod=d["rank_in_pod"],
            num_devices=d["num_devices"],
        )


@dataclass
class Pod:
    pod_id: str = field(default_factory=new_uuid)  # identity, NOT rank
    addr: str = "127.0.0.1"
    rank: int = -1
    stage: str = ""
    workers: List[Worker] = field(default_factory=list)

    @property
    def num_devices(self) -> int:
        return sum(w.num_devices for w in self.workers)

    def assign_global_ranks(self, base: int) -> int:
        """Number workers ``base..`` in rank_in_pod order; returns next base.

        Mirrors the reference's ``Pod.rank`` setter computing global trainer
        ranks from the pod rank (cluster.py:203)."""
        for i, worker in enumerate(sorted(self.workers, key=lambda w: w.rank_in_pod)):
            worker.rank_in_pod = i
            worker.global_rank = base + i
        return base + len(self.workers)

    def to_dict(self) -> dict:
        return {
            "pod_id": self.pod_id,
            "addr": self.addr,
            "rank": self.rank,
            "stage": self.stage,
            "workers": [w.to_dict() for w in self.workers],
        }

    @staticmethod
    def from_dict(d: dict) -> "Pod":
        return Pod(
            pod_id=d["pod_id"],
            addr=d["addr"],
            rank=d["rank"],
            stage=d["stage"],
            workers=[Worker.from_dict(w) for w in d["workers"]],
        )

    def to_json(self) -> bytes:
        return json.dumps(self.to_dict(), sort_keys=True).encode()

    @staticmethod
    def from_json(data: bytes) -> "Pod":
        return Pod.from_dict(json.loads(data))


@dataclass
class Cluster:
    stage: str = ""
    pods: List[Pod] = field(default_factory=list)

    @staticmethod
    def from_pods(pods: List[Pod], stage: str) -> "Cluster":
        """Build a cluster from rank-registered pods: order by rank, stamp
        the stage, and assign contiguous global worker ranks."""
        ordered = sorted(pods, key=lambda p: p.rank)
        base = 0
        for pod in ordered:
            pod.stage = stage
            base = pod.assign_global_ranks(base)
        return Cluster(stage=stage, pods=ordered)

    @property
    def world_size(self) -> int:
        return sum(len(p.workers) for p in self.pods)

    @property
    def num_pods(self) -> int:
        return len(self.pods)

    @property
    def num_devices(self) -> int:
        return sum(p.num_devices for p in self.pods)

    def leader(self) -> Pod:
        return self.pods[0]

    @property
    def coordinator(self) -> str:
        """Endpoint of worker 0 — what ``jax.distributed.initialize`` dials."""
        return self.pods[0].workers[0].endpoint

    def worker_endpoints(self) -> List[str]:
        return [
            w.endpoint
            for pod in self.pods
            for w in sorted(pod.workers, key=lambda w: w.rank_in_pod)
        ]

    def pod_ids(self) -> List[str]:
        return [p.pod_id for p in self.pods]

    def get_pod(self, pod_id: str) -> Optional[Pod]:
        for pod in self.pods:
            if pod.pod_id == pod_id:
                return pod
        return None

    def to_json(self) -> bytes:
        return json.dumps(
            {"stage": self.stage, "pods": [p.to_dict() for p in self.pods]},
            sort_keys=True,
        ).encode()

    @staticmethod
    def from_json(data: bytes) -> "Cluster":
        d = json.loads(data)
        return Cluster(stage=d["stage"], pods=[Pod.from_dict(p) for p in d["pods"]])

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Cluster):
            return NotImplemented
        return self.to_json() == other.to_json()

    def membership_equals(self, other: "Cluster") -> bool:
        """Same pods in the same rank order (ignores stage stamp)."""
        return self.pod_ids() == other.pod_ids()
