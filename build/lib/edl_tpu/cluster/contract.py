"""Store-layout and process-contract constants shared by the launcher and
the worker-side train context.

Both sides of the elastic handshake must agree on these, but the launcher
must not import the jax-heavy train package and workers must not import
the launcher — so the shared values live here, in the light cluster
package both already depend on.
"""

# services under the job root (see launch/launcher.py module docstring for
# the full layout)
RES_SERVICE = "pod_resource"
RANK_SERVICE = "pod_rank"
DRAIN_SERVICE = "drain"
CLUSTER_SERVICE = "cluster"
STATUS_SERVICE = "status"
JOB_SERVICE = "job"
# hot restage: worker {pod_id}.{rank_in_pod} -> stage it adopted in-process
HOTADOPT_SERVICE = "hotadopt"

# exit code a hot-restage-capable worker uses to say "I could not adopt
# the new stage in-process; respawn me" — the launcher treats it as a
# restage request, not a job failure (only in hot-restage mode)
HOT_RESTAGE_EXIT = 75

COMPLETE = b"COMPLETE"
