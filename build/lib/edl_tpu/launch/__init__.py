from edl_tpu.launch.launcher import ElasticLauncher, launch

__all__ = ["ElasticLauncher", "launch"]
