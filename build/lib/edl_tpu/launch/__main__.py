import sys

from edl_tpu.launch.launcher import main

sys.exit(main())
