from edl_tpu.harness.resize import ResizeHarness

__all__ = ["ResizeHarness"]
