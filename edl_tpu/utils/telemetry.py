"""Stage telemetry: resize-transition events + worker throughput meters.

What the reference never measures (SURVEY §6 derives a ≤5% img/s/chip
resize-loss target but the reference only has wall-clock demos): every
elastic transition here leaves a queryable record in the store, so the
resize cost — drain trigger → workers killed → new stage published →
first step of the new stage — is a number, not a log grep.

Store layout under the job root:

- ``events/{stage}/{kind}.{who}`` -> ``%.6f`` unix timestamp (permanent).
  Kinds: ``drain`` (CAS winner of the new token), ``killed`` (per pod,
  once its old workers are dead), ``published`` (leader), ``first_step``
  (per worker, first completed+blocked step of the stage).
- ``metrics/{stage}/w{rank}`` -> JSON ``{"sps": samples/s, "steps": N,
  "batch": B, "t0": ..., "t1": ...}`` — steady-state meter, excluding the
  first ``warmup`` steps (compile time is transition cost, counted via
  ``first_step``, not steady-state cost).

Writers are fire-and-forget (telemetry must never take down training);
:func:`collect` parses the whole keyspace back into dicts for
``tools/resize_bench.py``.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

from edl_tpu.store.client import StoreClient, connect_store
from edl_tpu.utils.log import get_logger

logger = get_logger("telemetry")

EVENTS_SERVICE = "events"
METRICS_SERVICE = "metrics"
STAGES_SERVICE = "stages"
CACHE_SERVICE = "cachestats"


def _prefix(job_id: str, service: str) -> str:
    return "/%s/%s/" % (job_id, service)


def record_event(
    client: StoreClient,
    job_id: str,
    stage: str,
    kind: str,
    who: str = "",
    ts: Optional[float] = None,
) -> None:
    """Permanent, fire-and-forget event record (also marked on the
    process's span timeline, so merged traces show every transition
    phase alongside the spans it interrupts)."""
    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.obs import trace as obs_trace

    obs_metrics.counter(
        "edl_resize_events_total", "resize-transition events recorded, by kind"
    ).inc(kind=kind)
    obs_trace.get_tracer().instant(
        "resize_" + kind, ts_wall=ts, stage=stage[:8], who=who
    )
    key = "%s%s/%s.%s" % (_prefix(job_id, EVENTS_SERVICE), stage, kind, who)
    try:
        client.put(key, ("%.6f" % (ts if ts is not None else time.time())).encode())
    except Exception as exc:  # noqa: BLE001 — never take down the caller
        logger.warning("event %s/%s not recorded: %s", kind, who, exc)


def record_stage(
    client: StoreClient, job_id: str, stage: str, info: dict
) -> None:
    """Permanent per-stage facts (world size, pod count, publish ts)."""
    key = _prefix(job_id, STAGES_SERVICE) + stage
    try:
        client.put(key, json.dumps(info).encode())
    except Exception as exc:  # noqa: BLE001
        logger.warning("stage record %s not written: %s", stage[:8], exc)


def record_cache_stats(
    client: StoreClient, job_id: str, stage: str, rank: int, stats: dict
) -> None:
    """Per-stage compile-cache counters (``train.aot.cache_event_counts``
    deltas: hits/misses/writes this worker saw reaching its first step),
    so resize_bench can tell "cache load" from "real compile" per stage
    without parsing logs. Fire-and-forget like every telemetry writer."""
    key = "%s%s/w%d" % (_prefix(job_id, CACHE_SERVICE), stage, rank)
    try:
        client.put(key, json.dumps(stats).encode())
    except Exception as exc:  # noqa: BLE001
        logger.warning("cache stats not recorded: %s", exc)


class WorkerMeter:
    """Per-worker throughput meter for one elastic stage.

    Call :meth:`step` after each completed (blocked-on) train step; the
    first call records the stage's ``first_step`` event, steady-state
    samples/s excludes the first ``warmup`` steps and is re-published
    every ``report_every`` steps and on :meth:`close`.
    """

    _RECONNECT_EVERY = 10.0  # s between connect attempts when store is down

    def __init__(
        self,
        env,
        batch_per_step: int,
        warmup: int = 2,
        report_every: int = 10,
        client: Optional[StoreClient] = None,
    ) -> None:
        self.env = env
        self.batch = batch_per_step
        self.warmup = warmup
        self.report_every = report_every
        self._client = client
        self._owns_client = client is None
        self._steps = 0
        # interval math runs on time.monotonic() (an NTP step mid-stage
        # must not corrupt samples/s); wall clocks are kept separately
        # for the cross-process event/metric records.
        self._first_ts: Optional[float] = None  # wall, first_step event
        self._first_recorded = False
        self._t_warm: Optional[float] = None  # monotonic
        self._t_warm_wall: Optional[float] = None
        self._last: Optional[float] = None  # monotonic
        self._last_wall: Optional[float] = None
        self._next_connect = 0.0

    def _store(self) -> Optional[StoreClient]:
        if self._client is None and self.env.store_endpoint:
            # bounded, rate-limited connect: an unreachable store must not
            # stall the training loop on every step
            now = time.time()
            if now < self._next_connect:
                return None
            self._next_connect = now + self._RECONNECT_EVERY
            try:
                self._client = connect_store(self.env.store_endpoint, timeout=1.0)
            except Exception as exc:  # noqa: BLE001
                logger.warning("meter store connect failed: %s", exc)
        return self._client

    def step(self, n: int = 1) -> None:
        now = time.monotonic()
        wall = time.time()
        if self._steps == 0:
            self._first_ts = wall
        self._steps += n
        self._last = now
        self._last_wall = wall
        client = self._store()
        if client is not None and not self._first_recorded and self._first_ts is not None:
            # recorded lazily (with the true timestamp) so a slow store
            # connect can't lose the stage's first_step event
            record_event(
                client, self.env.job_id, self.env.stage, "first_step",
                "w%d" % self.env.global_rank, ts=self._first_ts,
            )
            self._first_recorded = True
        if self._steps == self.warmup:
            self._t_warm = now
            self._t_warm_wall = wall
        if (
            self._steps > self.warmup
            and (self._steps - self.warmup) % self.report_every == 0
        ):
            self._publish()

    def samples_per_s(self) -> Optional[float]:
        if self._t_warm is None or self._last is None or self._last <= self._t_warm:
            return None
        return (self._steps - self.warmup) * self.batch / (self._last - self._t_warm)

    def _publish(self) -> None:
        client = self._store()
        sps = self.samples_per_s()
        if client is None or sps is None:
            return
        key = "%s%s/w%d" % (
            _prefix(self.env.job_id, METRICS_SERVICE),
            self.env.stage,
            self.env.global_rank,
        )
        try:
            client.put(
                key,
                json.dumps(
                    {
                        "sps": round(sps, 2),
                        "steps": self._steps,
                        "batch": self.batch,
                        "t0": self._t_warm_wall,
                        "t1": self._last_wall,
                        "world": self.env.world_size,
                    }
                ).encode(),
            )
        except Exception as exc:  # noqa: BLE001
            logger.warning("meter publish failed: %s", exc)

    def close(self) -> None:
        self._publish()
        if self._owns_client and self._client is not None:
            self._client.close()
            self._client = None


def collect(client: StoreClient, job_id: str) -> Dict[str, dict]:
    """Read back the full telemetry keyspace.

    Returns ``{"events": {stage: {kind: {who: ts}}},
    "metrics": {stage: {worker: dict}}, "stages": {stage: dict},
    "cache": {stage: {worker: dict}},
    "dropped": N}`` where ``dropped`` counts malformed entries (corrupt
    value, unparseable key) — logged and counted instead of silently
    swallowed, so ``tools/resize_bench.py`` / ``tools/edl_top.py`` can
    flag a corrupt run.
    """
    dropped = 0
    events: Dict[str, Dict[str, Dict[str, float]]] = {}
    rows, _rev = client.range(_prefix(job_id, EVENTS_SERVICE))
    plen = len(_prefix(job_id, EVENTS_SERVICE))
    for key, value, _c, _m in rows:
        rest = key[plen:]
        stage, _, tail = rest.partition("/")
        kind, _, who = tail.partition(".")
        try:
            events.setdefault(stage, {}).setdefault(kind, {})[who] = float(value)
        except ValueError:
            dropped += 1
            logger.debug("malformed event %r: value %r", key, value[:40])
    metrics: Dict[str, Dict[str, dict]] = {}
    rows, _rev = client.range(_prefix(job_id, METRICS_SERVICE))
    plen = len(_prefix(job_id, METRICS_SERVICE))
    for key, value, _c, _m in rows:
        rest = key[plen:]
        stage, _, worker = rest.partition("/")
        try:
            metrics.setdefault(stage, {})[worker] = json.loads(value)
        except ValueError:
            dropped += 1
            logger.debug("malformed meter %r: value %r", key, value[:40])
    stage_info: Dict[str, dict] = {}
    rows, _rev = client.range(_prefix(job_id, STAGES_SERVICE))
    plen = len(_prefix(job_id, STAGES_SERVICE))
    for key, value, _c, _m in rows:
        try:
            stage_info[key[plen:]] = json.loads(value)
        except ValueError:
            dropped += 1
            logger.debug("malformed stage record %r", key)
    cache_stats: Dict[str, Dict[str, dict]] = {}
    rows, _rev = client.range(_prefix(job_id, CACHE_SERVICE))
    plen = len(_prefix(job_id, CACHE_SERVICE))
    for key, value, _c, _m in rows:
        rest = key[plen:]
        stage, _, worker = rest.partition("/")
        try:
            cache_stats.setdefault(stage, {})[worker] = json.loads(value)
        except ValueError:
            dropped += 1
            logger.debug("malformed cache stats %r: value %r", key, value[:40])
    if dropped:
        # per-entry details go to debug: pollers (edl-top) call collect
        # every few seconds and must not re-spam N lines per refresh
        logger.warning(
            "telemetry keyspace for %s had %d malformed entr%s",
            job_id, dropped, "y" if dropped == 1 else "ies",
        )
        # scraper-side export: each collect pass that still observes
        # malformed entries advances the counter, so a nonzero RATE means
        # "the keyspace is corrupt right now" — the monitor plane's
        # telemetry-dropped-keys rule fires on exactly that
        from edl_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(
            "edl_obs_telemetry_dropped_keys_total",
            "malformed telemetry entries observed per collect() pass",
        ).inc(dropped)
    return {
        "events": events,
        "metrics": metrics,
        "stages": stage_info,
        "cache": cache_stats,
        "dropped": dropped,
    }
