"""Op-latency timeline tracer, env-gated. DEPRECATED shim.

Capability parity with the reference's ``_TimeLine`` distill profiler
(python/edl/distill/timeline.py:19-44): per-pid op-latency lines to stderr
when ``EDL_TIMELINE=1`` (the reference's env was ``DISTILL_READER_PROFILE``),
a zero-cost no-op otherwise. Used at queue get/put and RPC boundaries of the
distill pipeline and the data service.
"""

from __future__ import annotations

import os
import sys
import time


class _ObsTimeline:
    """reset()/record() adapter over :mod:`edl_tpu.obs.trace`.

    Keeps the legacy contract — a ``record(op)`` closes the span opened
    by the previous ``reset()``/``record()`` and prints the stderr line —
    while ALSO recording the span into the process tracer, so
    ``EDL_TIMELINE=1`` runs show up in ``EDL_TRACE_DIR`` exports and the
    merged job timeline.
    """

    __slots__ = ("_pid", "_t0", "_tracer")

    def __init__(self, feed_tracer: bool = True) -> None:
        self._pid = os.getpid()
        self._tracer = None
        if feed_tracer:
            from edl_tpu.obs.trace import get_tracer

            self._tracer = get_tracer()
        self._t0 = time.monotonic()

    def reset(self) -> None:
        self._t0 = time.monotonic()

    def record(self, op: str, **extra) -> None:
        now = time.monotonic()
        if self._tracer is not None:
            self._tracer.record(op, self._t0, now - self._t0, **extra)
        fields = "".join(" %s=%s" % kv for kv in sorted(extra.items()))
        sys.stderr.write(
            "[timeline] pid=%d op=%s span=%.6f ts=%.6f%s\n"
            % (self._pid, op, now - self._t0, time.time(), fields)
        )
        self._t0 = now


class _NopTimeline:
    __slots__ = ()

    def reset(self) -> None:
        pass

    def record(self, op: str, **extra) -> None:
        pass


def make_timeline(feed_tracer: bool = True):
    """Return a tracer; real when EDL_TIMELINE=1 else a no-op.

    .. deprecated:: Use :func:`edl_tpu.obs.trace.span` /
       :func:`edl_tpu.obs.trace.get_tracer` directly — the obs tracer is
       bounded, always-on, and exports mergeable Chrome traces. This
       shim survives only so ``EDL_TIMELINE=1`` keeps printing the
       legacy stderr lines (by default *also* feeding the obs tracer;
       pass ``feed_tracer=False`` at call sites whose interval is
       already span-recorded directly, or the ring holds every op
       twice).
    """
    if os.environ.get("EDL_TIMELINE", "0") == "1":
        return _ObsTimeline(feed_tracer)
    return _NopTimeline()
