"""One shared retry primitive for every reconnect/re-register loop.

The tree grew four hand-rolled retry loops (store client reconnect +
idempotent-request retry, registration lease restore, distill predict
attempts), each with its own backoff constants and none observable. This
helper replaces them: jittered exponential backoff, an optional overall
deadline, a ``give_up`` predicate for owners that can be closed mid-retry,
and an ``edl_rpc_retries_total`` counter (labeled by call site) so the
chaos store-blip scenario — and production incidents — show *which* path
is retrying and how hard.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from edl_tpu.obs.metrics import counter as _counter
from edl_tpu.utils.log import get_logger

logger = get_logger("utils.retry")

T = TypeVar("T")

_M_RETRIES = _counter(
    "edl_rpc_retries_total",
    "retry attempts after a retryable failure, by call site",
)


def backoff_delays(
    base_delay: float = 0.05,
    max_delay: float = 1.0,
    factor: float = 2.0,
    jitter: float = 0.1,
    rng: Optional[random.Random] = None,
):
    """Infinite generator of jittered exponential backoff delays.

    Jitter is multiplicative (+-``jitter`` fraction) so herds of
    reconnecting clients de-synchronize; pass a seeded ``rng`` for
    deterministic schedules (chaos scenarios).
    """
    rand = rng if rng is not None else random
    delay = base_delay
    while True:
        yield max(0.0, delay * (1.0 + rand.uniform(-jitter, jitter)))
        delay = min(delay * factor, max_delay)


def retry_call(
    fn: Callable[[], T],
    *,
    what: str,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    retries: Optional[int] = None,
    deadline: Optional[float] = None,
    base_delay: float = 0.05,
    max_delay: float = 1.0,
    factor: float = 2.0,
    jitter: float = 0.1,
    give_up: Optional[Callable[[], bool]] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn`` until it returns, a non-retryable error escapes, or the
    budget runs out.

    ``retries`` bounds the number of *re*-attempts (None = unbounded);
    ``deadline`` is an overall wall-clock budget in seconds; ``give_up``
    is polled before every sleep so a closing owner stops retrying
    immediately. The final failure re-raises the last exception.
    """
    deadline_at = None if deadline is None else time.monotonic() + deadline
    delays = backoff_delays(base_delay, max_delay, factor, jitter, rng)
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as exc:
            attempt += 1
            exhausted = (
                (retries is not None and attempt > retries)
                or (deadline_at is not None and time.monotonic() >= deadline_at)
                or (give_up is not None and give_up())
            )
            if exhausted:
                raise
            _M_RETRIES.inc(what=what)
            if on_retry is not None:
                on_retry(attempt, exc)
            pause = next(delays)
            if deadline_at is not None:
                pause = min(pause, max(0.0, deadline_at - time.monotonic()))
            sleep(pause)
