"""Exception hierarchy + wire (de)serialization.

Capability parity with the reference's ``EdlException`` family and its
serialize-by-name / re-raise-on-client scheme
(python/edl/utils/exceptions.py:20-57, protos/common.proto:20-23). Here the
wire form is a plain ``{"etype": ..., "detail": ...}`` dict carried inside
the framed-RPC error response instead of a protobuf Status.
"""

from __future__ import annotations


class EdlError(Exception):
    """Base class for all edl_tpu errors."""


class EdlRegisterError(EdlError):
    pass


class EdlBarrierError(EdlError):
    pass


class EdlRankError(EdlError):
    pass


class EdlLeaderError(EdlError):
    pass


class EdlStoreError(EdlError):
    pass


class EdlLeaseExpiredError(EdlStoreError):
    pass


class EdlCompactedError(EdlStoreError):
    """A watch-resume revision has been compacted out of the history ring."""


class EdlConnectionError(EdlStoreError):
    pass


class EdlNotPrimaryError(EdlConnectionError):
    """The contacted store is a warm standby: it replicates but does not
    serve. Subclasses ``EdlConnectionError`` so every existing retry path
    treats it as "try again" — the client advances to the next endpoint
    first, so the retry lands on the primary."""


class EdlFencedError(EdlConnectionError):
    """The contacted store was fenced by a higher epoch (a standby
    promoted past it). Like :class:`EdlNotPrimaryError`, retry-shaped:
    clients fail over to the promoted primary."""


class EdlDataError(EdlError):
    pass


class EdlStopIteration(EdlError):
    """Distill pipeline sentinel: the remote generator is exhausted."""


class EdlInternalError(EdlError):
    pass


class EdlOverloadError(EdlError):
    """The teacher shed this request at admission (queue full, or the
    deadline-aware admission test predicted a miss). Deliberately NOT a
    subclass of :class:`EdlConnectionError`: overload means the server is
    alive and telling you to back off — retry machinery must meter it
    against a budget instead of hammering the same endpoint."""

    def __init__(
        self, detail: str = "", qdepth: int = 0, est_wait_ms: float = 0.0
    ) -> None:
        super().__init__(detail)
        self.qdepth = qdepth
        self.est_wait_ms = est_wait_ms


_BY_NAME = {
    cls.__name__: cls
    for cls in (
        EdlError,
        EdlRegisterError,
        EdlBarrierError,
        EdlRankError,
        EdlLeaderError,
        EdlStoreError,
        EdlLeaseExpiredError,
        EdlCompactedError,
        EdlConnectionError,
        EdlNotPrimaryError,
        EdlFencedError,
        EdlDataError,
        EdlStopIteration,
        EdlInternalError,
        EdlOverloadError,
    )
}


def serialize_exception(exc: BaseException) -> dict:
    return {"etype": type(exc).__name__, "detail": str(exc)}


def deserialize_exception(status: dict) -> Exception:
    cls = _BY_NAME.get(status.get("etype", ""), EdlInternalError)
    return cls(status.get("detail", ""))


def raise_from_status(status: dict) -> None:
    raise deserialize_exception(status)
