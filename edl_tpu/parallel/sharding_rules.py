"""Rule-based parameter sharding: param-path patterns → PartitionSpecs.

The TPU-idiomatic replacement for the reference's strategy flags: instead
of choosing NCCL topologies, you declare where each weight lives on the
mesh and XLA inserts the collectives (scaling-book recipe: pick a mesh,
annotate shardings, let the compiler work).

``TRANSFORMER_TP_RULES`` is the Megatron-style split for
:class:`~edl_tpu.models.transformer.TransformerLM`: q/k/v and MLP
up/gate are column-parallel (output dim on ``tp``), attn-out and MLP
down are row-parallel (input dim on ``tp``), embeddings shard the vocab.
Compose with fsdp by putting both axes in the spec.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Set, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.utils.log import get_logger

logger = get_logger("parallel.sharding_rules")

Rules = Sequence[Tuple[str, P]]

# (param-suffix, axis, axis-size) triples already warned about a
# non-divisible rule axis. Keyed by SUFFIX, not full path: a transformer
# emits the same mismatch once per layer per tensor
# ("/layers_0/attn/q/kernel", "/layers_1/attn/q/kernel", ...) and
# MULTICHIP_r05 shows that flooding the log — one line per distinct
# parameter KIND per mesh axis says everything a misconfiguration needs
# to say, while intentional GQA replication stays a single line per
# process. The axis SIZE stays in the key: a hot restage reuses this
# process with a different mesh, and a new mismatch under the new size
# must not be swallowed by the old stage's warning.
_warned_suffixes: Set[Tuple[str, str, int]] = set()


def _param_suffix(path: str, parts: int = 3) -> str:
    """The path's trailing components ("attn/q/kernel"): stable across
    layer indices, distinct across parameter kinds."""
    return "/".join(path.strip("/").split("/")[-parts:])

TRANSFORMER_TP_RULES: List[Tuple[str, P]] = [
    (r".*/attn/[qkv]/kernel", P(None, "tp", None)),   # col: [d, H, hd]
    (r".*/attn/o/kernel", P("tp", None, None)),        # row: [H, hd, d]
    (r".*/mlp/(gate|up)/kernel", P(None, "tp")),       # col: [d, ff]
    (r".*/mlp/down/kernel", P("tp", None)),            # row: [ff, d]
    (r".*/embed/embedding", P("tp", None)),            # vocab-sharded
    (r".*/lm_head/kernel", P(None, "tp")),             # vocab-sharded out
]


def spec_for_path(path: str, rules: Rules) -> P:
    for pattern, spec in rules:
        if re.fullmatch(pattern, path):
            return spec
    return P()


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/" + "/".join(parts)


def shard_params_by_rules(mesh: Mesh, params, rules: Rules):
    """device_put each param according to the first matching rule.

    Axes named in a rule but absent from ``mesh`` are dropped (so the same
    rules work on a dp-only mesh), and a rule axis that does not divide
    the param dimension falls back to replicating THAT dimension — e.g.
    GQA's narrowed k/v head axis (2 KV heads on a tp=4 mesh): the grouped
    projections replicate while q/o keep their Megatron split, which is
    the standard GQA+TP layout."""
    names = set(mesh.axis_names)

    def place(key_path, x):
        spec = spec_for_path(_path_str(key_path), rules)
        resolved = []
        for dim, axis in enumerate(spec):
            if axis not in names:
                resolved.append(None)
                continue
            if x.shape[dim] % mesh.shape[axis]:
                # axis doesn't divide: replicate this dim — correct for
                # GQA's narrowed kv heads, but a silent loss of the TP
                # memory saving if it hits q/o/FFN kernels by mistake
                path = _path_str(key_path)
                warn_key = (_param_suffix(path), axis, mesh.shape[axis])
                if warn_key not in _warned_suffixes:
                    _warned_suffixes.add(warn_key)
                    logger.warning(
                        "param %s dim %d (size %d) not divisible by mesh "
                        "axis %r (size %d): replicating that dimension "
                        "(further params with suffix %r suppressed)",
                        path,
                        dim,
                        x.shape[dim],
                        axis,
                        mesh.shape[axis],
                        warn_key[0],
                    )
                resolved.append(None)
            else:
                resolved.append(axis)
        return jax.device_put(x, NamedSharding(mesh, P(*resolved)))

    return jax.tree_util.tree_map_with_path(place, params)
