"""Device meshes and sharding helpers — the TPU data plane.

Where the reference delegates its data plane to NCCL allreduce inside
Paddle fleet (SURVEY §2 comms row: EDL only passes ``nccl_comm_num`` and
endpoints through, train_with_fleet.py:92-93), the edl_tpu compute path is
jit/pjit over a ``jax.sharding.Mesh``: gradients of replicated parameters
against dp-sharded batches make XLA insert the all-reduce over ICI/DCN
itself; hierarchical allreduce, overlap, and topology mapping are the
compiler's job, not flags.

Axis conventions (used across models and train steps):
  ``dp``   data parallel (batch axis)
  ``fsdp`` parameter/optimizer sharding (zero-style)
  ``tp``   tensor parallel (hidden dims)
  ``sp``   sequence/context parallel (ring attention)
  ``ep``   expert parallel (MoE)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXES = ("dp", "fsdp", "tp", "sp", "ep")


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh from an axis->size dict; one axis may be -1 (fill).

    ``make_mesh()`` = pure data parallel over every visible device.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    axes = dict(axes)
    fills = [k for k, v in axes.items() if v == -1]
    if len(fills) > 1:
        raise ValueError("only one axis may be -1, got %r" % fills)
    fixed = math.prod(v for v in axes.values() if v != -1)
    if fills:
        if n % fixed:
            raise ValueError("cannot fill %r: %d devices / %d" % (fills[0], n, fixed))
        axes[fills[0]] = n // fixed
    if math.prod(axes.values()) != n:
        raise ValueError("axes %r do not cover %d devices" % (axes, n))
    shape = tuple(axes.values())
    try:
        # topology-aware placement: keeps inner axes on ICI neighbors
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices)
        )
    except (ImportError, ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Leading-dim sharding for batches over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "dp"):
    """device_put a batch pytree with its leading dim over ``axis``."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def _fsdp_spec(shape: Sequence[int], axis_size: int, axis: str) -> P:
    """Shard the largest divisible dim over ``axis``; replicate otherwise."""
    if not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if shape[dim] >= axis_size and shape[dim] % axis_size == 0:
            spec = [None] * len(shape)
            spec[dim] = axis
            return P(*spec)
    return P()


def shard_params_fsdp(mesh: Mesh, params, axis: str = "fsdp"):
    """ZeRO-style parameter sharding: each tensor's largest divisible dim is
    split over the fsdp axis (the TPU-idiomatic replacement for the
    reference's parameter-server role split, SURVEY §2 C-PS row)."""
    axis_size = mesh.shape[axis]

    def place(x):
        spec = _fsdp_spec(x.shape, axis_size, axis)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, params)


def sharded_seq_attention(
    per_shard_fn,
    local_fn,
    q,
    k,
    v,
    mesh,
    sp_axis: str = "sp",
    dp_axis=None,
):
    """Shared jit-compatible wrapper for sequence-parallel attention
    (ring and Ulysses): ``[B, H, T, D]`` global arrays, batch over
    ``dp_axis`` when present, sequence over ``sp_axis``. ``per_shard_fn``
    runs under shard_map on ``[B, H, T/sp, D]`` shards; ``local_fn`` is
    the sp == 1 passthrough (and both must agree numerically)."""
    import jax
    from jax.sharding import PartitionSpec as P

    if mesh.shape[sp_axis] == 1:
        return local_fn(q, k, v)
    batch = dp_axis if dp_axis in mesh.axis_names else None
    spec = P(batch, None, sp_axis, None)
    return jax.shard_map(
        per_shard_fn, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False,
    )(q, k, v)
