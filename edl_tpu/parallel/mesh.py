"""Device meshes and sharding helpers — the TPU data plane.

Where the reference delegates its data plane to NCCL allreduce inside
Paddle fleet (SURVEY §2 comms row: EDL only passes ``nccl_comm_num`` and
endpoints through, train_with_fleet.py:92-93), the edl_tpu compute path is
jit/pjit over a ``jax.sharding.Mesh``: gradients of replicated parameters
against dp-sharded batches make XLA insert the all-reduce over ICI/DCN
itself; hierarchical allreduce, overlap, and topology mapping are the
compiler's job, not flags.

Axis conventions (used across models and train steps):
  ``dp``   data parallel (batch axis)
  ``fsdp`` parameter/optimizer sharding (zero-style)
  ``tp``   tensor parallel (hidden dims)
  ``sp``   sequence/context parallel (ring attention)
  ``ep``   expert parallel (MoE)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXES = ("dp", "fsdp", "tp", "sp", "ep")


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh from an axis->size dict; one axis may be -1 (fill).

    ``make_mesh()`` = pure data parallel over every visible device.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    axes = dict(axes)
    fills = [k for k, v in axes.items() if v == -1]
    if len(fills) > 1:
        raise ValueError("only one axis may be -1, got %r" % fills)
    fixed = math.prod(v for v in axes.values() if v != -1)
    if fills:
        if n % fixed:
            raise ValueError("cannot fill %r: %d devices / %d" % (fills[0], n, fixed))
        axes[fills[0]] = n // fixed
    if math.prod(axes.values()) != n:
        raise ValueError("axes %r do not cover %d devices" % (axes, n))
    shape = tuple(axes.values())
    try:
        # topology-aware placement: keeps inner axes on ICI neighbors
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices)
        )
    except (ImportError, ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes))


def make_hybrid_mesh(
    dcn_axes: Dict[str, int],
    ici_axes: Dict[str, int],
    devices: Optional[Sequence] = None,
    slice_count: Optional[int] = None,
) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` span slices (data-center network),
    ``ici_axes`` stay within a slice (chip interconnect).

    The scaling-book recipe for multislice TPU: communication-heavy axes
    (tp/fsdp/sp) must ride ICI inside one slice; only gradient-size
    traffic (dp) should cross the slower DCN. Axis order in the mesh is
    dcn axes first, then ici axes, and device placement guarantees every
    ici-axis neighbor group lives inside a single slice.

    Slice membership comes from ``device.slice_index`` (real multislice
    TPU). ``slice_count`` overrides it by partitioning the device list
    evenly in order — how the CPU tests model 2 virtual slices; it also
    lets a single-slice job pretend N=1.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    dcn_size = math.prod(dcn_axes.values())
    ici_size = math.prod(ici_axes.values())
    if dcn_size * ici_size != len(devices):
        raise ValueError(
            "dcn %r x ici %r != %d devices" % (dcn_axes, ici_axes, len(devices))
        )
    if slice_count is None:
        groups: Dict[int, list] = {}
        for d in devices:
            groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
        slices = [groups[k] for k in sorted(groups)]
    else:
        if len(devices) % slice_count:
            raise ValueError("%d devices / %d slices" % (len(devices), slice_count))
        per = len(devices) // slice_count
        slices = [devices[i * per : (i + 1) * per] for i in range(slice_count)]
    if len(slices) != dcn_size:
        raise ValueError(
            "dcn axes %r need %d slices, found %d" % (dcn_axes, dcn_size, len(slices))
        )
    if any(len(s) != ici_size for s in slices):
        raise ValueError("ici axes %r do not cover every slice" % (ici_axes,))
    if slice_count is None:
        # real multislice topology: let jax place devices ICI-optimally.
        # The helper requires mesh_shape and dcn_mesh_shape of EQUAL rank
        # (per-dim products give the final dims), so pad each side with 1s:
        # dims = (dcn..., 1...) * (1..., ici...) -> dcn dims then ici dims.
        try:
            from jax.experimental import mesh_utils

            n_dcn, n_ici = len(dcn_axes), len(ici_axes)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                (1,) * n_dcn + tuple(ici_axes.values()),
                tuple(dcn_axes.values()) + (1,) * n_ici,
                devices=devices,
            )
            return Mesh(dev_array, tuple(dcn_axes) + tuple(ici_axes))
        except (ImportError, AttributeError):
            pass  # old jax: manual layout below
        except ValueError as exc:
            # jax raises ValueError both for missing slice metadata (CPU /
            # old runtimes — fallback is correct) and for genuine topology
            # misconfiguration (fallback would silently degrade ICI
            # locality), so the fallback must not be silent
            import warnings

            warnings.warn(
                "create_hybrid_device_mesh failed (%s); falling back to "
                "device-order layout whose intra-slice placement is not "
                "ICI-optimized" % (exc,),
                RuntimeWarning,
                stacklevel=2,
            )
    # slice_count override (virtual slices) — the documented in-order
    # partition IS the layout; the helper would regroup by real
    # slice_index and silently ignore the override
    per_slice = [
        np.asarray(s).reshape(tuple(ici_axes.values())) for s in slices
    ]
    dev_array = np.stack(per_slice).reshape(
        tuple(dcn_axes.values()) + tuple(ici_axes.values())
    )
    return Mesh(dev_array, tuple(dcn_axes) + tuple(ici_axes))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Leading-dim sharding for batches over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def device_put_global(x, sharding: NamedSharding):
    """Place a host value onto a (possibly multi-process) sharding.

    GLOBAL-value semantics: ``x`` is the whole array and EVERY process
    must pass the same value (the params case — each process computed or
    restored the identical tree). For per-process batch rows use
    ``shard_batch``/``prefetch_to_device``, whose cross-process path has
    local-rows semantics instead. Single-process meshes use plain
    ``device_put``; cross-process, the global array is assembled via
    ``make_array_from_callback`` so each process materializes only its
    addressable shards.
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def device_put_local_rows(x, sharding: NamedSharding):
    """Place per-process rows onto a (possibly multi-process) sharding.

    LOCAL-rows semantics: on a cross-process mesh each process passes
    ITS OWN rows and the global array is their concatenation — the
    dispatcher/loader pattern where every worker reads different
    records. Contrast ``device_put_global`` (same full value everywhere).
    Shared by ``shard_batch`` and ``prefetch_to_device``.
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def shard_batch(mesh: Mesh, batch, axis: str = "dp"):
    """Place a batch pytree with its leading dim sharded over ``axis``
    (local-rows semantics on cross-process meshes, see
    ``device_put_local_rows``)."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: device_put_local_rows(x, sharding), batch)


def _fsdp_spec(shape: Sequence[int], axis_size: int, axis: str) -> P:
    """Shard the largest divisible dim over ``axis``; replicate otherwise."""
    if not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if shape[dim] >= axis_size and shape[dim] % axis_size == 0:
            spec = [None] * len(shape)
            spec[dim] = axis
            return P(*spec)
    return P()


def shard_params_fsdp(mesh: Mesh, params, axis: str = "fsdp"):
    """ZeRO-style parameter sharding: each tensor's largest divisible dim is
    split over the fsdp axis (the TPU-idiomatic replacement for the
    reference's parameter-server role split, SURVEY §2 C-PS row)."""
    axis_size = mesh.shape[axis]

    def place(x):
        spec = _fsdp_spec(x.shape, axis_size, axis)
        return device_put_global(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, params)


def sharded_seq_attention(
    per_shard_fn,
    local_fn,
    q,
    k,
    v,
    mesh,
    sp_axis: str = "sp",
    dp_axis=None,
):
    """Shared jit-compatible wrapper for sequence-parallel attention
    (ring and Ulysses): ``[B, H, T, D]`` global arrays, batch over
    ``dp_axis`` when present, sequence over ``sp_axis``. ``per_shard_fn``
    runs under shard_map on ``[B, H, T/sp, D]`` shards; ``local_fn`` is
    the sp == 1 passthrough (and both must agree numerically)."""
    from jax.sharding import PartitionSpec as P

    from edl_tpu.parallel.compat import shard_map

    if mesh.shape[sp_axis] == 1:
        return local_fn(q, k, v)
    batch = dp_axis if dp_axis in mesh.axis_names else None
    spec = P(batch, None, sp_axis, None)
    return shard_map(
        per_shard_fn, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False,
    )(q, k, v)
