"""Ulysses-style all-to-all sequence parallelism over the ``sp`` axis.

The second of the two standard long-context strategies (the task charter
makes both first-class; the reference has neither — SURVEY §5): where
ring attention (``edl_tpu.parallel.ring``) keeps the sequence sharded and
rotates KV around the ring, Ulysses (DeepSpeed-Ulysses, Jacobs et al.
2023 — public recipe, re-implemented here on XLA collectives) RESHARDS
with two ``lax.all_to_all``s: sequence-sharded ``[B, H, T/sp, D]``
becomes head-sharded ``[B, H/sp, T, D]``, each device runs EXACT local
attention over the full sequence on its head group (through the Pallas
flash kernel), and a second all-to-all restores sequence sharding.

Trade-offs vs the ring: communication is two all-to-alls of activation
size (independent of sequence length per hop) instead of ``sp`` KV
rotations, attention itself needs no online-softmax merging (exact, any
mask), but head count bounds the parallelism (``H % sp == 0``) and peak
memory holds the full-sequence scores blockwise per head group.

Grouped k/v (GQA/MQA) shrink the KV communication: when kv heads still
divide ``sp`` the kv all-to-all carries 1/group the bytes; when each
device's whole q chunk maps to one kv head (MQA across a wide mesh) the
kv a2a is replaced by an all-gather of the tiny grouped KV plus a local
head slice; anything in between broadcasts like the old MHA path.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from edl_tpu.ops.attention import flash_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Callable = flash_attention,
) -> jax.Array:
    """Call under shard_map with ``q, k, v`` holding this device's
    sequence shard ``[B, H, T_local, D]``; returns the same shard of the
    attention output."""
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    h_kv = k.shape[1]
    if h % sp:
        raise ValueError(
            "ulysses needs heads %% sp == 0 (got H=%d, sp=%d); use ring "
            "attention for head counts the mesh can't divide" % (h, sp)
        )
    if h_kv < 1 or h % h_kv:
        # same contract the kernels enforce (_gqa_group) — checked here
        # too because the gather branch below would otherwise truncate
        # the group and silently slice the wrong kv head
        raise ValueError(
            "kv heads (%d) must divide q heads (%d)" % (h_kv, h)
        )
    # seq-sharded -> head-sharded: split H into sp groups, gather T.
    # all_to_all concatenates by source index, and source i holds sequence
    # shard i, so the gathered axis comes out in global sequence order.
    reshard = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1,
        concat_axis=2, tiled=True,
    )
    h_local = h // sp
    group = h // h_kv  # _gqa_group validated divisibility at the model
    if h_kv % sp == 0:
        # grouped heads still divide the mesh: the kv all-to-all carries
        # 1/group the bytes of the old broadcast-MHA path, and device
        # r's q chunk [r*h/sp, ...) lines up exactly with kv chunk
        # [r*h_kv/sp, ...) because group divides h_local here
        k2, v2 = reshard(k), reshard(v)
    elif group % h_local == 0 and h_kv < h_local:
        # small-kv regime (e.g. MQA across a wide mesh): kv heads can't
        # split over sp, but each device's whole q chunk maps to ONE kv
        # head (h_local divides group, so chunks never straddle a group
        # boundary). Gather the full grouped KV — B*h_kv*T*D bytes, vs
        # B*H*T/sp*D for the broadcast a2a: smaller whenever
        # h_kv < h_local — and slice this device's head out. all_gather's
        # VJP is the matching reduce-scatter; the slice's zero-pads.
        r = jax.lax.axis_index(axis_name)
        my_kv = (r * h_local) // group

        def gather_slice(x):
            full = jax.lax.all_gather(
                x, axis_name, axis=2, tiled=True
            )  # [B, h_kv, T, D]
            return jax.lax.dynamic_slice_in_dim(full, my_kv, 1, axis=1)

        k2, v2 = gather_slice(k), gather_slice(v)
    else:
        # awkward middle ground (kv heads neither divide sp nor collapse
        # to one per device): broadcast to full width like the old MHA
        # path — correct everywhere, just without the volume saving
        k2, v2 = (
            reshard(jnp.repeat(t, group, axis=1)) for t in (k, v)
        )
    out = attn_fn(
        reshard(q), k2, v2, causal=causal, scale=scale
    )  # [B, H/sp, T, D] — exact attention, full sequence, my head group
    # head-sharded -> seq-sharded (the transpose collective; autodiff of
    # all_to_all is the reverse all_to_all, so grads reshard for free)
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


ulysses_attention.supports_gqa = True  # grouped k/v shrink the a2a/gather


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    sp_axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    scale: Optional[float] = None,
    attn_fn: Callable = flash_attention,
) -> jax.Array:
    """jit-compatible wrapper mirroring ``ring_attention_sharded``:
    ``[B, H, T, D]`` global arrays, batch over ``dp_axis``, sequence over
    ``sp_axis``; ``attn_fn`` is the local kernel on every path (including
    the sp == 1 passthrough)."""
    from edl_tpu.parallel.mesh import sharded_seq_attention

    return sharded_seq_attention(
        functools.partial(
            ulysses_attention, axis_name=sp_axis, causal=causal,
            scale=scale, attn_fn=attn_fn,
        ),
        functools.partial(attn_fn, causal=causal, scale=scale),
        q, k, v, mesh, sp_axis=sp_axis, dp_axis=dp_axis,
    )


ulysses_attention_sharded.supports_gqa = True
