from edl_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
    shard_params_fsdp,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params_fsdp",
]
