"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Net-new versus the reference (SURVEY §5: long-context support is absent
there; the task charter makes it first-class here). The design follows
the public ring-attention recipe (Liu et al. 2023, blockwise parallel
transformers): the sequence is sharded over ``sp``; each device keeps its
query shard resident while KV shards rotate around the ring via
``lax.ppermute`` (XLA lowers this to ICI neighbor exchanges that overlap
with the per-step attention compute).

Both directions are BLOCKWISE end to end, so the [T_local, T_local]
score matrix never exists in HBM either:

- forward: each rotation runs the Pallas flash kernel, which returns
  ``(o, lse)``; partial results merge in logsumexp space (the online-
  softmax recurrence lifted to whole shards). Causal runs skip
  fully-masked rotations entirely (``lax.cond`` on the ring distance),
  and the diagonal rotation uses the kernel's causal mask.
- backward: a custom VJP replays the rotations with the flash BACKWARD
  kernels (:func:`edl_tpu.ops.attention.flash_block_grads`): the global
  ``lse``/``delta`` residuals make each KV shard's (dq, dk, dv)
  contribution independent, dq accumulates in place, and dk/dv
  accumulators rotate around the ring WITH their shard until everything
  lands back home.

Max context scales linearly with ring size; per-device live state is one
KV shard + one gradient accumulator.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from edl_tpu.ops.attention import (
    NEG_INF,
    flash_attention,
    flash_block_grads,
    flash_with_lse,
)


def _step_dispatch(s, my, causal, full_fn, diag_fn, masked_fn):
    """THE step-visibility rule, shared by forward and backward: at step
    ``s`` this device holds the KV shard of source ``(my - s) mod n``;
    under end-aligned global causal masking that shard is fully visible
    when ``s <= my`` (strictly earlier positions), diagonal when
    ``s == 0``, and fully masked otherwise."""
    if not causal:
        return full_fn()
    if s == 0:
        return diag_fn()
    return jax.lax.cond(s <= my, full_fn, masked_fn)


def _step_attention(q, k_cur, v_cur, s, my, causal, scale):
    """One rotation's (o, lse)."""
    b, h, t, d = q.shape
    return _step_dispatch(
        s, my, causal,
        lambda: flash_with_lse(q, k_cur, v_cur, causal=False, scale=scale),
        lambda: flash_with_lse(q, k_cur, v_cur, causal=True, scale=scale),
        lambda: (
            jnp.zeros((b, h, t, d), q.dtype),
            jnp.full((b, h, t), NEG_INF, jnp.float32),
        ),
    )


def _ring_forward(q, k, v, causal, scale, axis_name):
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, h, t, d = q.shape

    m = jnp.full((b, h, t, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t, 1), jnp.float32)
    acc = jnp.zeros((b, h, t, d), jnp.float32)
    k_cur, v_cur = k, v
    # static unroll: n is a trace-time constant (mesh axis size), and the
    # unrolled form lets XLA overlap each step's ppermute with compute
    for s in range(n):
        o_s, lse_s = _step_attention(q, k_cur, v_cur, s, my, causal, scale)
        lse_col = lse_s[..., None]
        m_new = jnp.maximum(m, lse_col)
        c_old = jnp.exp(m - m_new)
        c_s = jnp.exp(lse_col - m_new)
        l = l * c_old + c_s
        acc = acc * c_old + o_s.astype(jnp.float32) * c_s
        m = m_new
        if s < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).astype(q.dtype)
    lse = (m + jnp.log(l_safe))[..., 0]  # global logsumexp, [B, H, T]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, causal, scale, axis_name):
    out, _ = _ring_forward(q, k, v, causal, scale, axis_name)
    return out


def _ring_fwd(q, k, v, causal, scale, axis_name):
    out, lse = _ring_forward(q, k, v, causal, scale, axis_name)
    return out, (q, k, v, out, lse)


def _ring_bwd(causal, scale, axis_name, residuals, g):
    q, k, v, o, lse = residuals
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # global row correction: sum_d dO O (the softmax-jacobian term)
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )

    def zeros_like3(a, b_, c):
        return (
            jnp.zeros(a.shape, a.dtype),
            jnp.zeros(b_.shape, b_.dtype),
            jnp.zeros(c.shape, c.dtype),
        )

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur = k, v
    for s in range(n):
        dq_s, dk_s, dv_s = _step_dispatch(
            s, my, causal,
            lambda: flash_block_grads(
                q, k_cur, v_cur, g, lse, delta, causal=False, scale=scale
            ),
            lambda: flash_block_grads(
                q, k_cur, v_cur, g, lse, delta, causal=True, scale=scale
            ),
            lambda: zeros_like3(q, k_cur, v_cur),
        )
        dq = dq + dq_s.astype(jnp.float32)
        dk_acc = dk_acc + dk_s.astype(jnp.float32)
        dv_acc = dv_acc + dv_s.astype(jnp.float32)
        if s < n - 1:
            # accumulators travel WITH their shard so every holder adds
            # its contribution to the right gradient
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    # after n-1 rotations the accumulators describe shard (my+1); one more
    # hop brings every shard's full gradient home
    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (
        dq.astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention across a ring. Call under shard_map/pmap with ``q, k, v``
    holding this device's sequence shard ``[B, H, T_local, D]``.

    Grouped k/v (GQA/MQA: fewer kv heads, dividing q's) pass straight
    through — the rotating KV shards and the dk/dv accumulators stay at
    the GROUPED width, cutting the ring's ppermute volume (its scaling
    bottleneck) by ``num_heads/num_kv_heads``; the flash kernels
    underneath read grouped rows natively (ops/attention.py)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring(q, k, v, causal, scale, axis_name)


ring_attention.supports_gqa = True  # models may pass grouped k/v


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    sp_axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    scale: Optional[float] = None,
) -> jax.Array:
    """jit-compatible wrapper: shard_map ring attention over the mesh.

    ``[B, H, T, D]`` global arrays, batch over ``dp_axis``, sequence over
    ``sp_axis``."""
    from edl_tpu.parallel.mesh import sharded_seq_attention

    return sharded_seq_attention(
        functools.partial(
            ring_attention, axis_name=sp_axis, causal=causal, scale=scale
        ),
        functools.partial(flash_attention, causal=causal, scale=scale),
        q, k, v, mesh, sp_axis=sp_axis, dp_axis=dp_axis,
    )


ring_attention_sharded.supports_gqa = True  # grouped k/v ride the ring
