"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Net-new versus the reference (SURVEY §5: long-context support is absent
there; the task charter makes it first-class here). The design follows
the public ring-attention recipe (Liu et al. 2023, blockwise parallel
transformers): the sequence is sharded over ``sp``; each device keeps its
query shard resident while KV shards rotate around the ring via
``lax.ppermute`` (XLA lowers this to ICI neighbor exchanges that overlap
with the per-step attention compute), and partial results merge with the
same online-softmax recurrence flash attention uses — so the full
[T, T] score matrix never exists anywhere and max context scales linearly
with the ring size.

Use inside ``shard_map`` over a mesh with an ``sp`` axis (see
``ring_attention_sharded``); per-step local attention runs through the
Pallas flash kernel on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.ops.attention import NEG_INF, attention_reference, flash_attention


def _local_attention_stats(q, k, v, mask, scale):
    """One ring step: blockwise attention returning (numerator, rowmax,
    denominator) so steps merge with the online-softmax recurrence."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    num = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return num, m_safe, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention across a ring. Call under shard_map/pmap with ``q, k, v``
    holding this device's sequence shard ``[B, H, T_local, D]``."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]

    b, h, _, d = q.shape
    m = jnp.full((b, h, t_local, 1), NEG_INF / 2, jnp.float32)
    l = jnp.zeros((b, h, t_local, 1), jnp.float32)
    acc = jnp.zeros((b, h, t_local, d), jnp.float32)

    def step(s, carry):
        k_cur, v_cur, m, l, acc = carry
        src = (my - s) % n  # whose shard we hold this step
        mask = None
        if causal:
            qpos = my * t_local + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, t_local, t_local), 2
            )
            kpos = src * t_local + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, t_local, t_local), 3
            )
            mask = qpos >= kpos
        num, m_s, l_s = _local_attention_stats(q, k_cur, v_cur, mask, scale)
        m_new = jnp.maximum(m, m_s)
        c_old = jnp.exp(m - m_new)
        c_s = jnp.exp(m_s - m_new)
        l = l * c_old + l_s * c_s
        acc = acc * c_old + num * c_s
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l, acc

    carry = (k, v, m, l, acc)
    # static unroll: n is a trace-time constant (mesh axis size), and the
    # unrolled form lets XLA overlap each step's ppermute with compute
    for s in range(n):
        carry = step(s, carry)
    _, _, m, l, acc = carry
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = False,
    sp_axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    scale: Optional[float] = None,
) -> jax.Array:
    """jit-compatible wrapper: shard_map ring attention over the mesh.

    ``[B, H, T, D]`` global arrays, batch over ``dp_axis``, sequence over
    ``sp_axis``."""
    from edl_tpu.parallel.mesh import sharded_seq_attention

    return sharded_seq_attention(
        functools.partial(
            ring_attention, axis_name=sp_axis, causal=causal, scale=scale
        ),
        functools.partial(flash_attention, causal=causal, scale=scale),
        q, k, v, mesh, sp_axis=sp_axis, dp_axis=dp_axis,
    )
