"""Version-guarded jax API shims for the parallel plane.

``shard_map`` moved across jax releases: old trees export it only as
``jax.experimental.shard_map.shard_map`` (kwarg ``check_rep``), newer
ones graduate it to ``jax.shard_map`` (kwarg renamed ``check_vma``).
Call sites import :func:`shard_map` from here and always speak the new
spelling; the shim translates for the experimental fallback.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_experimental(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
