"""Pipeline parallelism: GPipe fill-drain schedule over the ``pp`` axis.

Net-new versus the reference (SURVEY §2 parallelism inventory: no
TP/PP/SP anywhere in its tree), built the TPU way: each ``pp`` rank holds
one pipeline stage's weights (a stacked ``[PP, ...]`` pytree sharded on
the leading axis); microbatch activations flow rank-to-rank via
``lax.ppermute`` inside a ``lax.scan`` over schedule ticks, so XLA lowers
stage handoff to ICI neighbor exchanges and the backward pipeline falls
out of autodiff (the transpose of ``ppermute`` is the reverse permute).

The schedule is plain GPipe: ``M`` microbatches drain through ``PP``
stages in ``M + PP - 1`` ticks; bubble ticks compute on zeros and are
masked out of the result. Peak per-device live state is one microbatch
activation per tick plus the stage weights — combine with
``jax.checkpoint`` on the stage fn for long pipelines.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_shard(stage_fn, num_micro: int, axis: str, params, x):
    """Runs on ONE pp rank inside shard_map.

    ``params``: this rank's stage weights (leading stage axis stripped to
    size 1 by shard_map; squeezed here). ``x``: [M, mb, ...] microbatches
    (replicated over pp).
    """
    pp = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), params)
    micro_shape = x.shape[1:]
    ticks = num_micro + pp - 1
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        prev_out, outputs = carry
        # activation arriving from the previous stage this tick
        incoming = jax.lax.ppermute(prev_out, axis, fwd_perm)
        # stage 0 injects microbatch t (zeros once the pipe is draining)
        feed = jax.lax.cond(
            t < num_micro,
            lambda: jax.lax.dynamic_index_in_dim(
                x, jnp.minimum(t, num_micro - 1), keepdims=False
            ),
            lambda: jnp.zeros(micro_shape, x.dtype),
        )
        my_input = jnp.where(rank == 0, feed, incoming)
        out = stage_fn(params, my_input)
        # last rank banks microbatch (t - pp + 1) once the pipe is full
        mb_idx = t - (pp - 1)
        outputs = jax.lax.cond(
            (rank == pp - 1) & (mb_idx >= 0),
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, out, jnp.maximum(mb_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        return (out, outputs), None

    zeros_out = jnp.zeros(micro_shape, x.dtype)
    outputs0 = jnp.zeros((num_micro,) + micro_shape, x.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (zeros_out, outputs0), jnp.arange(ticks)
    )
    # deliver the last stage's outputs to every rank (grads flow back the
    # same all-reduce); non-last ranks contribute zeros
    outputs = jnp.where(rank == pp - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
):
    """Apply a ``PP``-stage pipeline to ``x``.

    ``stage_fn(stage_params, micro) -> micro`` must preserve the
    microbatch shape (classic repeated-block pipelining). ``stacked_params``
    is a pytree with leading stage axis ``PP`` (shard it over ``axis``).
    ``x``: [batch, ...]; batch must divide into ``num_microbatches``.

    Returns stage ``PP-1``'s outputs with shape ``x.shape``.
    """
    if axis not in mesh.shape:
        raise ValueError("mesh has no %r axis (axes: %r)" % (axis, mesh.axis_names))
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            "batch %d not divisible into %d microbatches"
            % (batch, num_microbatches)
        )
    mb = batch // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )
    fn = partial(_pipeline_shard, stage_fn, num_microbatches, axis)
    out = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, micro)
    return out.reshape(x.shape)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees (one per pp rank) into the
    leading-axis form ``pipeline_apply`` expects."""
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params
    )
