"""Pipeline parallelism: GPipe fill-drain schedule over the ``pp`` axis.

Net-new versus the reference (SURVEY §2 parallelism inventory: no
TP/PP/SP anywhere in its tree), built the TPU way: each ``pp`` rank holds
one pipeline stage's weights (a stacked ``[PP, ...]`` pytree sharded on
the leading axis); microbatch activations flow rank-to-rank via
``lax.ppermute`` inside a ``lax.scan`` over schedule ticks, so XLA lowers
stage handoff to ICI neighbor exchanges and the backward pipeline falls
out of autodiff (the transpose of ``ppermute`` is the reverse permute).

The schedule is plain GPipe: ``M`` microbatches drain through ``PP``
stages in ``M + PP - 1`` ticks (``pipeline_efficiency`` gives the ideal
``M / (M + PP - 1)`` utilization bound); bubble ticks compute on zeros.
Peak per-device live state is one microbatch activation per tick plus the
stage weights — combine with ``jax.checkpoint`` on the stage fn for long
pipelines.

Beyond the repeated-block body, the schedule supports *non-shape-
preserving* first and last stages (``first_fn``/``last_fn``): the first
rank maps the raw feed (e.g. token ids) into the circulating activation
shape, the last rank maps activations into outputs (e.g. logits, or a
per-example loss so only scalars ever leave the pipeline). Both run
under ``lax.cond`` on the rank index, so only the owning rank pays their
FLOPs. Results are delivered by stacking each rank's output bank on a
pp-sharded leading axis and slicing the last entry — a broadcast of the
real data only, not a ``psum`` over PP-1 banks of zeros.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.parallel.compat import shard_map


def pipeline_efficiency(num_microbatches: int, pp: int) -> float:
    """GPipe ideal utilization: M busy ticks out of M + PP - 1 total."""
    return num_microbatches / (num_microbatches + pp - 1)


def _pipeline_shard(
    body_fn,
    first_fn,
    last_fn,
    num_micro: int,
    axis: str,
    body_params,
    first_params,
    last_params,
    x,
    last_aux,
):
    """Runs on ONE pp rank inside shard_map.

    ``body_params``: this rank's stage weights (leading stage axis
    stripped to size 1 by shard_map; squeezed here). ``x``: [M, mb, ...]
    microbatch feeds (replicated over pp). ``first_params``/``last_params``
    are replicated; their compute is rank-gated by ``lax.cond``.
    ``last_aux``: optional [M, ...] per-microbatch side input handed to
    ``last_fn`` (e.g. targets for an in-pipeline loss).
    """
    pp = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    body_params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), body_params)
    feed_shape = x.shape[1:]

    feed_sd = jax.ShapeDtypeStruct(feed_shape, x.dtype)
    if first_fn is not None:
        act_sd = jax.eval_shape(first_fn, first_params, feed_sd)
    else:
        act_sd = feed_sd
    if act_sd.shape != feed_shape and first_fn is None:
        raise ValueError("shape-changing input requires first_fn")
    out_sd = jax.eval_shape(body_fn, body_params, act_sd)
    if out_sd.shape != act_sd.shape or out_sd.dtype != act_sd.dtype:
        raise ValueError(
            "body_fn must preserve the activation shape/dtype "
            "(%r -> %r); shape changes belong in first_fn/last_fn"
            % (act_sd, out_sd)
        )
    if last_fn is not None:
        if last_aux is not None:
            aux_sd = jax.ShapeDtypeStruct(last_aux.shape[1:], last_aux.dtype)
            y_sd = jax.eval_shape(last_fn, last_params, act_sd, aux_sd)
        else:
            y_sd = jax.eval_shape(last_fn, last_params, act_sd)
    else:
        y_sd = act_sd

    ticks = num_micro + pp - 1
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        prev_out, outputs = carry
        # activation arriving from the previous stage this tick
        incoming = jax.lax.ppermute(prev_out, axis, fwd_perm)
        # stage 0 injects microbatch t (zeros once the pipe is draining)
        feed = jax.lax.cond(
            t < num_micro,
            lambda: jax.lax.dynamic_index_in_dim(
                x, jnp.minimum(t, num_micro - 1), keepdims=False
            ),
            lambda: jnp.zeros(feed_shape, x.dtype),
        )
        if first_fn is not None:
            my_input = jax.lax.cond(
                rank == 0,
                lambda: first_fn(first_params, feed),
                lambda: incoming,
            )
        else:
            my_input = jnp.where(rank == 0, feed, incoming)
        out = body_fn(body_params, my_input)
        # the microbatch the LAST rank just finished (valid once >= 0)
        mb_idx = t - (pp - 1)
        if last_fn is not None:
            if last_aux is not None:
                aux = jax.lax.dynamic_index_in_dim(
                    last_aux, jnp.clip(mb_idx, 0, num_micro - 1),
                    keepdims=False,
                )
                mk_y = lambda: last_fn(last_params, out, aux)
            else:
                mk_y = lambda: last_fn(last_params, out)
            y = jax.lax.cond(
                (rank == pp - 1) & (mb_idx >= 0),
                mk_y,
                lambda: jnp.zeros(y_sd.shape, y_sd.dtype),
            )
        else:
            y = out
        outputs = jax.lax.cond(
            mb_idx >= 0,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(mb_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        return (out, outputs), None

    zeros_out = jnp.zeros(act_sd.shape, act_sd.dtype)
    outputs0 = jnp.zeros((num_micro,) + y_sd.shape, y_sd.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (zeros_out, outputs0), jnp.arange(ticks)
    )
    # deliver by stacking banks on a pp-sharded leading axis; the caller
    # slices the last entry, so only the real data is ever broadcast
    # (non-last ranks' banks are dead stores XLA can sink)
    return outputs[None]


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    first_fn: Optional[Callable] = None,
    first_params: Any = None,
    last_fn: Optional[Callable] = None,
    last_params: Any = None,
    last_aux: Optional[jax.Array] = None,
    batch_axis: Optional[str] = None,
):
    """Apply a ``PP``-stage pipeline to ``x``.

    ``stage_fn(stage_params, micro) -> micro`` is the repeated body; it
    must preserve the circulating activation shape. ``stacked_params`` is
    a pytree with leading stage axis ``PP`` (sharded over ``axis``).
    ``x``: [batch, ...]; batch must divide into ``num_microbatches``.

    Optional non-shape-preserving edges:

    - ``first_fn(first_params, micro_feed) -> activation`` runs on rank 0
      only, mapping the raw feed (e.g. int tokens) into the activation
      the body circulates.
    - ``last_fn(last_params, activation[, aux]) -> y`` runs on the last
      rank only (e.g. head projection, or a per-example loss). ``aux``
      is ``last_aux[mb]``, an optional [batch, ...] side input (targets)
      microbatched alongside ``x``.
    - ``batch_axis``: mesh axis to shard the microbatch dimension over
      (data parallelism inside the pipeline; grads for replicated
      first/last params are psum'ed by the shard_map transpose).

    Returns the last stage's outputs, shape ``[batch, *y.shape[1:]]``
    (per-microbatch results are re-flattened when ``last_fn`` keeps the
    microbatch dimension; otherwise ``[M, *y.shape]``).
    """
    if axis not in mesh.shape:
        raise ValueError("mesh has no %r axis (axes: %r)" % (axis, mesh.axis_names))
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            "batch %d not divisible into %d microbatches"
            % (batch, num_microbatches)
        )
    mb = batch // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])
    aux = None
    if last_aux is not None:
        if last_aux.shape[0] != batch:
            raise ValueError(
                "last_aux batch %d != x batch %d" % (last_aux.shape[0], batch)
            )
        aux = last_aux.reshape(
            (num_microbatches, mb) + last_aux.shape[1:]
        )

    # pre-compute the per-microbatch output shape to build the out_spec
    # (and to sanity-check dp compatibility) before tracing the shard body
    mb_local = mb
    if batch_axis is not None:
        if batch_axis not in mesh.shape:
            raise ValueError(
                "mesh has no %r axis (axes: %r)"
                % (batch_axis, mesh.axis_names)
            )
        if mb % mesh.shape[batch_axis]:
            raise ValueError(
                "microbatch size %d not divisible by %r axis size %d"
                % (mb, batch_axis, mesh.shape[batch_axis])
            )
        mb_local = mb // mesh.shape[batch_axis]
    feed_sd = jax.ShapeDtypeStruct((mb_local,) + x.shape[1:], x.dtype)
    act_sd = (
        jax.eval_shape(first_fn, first_params, feed_sd)
        if first_fn is not None else feed_sd
    )
    if last_fn is not None:
        if aux is not None:
            aux_sd = jax.ShapeDtypeStruct(
                (mb_local,) + last_aux.shape[1:], last_aux.dtype
            )
            y_sd = jax.eval_shape(last_fn, last_params, act_sd, aux_sd)
        else:
            y_sd = jax.eval_shape(last_fn, last_params, act_sd)
    else:
        y_sd = act_sd
    keeps_mb = len(y_sd.shape) >= 1 and y_sd.shape[0] == mb_local
    if batch_axis is not None and not keeps_mb:
        raise ValueError(
            "batch_axis=%r requires last_fn to keep the microbatch "
            "dimension (got per-microbatch shape %r) — return per-example "
            "values (e.g. a [mb] loss vector) so dp shards aren't dropped"
            % (batch_axis, y_sd.shape)
        )

    param_specs = jax.tree.map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )
    data_spec = P(None, batch_axis)  # [M, mb, ...]: mb optionally dp-sharded
    out_spec = P(
        axis, None, *([batch_axis] + [None] * (len(y_sd.shape) - 1)
                      if keeps_mb else [None] * len(y_sd.shape))
    )
    fn = partial(
        _pipeline_shard, stage_fn, first_fn, last_fn, num_microbatches, axis
    )
    out = shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_specs, P(), P(), data_spec, data_spec),
        out_specs=out_spec,
        check_vma=False,
    )(stacked_params, first_params, last_params, micro, aux)
    out = out[-1]  # last rank's bank: [M, *y_shape]
    if out.ndim >= 2 and out.shape[1] == mb:
        return out.reshape((batch,) + out.shape[2:])
    return out


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees (one per pp rank) into the
    leading-axis form ``pipeline_apply`` expects."""
    return jax.tree.map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params
    )
