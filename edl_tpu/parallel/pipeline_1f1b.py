"""1F1B pipeline schedule: memory-bounded training over the ``pp`` axis.

GPipe (``edl_tpu.parallel.pipeline``) runs all forwards then lets
autodiff run all backwards, so per-device live activations grow with the
microbatch count M. The 1F1B schedule (Megatron's non-interleaved
pipeline) interleaves: after a warmup of ``PP-1-r`` forwards, rank ``r``
alternates one-forward-one-backward, so at most ~PP microbatch
activations are ever live per device — M can grow (shrinking the bubble,
``(PP-1)/(M+PP-1)``) without growing memory.

Because the backward IS part of the schedule, this module computes
``(loss, grads)`` directly (the Megatron shape) instead of being
differentiable: each backward tick runs ``jax.vjp`` over the composite
stage (recompute-based, so residual stash = one activation per in-flight
microbatch), gradients accumulate in place, and cotangents ride
``lax.ppermute`` one rank backward per tick.

Tick algebra (validated exhaustively in a schedule simulator up to PP=8,
M=33 before this was written — collisions, dependencies, and the mod-PP
stash reuse are all proven):

    F_m^r = r + m              (fill: m < PP-1-r)
    F_m^r = 2m + r             (steady: m >= PP-1-r)
    B_m^r = 2PP - 1 - r + 2m
    total ticks = 2(M + PP - 1); at most one op per (tick, rank);
    activations stash at slot m %% PP; cotangents always arrive exactly
    on their consuming tick.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.parallel.compat import shard_map


def _schedule(t, r, pp: int, num_micro: int):
    """Decode rank ``r``'s op at tick ``t``: (has_f, m_f, has_b, m_b)."""
    tr = t - r
    fill = (tr >= 0) & (t < pp - 1) & (tr < num_micro)
    m_steady = tr // 2
    steady = (
        (tr >= 0) & (tr % 2 == 0)
        & (m_steady >= pp - 1 - r) & (m_steady < num_micro)
    )
    has_f = fill | steady
    m_f = jnp.where(fill, tr, m_steady)
    tb = t - (2 * pp - 1 - r)
    has_b = (tb >= 0) & (tb % 2 == 0) & (tb // 2 < num_micro)
    m_b = tb // 2
    return has_f, jnp.clip(m_f, 0, num_micro - 1), has_b, jnp.clip(
        m_b, 0, num_micro - 1
    )


def _1f1b_shard(
    body_fn,
    first_fn,
    last_loss_fn,
    num_micro: int,
    axis: str,
    batch_axis,  # optional dp axis: grads/loss psum over it here
    batch_scale,  # 1 / (global example count) — the loss-mean seed
    body_params,
    first_params,
    last_params,
    feeds,
    aux,
):
    pp = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    body_params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), body_params)
    # non-cyclic: the wraparound edges would ship a full activation-sized
    # tensor every tick to ranks that discard it (missing pairs read as
    # zeros, which both receive paths treat correctly)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    bwd_perm = [(i + 1, i) for i in range(pp - 1)]

    feed_sd = jax.ShapeDtypeStruct(feeds.shape[1:], feeds.dtype)
    act_sd = jax.eval_shape(first_fn, first_params, feed_sd)
    mb = feeds.shape[1]

    def composite(body_p, first_p, last_p, act_in, feed, aux_m):
        """One rank's full stage: edge-in -> body -> edge-out. rank is
        closed over; lax.cond keeps the edges on their owning ranks."""
        x = jax.lax.cond(
            rank == 0,
            lambda: first_fn(first_p, feed),
            lambda: act_in,
        )
        y = body_fn(body_p, x)
        per_ex = jax.lax.cond(
            rank == pp - 1,
            lambda: last_loss_fn(last_p, y, aux_m),
            lambda: jnp.zeros((mb,), jnp.float32),
        )
        return y, per_ex

    zero_act = jnp.zeros(act_sd.shape, act_sd.dtype)
    zeros_body = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), body_params)
    zeros_first = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), first_params)
    zeros_last = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), last_params)

    carry = dict(
        in_stash=jnp.zeros((pp,) + act_sd.shape, act_sd.dtype),
        res_stash=jnp.zeros((pp,) + act_sd.shape, act_sd.dtype),
        recv_act=zero_act,
        recv_cot=jnp.zeros(act_sd.shape, act_sd.dtype),
        d_body=zeros_body,
        d_first=zeros_first,
        d_last=zeros_last,
        loss_sum=jnp.zeros((), jnp.float32),
    )

    def tick(t, c):
        # 1. bank an activation that arrived this tick (sender = rank-1's
        #    F at t-1); receives happen before this tick's own op
        s_has_f, s_m, _, _ = _schedule(t - 1, rank - 1, pp, num_micro)
        arrived = s_has_f & (rank > 0)
        slot = s_m % pp
        in_stash = jax.lax.cond(
            arrived,
            lambda: jax.lax.dynamic_update_index_in_dim(
                c["in_stash"], c["recv_act"], slot, axis=0
            ),
            lambda: c["in_stash"],
        )

        has_f, m_f, has_b, m_b = _schedule(t, rank, pp, num_micro)

        # 2. forward op
        def do_f():
            feed = jax.lax.dynamic_index_in_dim(feeds, m_f, keepdims=False)
            aux_m = jax.lax.dynamic_index_in_dim(aux, m_f, keepdims=False)
            act_in = jax.lax.dynamic_index_in_dim(
                in_stash, m_f % pp, keepdims=False
            )
            y, per_ex = composite(
                body_params, first_params, last_params, act_in, feed, aux_m
            )
            res = jax.lax.dynamic_update_index_in_dim(
                c["res_stash"], act_in, m_f % pp, axis=0
            )
            return y, res, jnp.sum(per_ex) * batch_scale

        def no_f():
            return zero_act, c["res_stash"], jnp.zeros((), jnp.float32)

        send_act, res_stash, loss_add = jax.lax.cond(has_f, do_f, no_f)

        # 3. backward op (recompute-vjp over the composite stage)
        def do_b():
            feed = jax.lax.dynamic_index_in_dim(feeds, m_b, keepdims=False)
            aux_m = jax.lax.dynamic_index_in_dim(aux, m_b, keepdims=False)
            act_in = jax.lax.dynamic_index_in_dim(
                res_stash, m_b % pp, keepdims=False
            )
            _, vjp_fn = jax.vjp(
                lambda bp, fp, lp, a: composite(bp, fp, lp, a, feed, aux_m),
                body_params, first_params, last_params, act_in,
            )
            cot_y = jnp.where(
                rank == pp - 1, jnp.zeros_like(c["recv_cot"]), c["recv_cot"]
            )
            seed = jnp.where(
                rank == pp - 1,
                jnp.full((mb,), batch_scale, jnp.float32),
                jnp.zeros((mb,), jnp.float32),
            )
            db, df, dl, dact = vjp_fn((cot_y, seed))
            return db, df, dl, dact.astype(act_sd.dtype)

        def no_b():
            return (
                zeros_body, zeros_first, zeros_last,
                jnp.zeros(act_sd.shape, act_sd.dtype),
            )

        db, df, dl, send_cot = jax.lax.cond(has_b, do_b, no_b)
        add = lambda acc, g: jax.tree.map(
            lambda a, b: a + b.astype(a.dtype), acc, g
        )
        return dict(
            in_stash=in_stash,
            res_stash=res_stash,
            recv_act=jax.lax.ppermute(send_act, axis, fwd_perm),
            recv_cot=jax.lax.ppermute(send_cot, axis, bwd_perm),
            d_body=add(c["d_body"], db),
            d_first=add(c["d_first"], df),
            d_last=add(c["d_last"], dl),
            loss_sum=c["loss_sum"] + loss_add,
        )

    ticks = 2 * (num_micro + pp - 1)
    c = jax.lax.fori_loop(0, ticks, tick, carry)

    # reductions: pp makes edge grads/loss whole (they live on one rank);
    # dp sums the per-shard contributions (each already scaled by the
    # GLOBAL example count, so sum = mean over the full batch)
    axes_all = (axis,) + ((batch_axis,) if batch_axis else ())
    loss = jax.lax.psum(c["loss_sum"], axes_all)
    d_first = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), c["d_first"])
    d_last = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), c["d_last"])
    d_body = c["d_body"]
    if batch_axis:
        d_body = jax.tree.map(
            lambda g: jax.lax.psum(g, batch_axis), d_body
        )
    d_body = jax.tree.map(lambda g: g[None], d_body)  # re-add pp axis
    return loss, d_body, d_first, d_last


def pipeline_1f1b_loss_and_grads(
    body_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    first_fn: Callable,
    first_params: Any,
    last_loss_fn: Callable,
    last_params: Any,
    last_aux: jax.Array,
    axis: str = "pp",
    batch_axis: Optional[str] = None,
):
    """Run the 1F1B schedule; returns ``(loss, (d_body, d_first, d_last))``.

    Same stage contract as :func:`edl_tpu.parallel.pipeline.pipeline_apply`
    with ``first_fn``/``last_fn`` mandatory and ``last_loss_fn(last_p, y,
    aux) -> [mb]`` per-example losses (the loss IS computed in-pipeline;
    this function is the gradient computation, not differentiable again).
    Requires ``num_microbatches >= PP``.
    """
    if axis not in mesh.shape:
        raise ValueError(
            "mesh has no %r axis (axes: %r)" % (axis, mesh.axis_names)
        )
    if batch_axis is not None and batch_axis not in mesh.shape:
        raise ValueError(
            "mesh has no %r axis (axes: %r)" % (batch_axis, mesh.axis_names)
        )
    pp = mesh.shape[axis]
    if num_microbatches < pp:
        raise ValueError(
            "1F1B needs num_microbatches >= pp (%d < %d)"
            % (num_microbatches, pp)
        )
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            "batch %d not divisible into %d microbatches"
            % (batch, num_microbatches)
        )
    if last_aux.shape[0] != batch:
        raise ValueError(
            "last_aux batch %d != x batch %d" % (last_aux.shape[0], batch)
        )
    mb = batch // num_microbatches
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            "microbatch %d not divisible by %r" % (mb, batch_axis)
        )
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])
    aux = last_aux.reshape((num_microbatches, mb) + last_aux.shape[1:])

    # mean over EVERY example globally (dp shards included: each shard's
    # per-example sums are scaled by the GLOBAL count, then psum'ed)
    batch_scale = 1.0 / (num_microbatches * mb)

    param_specs = jax.tree.map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )
    rep = lambda tree: jax.tree.map(lambda p: P(), tree)
    data_spec = P(None, batch_axis)

    fn = partial(
        _1f1b_shard, body_fn, first_fn, last_loss_fn, num_microbatches,
        axis, batch_axis, batch_scale,
    )
    loss, d_body, d_first, d_last = shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            param_specs, rep(first_params), rep(last_params),
            data_spec, data_spec,
        ),
        out_specs=(P(), param_specs, rep(first_params), rep(last_params)),
        check_vma=False,
    )(stacked_params, first_params, last_params, micro, aux)
    return loss, (d_body, d_first, d_last)
