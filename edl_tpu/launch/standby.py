"""Hot-standby worker shells: pre-paid process start for elastic restages.

The measured anatomy of a stop-resume restage on real TPU
(bench_results/resize_tpu_r4b.json: 26.8 s drain → first step) is almost
entirely worker COLD START: python interpreter + axon broker dial at
interpreter start + jax/flax/optax imports + backend init + compile-cache
load. The reference pays none of this (its workers re-exec into a warm
Paddle runtime in seconds, /root/reference/python/edl/collective/
launch.py:200-244, because Paddle program build was cheap); a TPU-native
framework must engineer the cost away instead.

A :class:`StandbyPool` keeps ``nproc`` *standby shells* per pod: fully
spawned worker processes (own session, PDEATHSIG armed) that have already
paid the interpreter start and the heavy imports, and then BLOCK on stdin
waiting for an activation message. When the launcher adopts a stage it
activates a standby instead of cold-spawning: one json line carries the
complete worker env, script path, args, and log path; the shell replaces
its environment, redirects stdout/stderr to the worker log, and
``runpy``-executes the training script in-process. The imports overlap
the control-plane convergence window (lease expiry of the dead pod →
drain → re-publish), which is exactly the window a fresh machine joining
a real elastic job would otherwise waste.

Eager backend init: when the elastic window pins the world to ONE worker
(``max_nodes * nproc_per_node == 1`` — the single-chip restart drill, or
any single-host job), the first standby also initializes the jax backend
at spawn, claiming the just-freed chip while the control plane converges.
Multi-worker windows must NOT do this: ``jax.distributed.initialize``
is required to run before backend init, and the coordinator address only
exists after publish. Replacement standbys (spawned while a live stage
owns the chip) never eager-init.

The standby is a strict fallback chain: a dead/unusable standby (or a
jax-env mismatch between spawn and activation) degrades to the normal
cold spawn in ``start_local_workers`` — activation can never be worse
than not having a pool.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

from edl_tpu.utils.log import get_logger

logger = get_logger("launch.standby")

# jax reads these at import time; an activation that disagrees with the
# spawn env would run the worker under the wrong platform/flags
_IMPORT_TIME_VARS = ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")


def standby_enabled(cli_flag: bool = False) -> bool:
    env = os.environ.get("EDL_STANDBY", "")
    if env in ("0", "off"):
        return False
    return cli_flag or env == "1"


class StandbyPool:
    """Per-pod pool of pre-imported worker shells.

    ``spawn_env`` is the complete base env for the shells (the launcher's
    env after proxy/axon stripping, plus the job's extra worker env) —
    activation replaces it wholesale with the stage's worker env, but the
    import-time jax variables must already be right at spawn.
    """

    def __init__(
        self,
        spawn_env: Dict[str, str],
        count: int = 1,
        eager: bool = False,
    ) -> None:
        self.spawn_env = dict(spawn_env)
        self.count = max(1, count)
        self._eager_budget = self.count if eager else 0
        self._mu = threading.Lock()
        self._idle: List[subprocess.Popen] = []
        self._stopped = False
        self._respawn_timer: Optional[threading.Timer] = None
        # replacements wait out the fresh workers' own startup (measured:
        # an immediate respawn's jax import contends with the worker's
        # first compile and ADDS downtime), and run niced for the same
        # reason — the initial pool races the first publish un-niced
        # because there is no live worker to protect yet
        self.respawn_delay = float(
            os.environ.get("EDL_STANDBY_RESPAWN_DELAY", "30")
        )
        self.ensure()

    # -- spawning ----------------------------------------------------------

    def _spawn_one(self, nice: bool = False) -> Optional[subprocess.Popen]:
        env = dict(self.spawn_env)
        if self._eager_budget > 0:
            env["EDL_STANDBY_EAGER"] = "1"
            self._eager_budget -= 1
        else:
            env.pop("EDL_STANDBY_EAGER", None)
        cmd = [sys.executable, "-u", "-m", "edl_tpu.launch.standby"]
        if nice:
            cmd = ["nice", "-n", "10"] + cmd
        try:
            proc = subprocess.Popen(  # edl: blocking-ok(fork+exec is ms-scale and top-ups are restage-rare; take() waits at most one pool refill — same budget as launch/process.py)
                cmd,
                env=env,
                stdin=subprocess.PIPE,
                start_new_session=True,
            )
        except OSError as exc:
            logger.warning("standby spawn failed: %s", exc)
            return None
        logger.info(
            "standby shell pid=%d spawned%s%s",
            proc.pid,
            " (eager backend init)" if env.get("EDL_STANDBY_EAGER") else "",
            " (niced replacement)" if nice else "",
        )
        return proc

    def ensure(self, nice: bool = False) -> None:
        """Top the pool back up to ``count`` live shells."""
        with self._mu:
            if self._stopped:
                return
            self._idle = [p for p in self._idle if p.poll() is None]
            while len(self._idle) < self.count:
                proc = self._spawn_one(nice=nice)
                if proc is None:
                    break
                self._idle.append(proc)

    def ensure_later(self) -> None:
        """Schedule a (niced) top-up after ``respawn_delay`` seconds —
        called right after activation, when an immediate respawn would
        contend with the just-activated workers' startup."""
        with self._mu:
            if self._stopped:
                return
            if self._respawn_timer is not None:
                self._respawn_timer.cancel()
            self._respawn_timer = threading.Timer(
                self.respawn_delay, self.ensure, kwargs={"nice": True}
            )
            self._respawn_timer.daemon = True
            self._respawn_timer.start()

    # -- activation --------------------------------------------------------

    def _env_compatible(self, env: Dict[str, str]) -> bool:
        for var in _IMPORT_TIME_VARS:
            if self.spawn_env.get(var, "") != env.get(var, ""):
                logger.info(
                    "standby declined: %s changed between spawn (%r) and "
                    "activation (%r)",
                    var, self.spawn_env.get(var, ""), env.get(var, ""),
                )
                return False
        return True

    def activate(
        self,
        env: Dict[str, str],
        training_script: str,
        training_args: Sequence[str],
        log_path: str = "",
    ) -> Optional[subprocess.Popen]:
        """Turn one standby shell into THE worker; None = use a cold spawn.

        The returned Popen is the worker process (same pid, same session,
        PDEATHSIG already armed); its exit code is the training script's.
        """
        if not self._env_compatible(env):
            return None
        with self._mu:
            while self._idle:
                proc = self._idle.pop(0)
                if proc.poll() is not None:
                    continue
                msg = json.dumps({
                    "env": dict(env),
                    "script": training_script,
                    "args": list(training_args),
                    "log_path": log_path,
                })
                try:
                    proc.stdin.write(msg.encode() + b"\n")
                    proc.stdin.flush()
                    proc.stdin.close()
                except (OSError, ValueError):
                    logger.warning(
                        "standby pid=%d unusable at activation; trying next",
                        proc.pid,
                    )
                    try:
                        proc.kill()
                    except OSError:
                        pass
                    continue
                logger.info(
                    "standby pid=%d activated as worker rank=%s",
                    proc.pid, env.get("EDL_WORKER_RANK", "?"),
                )
                return proc
        return None

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            if self._respawn_timer is not None:
                self._respawn_timer.cancel()
                self._respawn_timer = None
            procs, self._idle = self._idle, []
        for proc in procs:
            try:
                proc.kill()
            except OSError:
                pass
        for proc in procs:
            try:
                proc.wait(timeout=5.0)
            except (subprocess.TimeoutExpired, OSError):
                pass


# -- the shell child (python -m edl_tpu.launch.standby) ---------------------


def _child_main() -> None:
    # PDEATHSIG first: the shell must die with its launcher exactly like a
    # cold-spawned worker (worker_command's bootstrap arms the same flag)
    try:
        import ctypes
        import signal as _signal

        ctypes.CDLL("libc.so.6", use_errno=True).prctl(
            1, int(_signal.SIGKILL), 0, 0, 0
        )
    except Exception:
        pass  # non-glibc: orphan cleanup degrades to lease TTL

    # the pre-payment: heavy imports now, while the control plane converges.
    # NO device/backend access here unless eager (a live stage may own the
    # chip); model/train modules are import-only.
    import numpy  # noqa: F401

    try:
        import flax  # noqa: F401
        import jax
        import optax  # noqa: F401

        import edl_tpu.models  # noqa: F401
        import edl_tpu.parallel  # noqa: F401
        import edl_tpu.train  # noqa: F401

        if os.environ.get("EDL_STANDBY_EAGER") == "1":
            # single-worker window: claim the freed chip before the stage
            # publishes (see module docstring for why this is gated)
            try:
                dev = jax.devices()[0]
                logger.info("standby eager backend init: %s", dev.device_kind)
            except Exception as exc:
                logger.warning("standby eager init failed: %s", exc)
    except ImportError as exc:
        logger.warning("standby pre-import incomplete: %s", exc)

    line = sys.stdin.buffer.readline()
    if not line.strip():
        sys.exit(0)  # launcher closed the pipe without activating: retire
    spec = json.loads(line)

    env = spec.get("env", {})
    os.environ.clear()
    os.environ.update(env)
    log_path = spec.get("log_path", "")
    if log_path:
        fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        sys.stdout.flush()
        sys.stderr.flush()
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(fd)

    # cache exchange (train/aot.py): pull compile-cache entries peers
    # already compiled, HERE — the activation window overlaps the control
    # plane's own convergence (lease expiry -> drain -> publish), so the
    # transfer is free wall-clock. EDL_CACHE_PULLED tells train.init()
    # not to pull a second time. Best-effort: any failure degrades to
    # init()'s own bounded pull / a normal compile.
    if (
        os.environ.get("EDL_COMPILE_CACHE_DIR")
        and os.environ.get("EDL_STORE_ENDPOINT")
        and os.environ.get("EDL_CACHE_EXCHANGE", "1") != "0"
    ):
        try:
            from edl_tpu.train.aot import pull_missing

            stats = pull_missing(
                os.environ["EDL_COMPILE_CACHE_DIR"],
                endpoint=os.environ["EDL_STORE_ENDPOINT"],
                job_id=os.environ.get("EDL_JOB_ID", ""),
                own_pod=os.environ.get("EDL_POD_ID", ""),
            )
            # dedupe init()'s pull only when this one actually reached a
            # peer: activating before any manifest exists (or through a
            # store hiccup) returns peers=0, and suppressing the later
            # bounded pull would forfeit entries published moments later
            if stats.get("peers") or stats.get("pulled"):
                os.environ["EDL_CACHE_PULLED"] = "1"
        except Exception as exc:  # noqa: BLE001
            logger.warning("standby cache pull failed: %s", exc)

    import runpy

    script = spec["script"]
    sys.argv = [script] + list(spec.get("args", []))
    # `python script.py` puts the script's directory at sys.path[0];
    # run_path does not — match it, or script-local imports would work
    # cold-spawned but break through the standby fast path
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)))
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    _child_main()
