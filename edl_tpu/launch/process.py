"""Worker-process management: spawn, watch, terminate.

Capability parity with the reference's trainer process manager
(python/edl/utils/edl_process.py:39-166): one subprocess per worker with the
rank env contract injected, per-rank ``workerlog.N`` files, proxy env
stripped (the reference strips proxies so NCCL's socket bootstrap works,
edl_process.py:45-50 — the same applies to the JAX coordinator's gRPC
bootstrap), SIGTERM-then-SIGKILL teardown of the whole descendant tree via
psutil, and exit-code polling.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import psutil

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.cluster.model import Cluster, Pod, Worker
from edl_tpu.utils.log import get_logger

logger = get_logger("launch.process")

_FP_SPAWN = _fault_point(
    "launch.process.spawn",
    "per-worker spawn: delay (slow cold start) or kill (pod dies mid-spawn)",
)


@dataclass
class WorkerProc:
    worker: Worker
    proc: subprocess.Popen
    log_path: str = ""
    log_file: object = None
    exit_code: Optional[int] = None


# Child-side bootstrap run via ``python -c``: arms PR_SET_PDEATHSIG, then
# replaces itself with the real worker via execv (prctl survives a normal
# execve, so the final process keeps the death signal and an argv identical
# to a direct launch). This replaces the old preexec_fn approach: a
# preexec_fn forces subprocess onto the fork+Python-hooks path, which JAX's
# at-fork handler (rightly) flags as a deadlock hazard in any parent that
# has JAX loaded. The session split is handled by ``start_new_session=True``
# (C-side setsid with the same completed-before-Popen-returns guarantee).
# PDEATHSIG is armed a few ms later than preexec_fn would — the interpreter
# startup window — which only widens the already-nonzero fork-to-prctl gap.
_PDEATHSIG_BOOT = (
    "import ctypes, os, signal, sys\n"
    "try:\n"
    "    ctypes.CDLL('libc.so.6', use_errno=True)"
    ".prctl(1, int(signal.SIGKILL), 0, 0, 0)\n"
    "except Exception:\n"
    "    pass  # non-glibc: orphan cleanup degrades to lease TTL\n"
    "os.execv(sys.executable, [sys.executable, '-u'] + sys.argv[1:])\n"
)


def worker_command(training_script: str, training_args: Sequence[str]) -> List[str]:
    """argv for one worker: PDEATHSIG bootstrap + ``python -u script args``.

    PR_SET_PDEATHSIG delivers SIGKILL to the worker if the launcher dies
    without running its teardown (SIGKILL, OOM) — otherwise workers would
    outlive the launcher as orphans still holding TPU devices, and the
    respawned pod could not reacquire them.
    """
    return [sys.executable, "-c", _PDEATHSIG_BOOT, training_script, *training_args]


def base_worker_env(extra: Dict[str, str]) -> Dict[str, str]:
    """The launcher env with worker-hostile vars stripped — the common
    base of every spawned worker AND the standby shells (which must see
    the same import-time jax env a real worker would)."""
    env = dict(os.environ)
    for key in ("http_proxy", "https_proxy", "HTTP_PROXY", "HTTPS_PROXY"):
        env.pop(key, None)
    if extra.get("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "")).strip().lower() == "cpu":
        # a CPU-pinned job must not let the axon site hook dial the remote
        # TPU broker at interpreter start (it hangs every worker when the
        # tunnel is down); same spirit as the proxy strip above
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def worker_env(cluster: Cluster, pod: Pod, worker: Worker, extra: Dict[str, str]) -> Dict[str, str]:
    env = base_worker_env(extra)
    env.update(
        {
            "EDL_JOB_ID": extra.get("EDL_JOB_ID", ""),
            "EDL_POD_ID": pod.pod_id,
            "EDL_STAGE": cluster.stage,
            "EDL_WORKER_RANK": str(worker.global_rank),
            "EDL_WORKER_RANK_IN_POD": str(worker.rank_in_pod),
            "EDL_NUM_WORKERS": str(cluster.world_size),
            "EDL_COORDINATOR": cluster.coordinator,
            "EDL_WORKER_ENDPOINTS": ",".join(cluster.worker_endpoints()),
            # distributed tracing: the worker's restage trace records a
            # worker_boot segment from this wall-clock stamp, so the
            # interpreter+import cold start is attributed, not a gap
            "EDL_SPAWN_TS": repr(time.time()),
        }
    )
    env.update(extra)
    return env


def start_local_workers(
    cluster: Cluster,
    pod: Pod,
    training_script: str,
    training_args: Sequence[str],
    log_dir: str = "",
    extra_env: Optional[Dict[str, str]] = None,
    standby=None,
) -> List[WorkerProc]:
    """Spawn this pod's workers for ``cluster``'s stage. With a
    ``standby`` pool (launch/standby.py), each worker first tries to
    activate a pre-imported shell — the restage fast path — and cold
    spawns only when the pool declines."""
    procs: List[WorkerProc] = []
    extra = dict(extra_env or {})
    for worker in sorted(pod.workers, key=lambda w: w.rank_in_pod):
        if _FP_SPAWN.armed:
            _FP_SPAWN.fire(rank=worker.global_rank, stage=cluster.stage[:8])
        env = worker_env(cluster, pod, worker, extra)
        log_path, log_file = "", None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, "workerlog.%d" % worker.global_rank)
        proc = None
        if standby is not None:
            proc = standby.activate(
                env, training_script, training_args, log_path
            )
        if proc is None:
            if log_path:
                log_file = open(log_path, "ab")
            proc = subprocess.Popen(  # edl: blocking-ok(spawning workers IS the supervision action; fork+exec is bounded and restage-rare)
                worker_command(training_script, training_args),
                env=env,
                stdout=log_file if log_file else None,
                stderr=subprocess.STDOUT if log_file else None,
                start_new_session=True,
            )
        logger.info(
            "spawned worker rank=%d pid=%d stage=%s log=%s",
            worker.global_rank,
            proc.pid,
            cluster.stage[:8],
            log_path or "-",
        )
        procs.append(WorkerProc(worker, proc, log_path, log_file))
    if standby is not None:
        # replace what activation consumed — DEFERRED and niced, so the
        # respawned shells' imports don't contend with the new workers'
        # own startup (measured to add downtime when immediate)
        standby.ensure_later()
    return procs


def watch_local_workers(procs: List[WorkerProc]) -> Optional[int]:
    """Poll exit codes. Returns the first nonzero exit code, 0 when ALL
    workers exited cleanly, or None while any is still running."""
    alive = False
    for wp in procs:
        if wp.exit_code is None:
            wp.exit_code = wp.proc.poll()
        if wp.exit_code is None:
            alive = True
        elif wp.exit_code != 0:
            return wp.exit_code
    return None if alive else 0


def terminate_local_workers(procs: List[WorkerProc], grace: float = 3.0) -> None:
    """SIGTERM the worker trees, escalate to SIGKILL after ``grace``."""
    trees: List[psutil.Process] = []
    for wp in procs:
        if wp.proc.poll() is None:
            try:
                root = psutil.Process(wp.proc.pid)
                trees.extend([root, *root.children(recursive=True)])
            except psutil.NoSuchProcess:
                pass
    for proc in trees:
        try:
            proc.terminate()
        except psutil.NoSuchProcess:
            pass
    _, survivors = psutil.wait_procs(trees, timeout=grace)
    for proc in survivors:
        try:
            proc.kill()
        except psutil.NoSuchProcess:
            pass
    for wp in procs:
        try:
            wp.exit_code = wp.proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            logger.warning("worker pid=%d did not exit after SIGKILL", wp.proc.pid)
        if wp.log_file:
            try:
                wp.log_file.close()
            except OSError:
                pass
            wp.log_file = None
    if trees:
        logger.info("terminated %d worker process(es)", len(procs))


def close_worker_logs(procs: List[WorkerProc]) -> None:
    for wp in procs:
        if wp.log_file:
            try:
                wp.log_file.close()
            except OSError:
                pass
            wp.log_file = None
