"""The elastic launcher: rank racing, stage fencing, stop-resume supervision.

Capability parity with the reference's v0.2 flagship
(python/edl/collective/launch.py:162-244: register → barrier → watch →
spawn → on change kill/re-register/re-barrier/respawn), re-designed as an
explicit event-driven state machine — the reference's resize branch is its
weakest code (undefined names at launch.py:213/223) and its timing rests on
a hard-coded ``sleep(15) > lease TTL 10`` (launch.py:228-230); here every
transition is driven by store watch events and lease-expiry convergence.

Store layout under the job root (all via :class:`Registry`):

- ``pod_resource/{pod_id}`` -> Pod json, leased     (proof of life; ≙ reference
  PodResourceRegister, register.py:178)
- ``pod_rank/{slot}``       -> pod_id, leased       (contended ordering slots,
  0..max_nodes-1; ≙ PodRankRegister's rank race, register.py:72-114. Slots
  need NOT stay contiguous: the *minimum live slot* is the leader, so a
  dead rank-0 never wedges the job.)
- ``drain/token``           -> uuid                  (the fencing token. Any
  membership change is broadcast by CAS-bumping it; the value IS the stage
  every pod runs under — ≙ the reference's leader-stamped stage uuid,
  register.py:135 — so "which cluster generation am I in" and "was a drain
  requested" are one atomic datum.)
- ``cluster/current``       -> Cluster json          (leader-published; pods
  spawn workers if and only if they appear in it, with its stage in env)
- ``status/{pod_id}``       -> COMPLETE, permanent   (≙ register.complete())
- ``job/status``            -> COMPLETE              (leader-aggregated)
- ``preempt/{pod_id}``      -> json, permanent       (health plane: this pod
  received an advance preemption notice — SIGTERM/SIGUSR1 — and is
  draining. Payload ``{"deadline": wall-ts, "budget": s, "ts": ...}``.
  The leader treats noticed pods as already gone: the next generation
  excludes them with NO lease-expiry wait and NO failure-grace hold,
  while the pod's own workers see the key through their store watch,
  take an emergency best-effort checkpoint inside the budget, and exit
  ``DRAINED_EXIT`` — which every supervisor treats as a clean departure.)
- ``heartbeat/{pod}.{rank}`` -> json, permanent      (health plane: per-step
  worker progress ``{"step", "ts", "dt", "stage"}``. The launcher-side
  straggler watchdog compares each LOCAL worker's heartbeat age against
  a peer-median-derived deadline — a worker that is behind its peers AND
  quiet past the deadline is wedged (dead collective, stuck I/O) and is
  ejected via kill + drain; uniformly slow stages eject nobody.)

The elastic contract is stop-resume, exactly the reference's
(doc/edl_collective_design_doc.md): on any membership change every pod
kills its workers and the job restarts from the last checkpoint under a new
stage with the new world size. Worker processes get the ``EDL_*`` env
(process.py) and call :func:`edl_tpu.train.init`, which drives
``jax.distributed.initialize`` with the published coordinator — the
TPU-native replacement for the reference's ``PADDLE_TRAINER_*`` → NCCL
bootstrap (SURVEY §2 comms row).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from edl_tpu.chaos.plane import arm_from_env as _chaos_arm
from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.cluster.job_env import JobEnv, local_device_count
from edl_tpu.cluster.model import Cluster, Pod, Worker, new_uuid
from edl_tpu.discovery.registry import Registration, Registry
from edl_tpu.launch import process as procs_mod
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import memory as obs_memory
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.store.client import connect_store
from edl_tpu.utils import telemetry
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import find_free_ports, get_host_ip

logger = get_logger("launch")

_FP_LOOP = _fault_point(
    "launch.launcher.loop",
    "one supervision-loop pass: kill (pod/machine death) or delay",
)
_FP_NOTICE = _fault_point(
    "launch.drain.notice",
    "handling a preemption notice: delay (slow store eats into the drain "
    "budget) or drop (the preempt publication fails; drain proceeds "
    "best-effort)",
)

# store layout + worker exit contract shared with train/context.py
from edl_tpu.cluster.contract import (  # noqa: E402 (module docstring above)
    CLUSTER_SERVICE,
    COMPLETE,
    DRAIN_SERVICE,
    DRAINED_EXIT,
    HEARTBEAT_SERVICE,
    HOT_RESTAGE_EXIT,
    HOTADOPT_SERVICE,
    JOB_SERVICE,
    PREEMPT_SERVICE,
    RANK_SERVICE,
    RES_SERVICE,
    SCALE_SERVICE,
    STATUS_SERVICE,
)


def stalled_workers(
    heartbeats: Dict[str, dict],
    mine: Sequence[str],
    now: float,
    abs_deadline: float = 300.0,
    factor: float = 8.0,
    floor: float = 5.0,
) -> List[str]:
    """The watchdog's decision function, pure so it is unit-testable.

    ``heartbeats``: ``{"{pod}.{rank}": {"step": N, "ts": wall}}`` for ONE
    stage; ``mine``: the subset of keys this launcher supervises. A local
    worker is stalled when either

    - its heartbeat age exceeds ``abs_deadline`` (a forever-wedge bound
      that needs no peers; 0 disables), or
    - it is *behind* some peer's step AND its age exceeds
      ``max(floor, factor x median(peer ages))`` — being behind is what
      separates a wedged worker from a uniformly slow stage, where every
      age grows together and nobody is ejected.
    """
    ages = {k: now - float(h.get("ts", now)) for k, h in heartbeats.items()}
    steps = {k: int(h.get("step", -1)) for k, h in heartbeats.items()}
    out: List[str] = []
    for key in mine:
        if key not in heartbeats:
            continue  # no heartbeat yet this stage: spawn/restore in flight
        age = ages[key]
        if abs_deadline > 0 and age > abs_deadline:
            out.append(key)
            continue
        peers = [k for k in heartbeats if k != key]
        if not peers:
            continue
        peer_ages = sorted(ages[k] for k in peers)
        median = peer_ages[len(peer_ages) // 2]
        behind = steps[key] < max(steps[k] for k in peers)
        if behind and age > max(floor, factor * median):
            out.append(key)
    return out


class ElasticLauncher:
    def __init__(
        self,
        job_env: JobEnv,
        training_script: str,
        training_args: Sequence[str] = (),
        ttl: float = 10.0,
        poll_interval: float = 0.2,
        extra_worker_env: Optional[Dict[str, str]] = None,
        prewarm: bool = False,
        standby: bool = False,
        hot_restage: bool = False,
        fail_grace: Optional[float] = None,
        drain_budget: Optional[float] = None,
    ) -> None:
        self.job_env = job_env
        self.training_script = training_script
        self.training_args = list(training_args)
        self.ttl = ttl
        self.poll = poll_interval
        self.extra_worker_env = dict(extra_worker_env or {})
        # worker-crash grace window before abandoning the job (historically
        # hardcoded 3xTTL): a peer pod's death kills healthy workers too,
        # and the restage must win the race against "leave the job"
        if fail_grace is None:
            fail_grace = float(
                os.environ.get("EDL_FAIL_GRACE", 0) or max(3.0 * ttl, 3.0)
            )
        self.fail_grace = fail_grace
        # graceful drain: how long a noticed pod may spend on its
        # emergency checkpoint before the launcher kills what remains
        if drain_budget is None:
            drain_budget = float(os.environ.get("EDL_DRAIN_BUDGET", "10"))
        self.drain_budget = drain_budget
        # straggler watchdog knobs (see stalled_workers above)
        self.stall_abs = float(os.environ.get("EDL_STALL_DEADLINE", "300"))
        self.stall_factor = float(os.environ.get("EDL_STALL_FACTOR", "8"))
        self.stall_floor = float(
            os.environ.get("EDL_STALL_FLOOR", 0) or max(5.0, 2.0 * ttl)
        )
        self.prewarm = prewarm
        self.warmer = None  # created on first adopted stage
        # the elastic window rides the worker env contract so the AOT
        # resize ladder (train/aot.py) can enumerate its neighbor worlds
        self.extra_worker_env.setdefault(
            "EDL_NODES_RANGE",
            "%d:%d" % (job_env.min_nodes, job_env.max_nodes),
        )
        self.extra_worker_env.setdefault(
            "EDL_NPROC_PER_NODE", str(job_env.nproc_per_node)
        )
        self.cache_exchange = None  # started in run() when the cache is armed
        # checkpoint peer-replication plane (checkpoint/replicate.py):
        # with EDL_CKPT_LOCAL_BASE set, each pod gets a pod-local
        # checkpoint tier (derived here so workers just read
        # EDL_CKPT_LOCAL_DIR) and this launcher hosts the pod's replica
        # holder — receiving peers' checkpoint shards, serving them back
        # to restoring pods, and GC'ing superseded replicas on
        # membership change. The job's EDL_CKPT_PATH demotes to the
        # durable backstop the workers' replicators mirror into.
        self.ckpt_replicas = None  # started in run()
        self._ckpt_peers_reg: Optional[Registration] = None
        self._ckpt_local_base = os.environ.get("EDL_CKPT_LOCAL_BASE", "")
        # hot-restage mode: surviving workers adopt new stages in-process
        # (train/context.py reinit_for_stage) instead of kill+respawn; the
        # launcher hands the stage over and enforces an adoption deadline
        self.hot = hot_restage or os.environ.get("EDL_HOT_RESTAGE") == "1"
        if self.hot:
            self.extra_worker_env.setdefault("EDL_HOT_RESTAGE", "1")
        self.hot_grace = float(os.environ.get("EDL_HOT_GRACE", "20"))
        self._hot_deadline: Optional[float] = None
        # (count, last_ts): consecutive-fallback guard with decay — widely
        # spaced recovered fallbacks on a long-lived job must not
        # accumulate into a spurious abandonment
        self._hot_fallbacks = 0
        self._hot_fallback_ts = 0.0
        self.standby_pool = None
        from edl_tpu.launch.standby import StandbyPool, standby_enabled

        if standby_enabled(standby):
            spawn_env = procs_mod.base_worker_env(self.extra_worker_env)
            spawn_env.update(self.extra_worker_env)
            # eager backend init is only safe when the elastic window pins
            # the world to one worker (see launch/standby.py docstring)
            eager = (
                job_env.max_nodes * job_env.nproc_per_node == 1
                or os.environ.get("EDL_STANDBY_EAGER") == "1"
            )
            self.standby_pool = StandbyPool(
                spawn_env, count=job_env.nproc_per_node, eager=eager
            )

        self.client = connect_store(job_env.store_endpoint, timeout=max(10.0, ttl))
        # chaos plane (EDL_CHAOS env or the job's chaos/ keyspace): no-op
        # unless this job opted into fault injection
        _chaos_arm("launcher", client=self.client, job_id=job_env.job_id)
        self.registry = Registry(self.client, job_env.job_id)
        self.pod = self._make_pod()
        if self._ckpt_local_base:
            # the pod-local checkpoint tier: derived from the shared base
            # here (the pod id exists only now) so every worker — spawned
            # or standby-activated — reads one env var
            self.extra_worker_env.setdefault(
                "EDL_CKPT_LOCAL_DIR",
                os.path.join(self._ckpt_local_base, self.pod.pod_id),
            )

        self._events: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()

        self.resource_reg: Optional[Registration] = None
        self.rank_reg: Optional[Registration] = None
        self.rank_slot: Optional[int] = None
        self.running: Optional[Cluster] = None  # cluster my workers run under
        self.procs: List[procs_mod.WorkerProc] = []
        self.completed = False
        self._complete_published = False
        self._handled_token = ""
        self._mem_gate_last: Optional[int] = None  # last recorded fit cap
        # health plane: a preemption notice (SIGTERM/SIGUSR1) flips the
        # event from the signal handler; the loop turns it into a drain
        self._preempt_notice = threading.Event()
        self._draining = False
        self._drain_trace = ""  # drain-op trace id once a notice landed
        self._drain_deadline: Optional[float] = None
        self._drained_workers = False
        self._preempt_handled: set = set()
        self._was_leader: Optional[bool] = None
        self._prev_handlers: Dict[int, object] = {}
        # (exit_code, deadline, failed_stage): a worker crash holds here for
        # a grace window instead of abandoning the job — a peer pod's death
        # kills healthy workers too (the jax.distributed client aborts the
        # whole process when the coordinator dies), and THAT must restage,
        # not fail the job. A crash with stable membership still fails fast
        # once the grace window (~lease TTL) lapses with no new stage.
        self._worker_failure: Optional[tuple] = None

        # observability plane (EDL_OBS_PORT gates the HTTP mount)
        self._tracer = obs_trace.get_tracer("launcher")
        self._m_drains = obs_metrics.counter(
            "edl_launch_drains_total", "drain tokens this pod CAS-won"
        )
        self._m_spawns = obs_metrics.counter(
            "edl_launch_spawns_total", "worker generations spawned by this pod"
        )
        self._m_hot_handoffs = obs_metrics.counter(
            "edl_launch_hot_handoffs_total", "stages handed to live workers in-process"
        )
        self._m_hot_fallbacks = obs_metrics.counter(
            "edl_launch_hot_fallbacks_total", "hot restages that fell back to respawn"
        )
        self._m_worker_failures = obs_metrics.counter(
            "edl_launch_worker_failures_total", "nonzero worker exits observed"
        )
        self._m_leader = obs_metrics.gauge(
            "edl_launch_leader_state", "1 when this pod is the stage leader"
        )
        self._m_stragglers = obs_metrics.counter(
            "edl_launch_straggler_ejections_total",
            "wedged local workers ejected by the straggler watchdog",
        )
        self._m_notices = obs_metrics.counter(
            "edl_launch_preempt_notices_total",
            "preemption notices (SIGTERM/SIGUSR1 or worker-relayed) this "
            "pod began draining for",
        )
        # histogram, not gauge: edl-top renders p50/p95 from the buckets,
        # so a transient stall is visible after the fact, not only while
        # a scrape happens to catch it
        self._m_hb_age = obs_metrics.histogram(
            "edl_train_step_heartbeat_age_seconds",
            "age of each local worker's last step heartbeat, sampled by "
            "the watchdog every supervision pass",
        )
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_launch_workers_running", "live local worker processes",
             lambda: len(self.procs)),
            ("edl_launch_grace_remaining_seconds",
             "seconds left in the worker-failure grace window (0 outside it)",
             lambda: max(0.0, self._worker_failure[1] - time.time())
             if self._worker_failure is not None else 0.0),
        ))
        # stable bound-method reference for identity-guarded release
        self._health_fn = self._health
        self._obs = obs_http.start_from_env(
            "launcher", health_fn=self._health_fn
        )

    def _health(self) -> Dict:
        return {
            "pod": self.pod.pod_id,
            "stage": self.running.stage if self.running is not None else "",
            "workers": len(self.procs),
            "leader": bool(self._m_leader.value()),
            "completed": self.completed,
            "draining": self._draining,
        }

    # -- setup -------------------------------------------------------------

    def _make_pod(self) -> Pod:
        nproc = self.job_env.nproc_per_node
        devices = max(1, local_device_count() // max(1, nproc))
        addr = get_host_ip()
        ports = find_free_ports(nproc)
        workers = [
            Worker(endpoint="%s:%d" % (addr, ports[i]), rank_in_pod=i, num_devices=devices)
            for i in range(nproc)
        ]
        return Pod(addr=addr, workers=workers)

    def _wake(self, _arg=None) -> None:
        self._events.put("changed")

    # -- snapshots ---------------------------------------------------------

    def _live_pods(self) -> Dict[str, Pod]:
        return {
            name: Pod.from_json(meta.value)
            for name, meta in self._res_watch.snapshot().items()
        }

    def _rank_map(self) -> Dict[int, str]:
        out = {}
        for name, meta in self._rank_watch.snapshot().items():
            try:
                out[int(name)] = meta.value.decode()
            except ValueError:
                pass
        return out

    def _drain_token(self) -> str:
        meta = self._drain_watch.snapshot().get("token")
        return meta.value.decode() if meta else ""

    def _published(self) -> Optional[Cluster]:
        meta = self._cluster_watch.snapshot().get("current")
        return Cluster.from_json(meta.value) if meta else None

    def _draining_pods(self) -> set:
        """pod_ids with a preemption notice published (any payload: a key
        we cannot parse still means "this pod is going away")."""
        return set(self._preempt_watch.snapshot())

    # -- drain token (stage fencing) --------------------------------------

    def _trigger_drain(
        self, reason: str, cause: str = "membership",
        caused_by: Optional[str] = None,
    ) -> None:
        token_key = "/%s/%s/token" % (self.job_env.job_id, DRAIN_SERVICE)
        try:
            value, mod_rev = self.client.get_with_rev(token_key)
            new = new_uuid()
            if self.client.cas(token_key, mod_rev if value is not None else 0, new.encode()):
                logger.info("pod %s triggered drain %s (%s)", self.pod.pod_id[:8], new[:8], reason)
                self._m_drains.inc(cause=cause)
                # restage operation root: the CAS winner anchors the
                # trace every other process stitches to — the trace id
                # derives from the new token, so the leader's publish,
                # peers' spawns, and the fresh workers' restore/first-jit
                # all join it with zero extra wire traffic
                root_args = {"cause": cause, "reason": reason,
                             "pod": self.pod.pod_id[:8]}
                if self._drain_trace:
                    # a preemption notice caused this restage: link the
                    # pod's drain trace so edl-trace can chain them
                    root_args["caused_by"] = self._drain_trace
                elif caused_by:
                    # a scale decision caused this restage directly
                    # (leader-side grow/shrink reconcile, no local drain)
                    root_args["caused_by"] = caused_by
                ctx = obs_trace.record_op_root("restage", new, **root_args)
                with obs_trace.use(ctx):
                    self._tracer.instant("drain", stage=new[:8], reason=reason)
                    obs_events.record(
                        "drain", fsync=True, token=new[:8], reason=reason,
                        cause=cause, pod=self.pod.pod_id[:8],
                    )
                telemetry.record_event(
                    self.client, self.job_env.job_id, new, "drain",
                    self.pod.pod_id[:8],
                )
        except EdlStoreError as exc:
            logger.warning("drain trigger failed (%s): %s", reason, exc)

    # -- rank racing -------------------------------------------------------

    def _race_rank(self) -> None:
        """Try to win a free slot 0..max_nodes-1 (reference races
        0..1024 in order, register.py:72-114 — but each miss there costs
        a full RPC round; here one range read finds the free slots and we
        race only those, so a pod joining a nearly-full job pays one read
        plus ~one contended put instead of ~3N round-trips)."""
        if self.rank_reg is not None:
            return
        taken = {
            m.name for m in self.registry.get_service(RANK_SERVICE)
        }
        free = [
            s for s in range(self.job_env.max_nodes) if str(s) not in taken
        ]
        for slot in free:
            reg, _holder = self.registry.register_if_absent(
                RANK_SERVICE,
                str(slot),
                self.pod.pod_id.encode(),
                ttl=self.ttl,
                on_lost=self._on_rank_lost,
            )
            if reg is not None:
                self.rank_reg, self.rank_slot = reg, slot
                logger.info("pod %s won rank slot %d", self.pod.pod_id[:8], slot)
                return
        logger.info(
            "pod %s found no free rank slot (%d taken); waiting",
            self.pod.pod_id[:8], len(taken),
        )

    def _on_rank_lost(self) -> None:
        self.rank_reg = None
        self.rank_slot = None
        self._wake()

    def _is_leader(self) -> bool:
        if self.rank_slot is None:
            return False
        ranks = self._rank_map()
        # a draining pod must not lead: it is about to leave, and leadership
        # passing to the next live slot NOW is what makes the proactive
        # exclusion publish happen while the drainer is still checkpointing
        live = set(self._live_pods()) - self._draining_pods()
        live_slots = [s for s, pid in ranks.items() if pid in live]
        return bool(live_slots) and self.rank_slot == min(live_slots)

    # -- scale-plane reconciliation ---------------------------------------

    def _scale_target(self) -> Optional[dict]:
        """The autoscaler's ``scale/target`` doc for this job, parsed
        (None = no target in force: fit to whatever membership exists)."""
        watch = getattr(self, "_scale_watch", None)
        if watch is None:
            return None
        meta = watch.snapshot().get("target")
        if meta is None:
            return None
        try:
            doc = json.loads(meta.value)
            int(doc.get("pods", 0))
        except (ValueError, TypeError, AttributeError):
            return None
        return doc

    def _mem_fit_cap(self) -> Optional[int]:
        """The memory plane's fit verdict (obs/memory.fit_cap) in pods:
        the largest published ``mem/plan/{world}`` whose compile-time
        plan fits its stamped device limit minus ``EDL_MEM_MARGIN``
        (plan worlds count processes — divided by nproc_per_node).
        None when no judgeable plan is published: unknown never gates."""
        try:
            plans = obs_memory.read_plans(self.client, self.job_env.job_id)
            cap = obs_memory.fit_cap(plans)
        except Exception:  # noqa: BLE001 — store blip reads as unknown
            return None
        if cap is None:
            return None
        return cap // max(1, self.job_env.nproc_per_node)

    def _want_pods(
        self, n_live: int, target: Optional[dict], current: int = 0
    ) -> int:
        """How many pods the next generation should hold: membership
        capped by max_nodes, further capped by the autoscale target,
        further capped by the memory-plane fit verdict. 0 means pause —
        every pod drained, and the leader publishes the EMPTY generation
        so the pause lands in cluster/current (the gang floor: a job
        runs at >= min_nodes or not at all).

        The fit cap is the reconcile path's own last line — it holds
        even with no scaler running — but, like the scaler's gate, it
        only refuses GROWTH: it never shrinks below ``current`` (the
        published world is live evidence it fits) or the gang floor."""
        want = min(n_live, self.job_env.max_nodes)
        if target is not None:
            pods = int(target.get("pods", 0) or 0)
            if pods <= 0:
                return 0
            want = min(want, max(pods, self.job_env.min_nodes))
        cap = self._mem_fit_cap()
        if cap is not None:
            fit = max(cap, self.job_env.min_nodes, current)
            if fit < want:
                if self._mem_gate_last != fit:
                    self._mem_gate_last = fit
                    obs_events.record(
                        "mem_unfit", fsync=True, component="launcher",
                        cap_pods=fit, wanted=want,
                        cause="mem_unfit: reconcile capped at %d pods "
                              "(plan over device limit)" % fit,
                    )
                    logger.info(
                        "memory fit gate: next generation capped at %d "
                        "pods (wanted %d)", fit, want,
                    )
                want = fit
        return want

    def _drift_cause(self, missing: set) -> Tuple[str, Optional[str]]:
        """Attribute a membership-drift restage: when every missing pod
        carries an autoscale preempt notice the SCALER caused this drift
        — label the drain so thrash detection and the scale op trace see
        it (otherwise it is ordinary membership weather)."""
        notices = self._preempt_watch.snapshot()
        seq = None
        for pid in missing:
            meta = notices.get(pid)
            if meta is None:
                return "membership", None
            try:
                doc = json.loads(meta.value)
            except ValueError:
                return "membership", None
            if doc.get("cause") != "autoscale":
                return "membership", None
            seq = doc.get("seq", seq)
        if seq is None:
            return "membership", None
        return "autoscale", obs_trace.op_trace_id("scale", str(int(seq)))

    def _release_pods(
        self, current: set, ranks: Dict[int, str], n_excess: int,
        target: dict,
    ) -> None:
        """Autoscale shrink: publish ``preempt/{pod}`` drain notices for
        the ``n_excess`` highest-slot published pods (the leader holds
        the lowest live slot, so it is released last — only when the
        target pauses the whole job). The existing drain machinery does
        everything else: the victims' workers checkpoint and exit
        DRAINED, membership converges without them, and the next
        generation publishes at the target size."""
        slot_of = {pid: s for s, pid in ranks.items()}
        victims = sorted(
            current, key=lambda pid: -slot_of.get(pid, -1)
        )[:n_excess]
        seq = int(target.get("seq", 0) or 0)
        tid = obs_trace.op_trace_id("scale", str(seq))
        now = time.time()
        for pid in victims:
            try:
                self.registry.set_permanent(
                    PREEMPT_SERVICE,
                    pid,
                    json.dumps(
                        {"deadline": now + self.drain_budget,
                         "budget": self.drain_budget, "ts": now,
                         "cause": "autoscale", "seq": seq}
                    ).encode(),
                )
            except EdlStoreError as exc:
                logger.warning(
                    "autoscale release of %s not published: %s", pid[:8], exc
                )
                continue
            obs_events.record(
                "scale_preempt", fsync=True, pod=pid[:8], seq=seq,
                cause="autoscale", trace_id=tid,
            )
            logger.info(
                "autoscale: released pod %s (target %d pods, seq %d)",
                pid[:8], int(target.get("pods", 0) or 0), seq,
            )

    # -- leader duties -----------------------------------------------------

    def _maybe_publish(self) -> None:
        token = self._drain_token()
        draining = self._draining_pods()
        # preemption-noticed pods are excluded from the next generation
        # IMMEDIATELY: no lease-expiry wait (they are still heartbeating
        # while they checkpoint), their rank slots don't block convergence
        live = {
            pid: pod for pid, pod in self._live_pods().items()
            if pid not in draining
        }
        ranks = {
            s: pid for s, pid in self._rank_map().items()
            if pid not in draining
        }
        if not token:
            # first generation: establish the initial stage token
            if live:
                self._trigger_drain("bootstrap", cause="bootstrap")
            return
        target = self._scale_target()
        published = self._published()
        if published is not None and published.stage == token:
            # this generation is already out; reconcile it against
            # membership AND the autoscale target
            current = set(published.pod_ids())
            if not current <= set(live):
                # a published pod died or was preemption-noticed; when
                # the notices are the scaler's, the restage is its doing
                cause, caused_by = self._drift_cause(current - set(live))
                self._trigger_drain(
                    "membership drift", cause=cause, caused_by=caused_by
                )
                return
            want = self._want_pods(len(live), target, current=len(current))
            if want < len(current):
                # autoscale shrink (or pause at want == 0): release the
                # excess through the drain plane, never a bare kill
                self._release_pods(current, ranks, len(current) - want, target)
                return
            if want > len(current):
                # grow: admit pods through a fresh generation — held
                # ones when a target raised, ordinary joiners otherwise
                if target is not None:
                    self._trigger_drain(
                        "autoscale grow to %d (seq %s)"
                        % (want, target.get("seq")),
                        cause="autoscale",
                        caused_by=obs_trace.op_trace_id(
                            "scale", str(int(target.get("seq", 0) or 0))
                        ),
                    )
                else:
                    self._trigger_drain("membership drift")
                return
            ranked_live = {s: pid for s, pid in ranks.items() if pid in live}
            if current != {
                ranked_live[s] for s in sorted(ranked_live)[:want]
            }:
                # same size, different slots/membership (a published pod
                # lost its rank slot to another live pod). With a target
                # in force the comparison is against the first ``want``
                # slots — what the publish path below would emit — so
                # held pods beyond the target never read as drift, but
                # a slot takeover at equal world size still restages
                self._trigger_drain("membership drift")
            return
        # convergence condition: stale rank slots (dead holders) must have
        # lease-expired, every live pod (up to max) must hold a slot
        ranked = {s: pid for s, pid in ranks.items() if pid in live}
        if len(ranked) != len(ranks):
            return  # stale slots still draining out via TTL
        if len(ranked) != min(len(live), self.job_env.max_nodes):
            return  # not every live pod holds a slot yet
        want = self._want_pods(len(live), target)
        # autoscale pause (want == 0): pods stay held, but the EMPTY
        # generation still publishes — cluster/current is the scaler's
        # actual-world source, and leaving the victims' last nonzero
        # doc in place would read as a shrink that never settles,
        # deferring the preempting gang's grow forever
        if 0 < want < self.job_env.min_nodes:
            return
        pods = []
        for slot in sorted(ranked)[:want]:
            pod = live[ranked[slot]]
            pod.rank = slot
            pods.append(pod)
        cluster = Cluster.from_pods(pods, stage=token)
        # restage-trace segment: the leader's publish is one hop of the
        # critical path (token CAS -> election -> PUBLISH -> spawn -> ...)
        with obs_trace.op_segment(
            "publish", "restage", token,
            world=cluster.world_size, pods=cluster.num_pods,
        ):
            self.registry.set_permanent(
                CLUSTER_SERVICE, "current", cluster.to_json()
            )
            obs_events.record(
                "publish", fsync=True, stage=token[:8],
                world=cluster.world_size, pods=cluster.num_pods,
            )
        if target is not None and int(target.get("seq", 0) or 0):
            # decision->restage closure: this publish satisfies the
            # scaler's target — a segment under the deterministic
            # op_trace_id("scale", seq) root plus an fsync'd flight
            # record make the latency a first-class edl-trace query
            seq = int(target["seq"])
            with obs_trace.op_segment(
                "reconcile", "scale", str(seq),
                stage=token[:8], world=cluster.world_size,
                pods=cluster.num_pods,
            ):
                pass
            obs_events.record(
                "scale_reconcile", fsync=True, seq=seq, stage=token[:8],
                pods=cluster.num_pods, world=cluster.world_size,
                trace_id=obs_trace.op_trace_id("scale", str(seq)),
            )
        telemetry.record_event(
            self.client, self.job_env.job_id, token, "published",
            self.pod.pod_id[:8],
        )
        telemetry.record_stage(
            self.client, self.job_env.job_id, token,
            {"world": cluster.world_size, "pods": cluster.num_pods,
             "ts": time.time()},
        )
        logger.info(
            "leader %s published stage %s: %d pod(s), world=%d",
            self.pod.pod_id[:8],
            token[:8],
            cluster.num_pods,
            cluster.world_size,
        )

    def _maybe_complete_job(self) -> None:
        published = self._published()
        if published is None or not published.pod_ids():
            # no generation yet, or a paused (empty) one — vacuous
            # "all pods COMPLETE" must not mark the job done
            return
        statuses = self._status_watch.snapshot()
        done = all(
            (meta := statuses.get(pid)) is not None and meta.value == COMPLETE
            for pid in published.pod_ids()
        )
        if done:
            self.registry.set_permanent(JOB_SERVICE, "status", COMPLETE)
            logger.info("leader %s marked job COMPLETE", self.pod.pod_id[:8])

    # -- follower duties ---------------------------------------------------

    def _check_death(self) -> None:
        """T1: a member of the generation I'm running vanished."""
        if self.running is None:
            return
        live = set(self._live_pods())
        draining = self._draining_pods()
        # a noticed pod's departure is already being handled by the drain
        # its notice triggered — re-triggering here would burn a second
        # restage for the same membership change
        dead = [
            pid for pid in self.running.pod_ids()
            if pid not in live and pid not in draining
        ]
        if dead:
            self._trigger_drain(
                "pod(s) died: %s" % ",".join(p[:8] for p in dead),
                cause="death",
            )

    # -- graceful drain (health plane) -------------------------------------

    def _on_preempt_signal(self, signum=None, _frame=None) -> None:
        """SIGTERM/SIGUSR1: an advance preemption notice (spot VM reclaim,
        k8s eviction). Idempotent — repeated signals while draining are
        absorbed. Safe in a signal context: set a flag, wake the loop."""
        if not self._preempt_notice.is_set():
            logger.warning(
                "pod %s received preemption notice (signal %s); draining",
                self.pod.pod_id[:8], signum,
            )
        self._preempt_notice.set()
        self._wake()

    def _begin_drain(self) -> None:
        """Turn the notice into a drain: publish ``preempt/{pod_id}`` with
        the deadline, bump the drain token so the leader restages without
        this pod, and let the local workers (who see the preempt key via
        their store watch) take their emergency checkpoint. Called from the
        loop, once — double notices are idempotent by construction."""
        if self._draining:
            return
        self._draining = True
        self._m_leader.set(0.0)  # a draining pod never leads
        now = time.time()
        self._drain_deadline = now + self.drain_budget
        # a notice may already be published FOR us (the scaler's leader
        # released this pod with cause=autoscale): preserve its payload
        # — cause and seq attribute the drain, and the key must not be
        # overwritten with a causeless local one
        existing: Optional[dict] = None
        watch = getattr(self, "_preempt_watch", None)
        if watch is not None:
            meta = watch.snapshot().get(self.pod.pod_id)
        else:
            # drain before the loop armed its watches (early signal):
            # one direct read keeps the attribution semantics
            try:
                meta = self.registry.get_server(PREEMPT_SERVICE, self.pod.pod_id)
            except Exception:  # noqa: BLE001 — store blip: local cause wins
                meta = None
        if meta is not None:
            try:
                existing = json.loads(meta.value)
            except ValueError:
                existing = None
        cause = "preempt"
        if existing and existing.get("cause"):
            cause = str(existing["cause"])
        # the token bump below counts in edl_launch_drains_total{cause=
        # "preempt"/"autoscale"} only on CAS win, like every other
        # cause; the notice itself gets its own counter
        self._m_notices.inc()
        # drain operation root, keyed by pod id (a pod drains at most
        # once): this pod's notice, emergency checkpoint, and DRAINED
        # exit stitch under it, and the restage it triggers records it
        # as caused_by
        root_args = {
            "pod": self.pod.pod_id[:8],
            "budget": "%.1f" % self.drain_budget,
        }
        if cause == "autoscale" and existing and existing.get("seq") is not None:
            # chain back to the decision that released this pod
            root_args["caused_by"] = obs_trace.op_trace_id(
                "scale", str(int(existing["seq"]))
            )
        drain_ctx = obs_trace.record_op_root(
            "drain", self.pod.pod_id, **root_args
        )
        self._drain_trace = drain_ctx.trace_id
        with obs_trace.use(drain_ctx):
            self._tracer.instant(
                "preempt_notice", pod=self.pod.pod_id[:8],
                budget="%.1f" % self.drain_budget,
            )
            obs_events.record(
                "preempt_notice", fsync=True, pod=self.pod.pod_id[:8],
                budget=self.drain_budget, deadline=self._drain_deadline,
            )
        stage = (
            self.running.stage if self.running is not None
            else self._handled_token
        )
        if _FP_NOTICE.armed:
            try:
                _FP_NOTICE.fire(pod=self.pod.pod_id[:8])
            except ConnectionError:
                logger.warning("chaos: preempt publication dropped")
                return  # drain proceeds without the store's help
        try:
            if existing is None:
                self.registry.set_permanent(
                    PREEMPT_SERVICE,
                    self.pod.pod_id,
                    json.dumps(
                        {"deadline": self._drain_deadline,
                         "budget": self.drain_budget, "ts": now}
                    ).encode(),
                )
            telemetry.record_event(
                self.client, self.job_env.job_id, stage, "preempt",
                self.pod.pod_id[:8], ts=now,
            )
        except EdlStoreError as exc:
            logger.warning("preempt notice not published: %s", exc)
        if not self.completed and (self.procs or self.running is not None):
            self._trigger_drain("preemption notice", cause=cause)
        if not self.procs:
            # nothing to checkpoint: the drain is already complete
            self._drain_deadline = now

    def _finish_drain(self) -> int:
        """Exit path of a draining pod: everything local is down (or the
        budget lapsed), leases are deleted by run()'s finally so the
        membership converges instantly — no TTL wait for the survivors."""
        if self.procs:
            logger.warning(
                "pod %s drain budget lapsed with %d worker(s) still up; "
                "killing", self.pod.pod_id[:8], len(self.procs),
            )
            self._kill_workers()
        with obs_trace.use(obs_trace.op_context("drain", self.pod.pod_id)):
            self._tracer.instant("drained", pod=self.pod.pod_id[:8])
            obs_events.record(
                "pod_drained", fsync=True, pod=self.pod.pod_id[:8],
                clean=self._drained_workers,
            )
        logger.info(
            "pod %s drained (%s); leaving with exit code %d",
            self.pod.pod_id[:8],
            "workers checkpointed and exited DRAINED"
            if self._drained_workers else "no worker drained cleanly",
            0 if self.completed else DRAINED_EXIT,
        )
        return 0 if self.completed else DRAINED_EXIT

    # -- straggler watchdog ------------------------------------------------

    def _check_stragglers(self) -> None:
        """Eject a LOCAL worker that is wedged: behind its peers and quiet
        past the peer-median-derived deadline (or past the absolute
        forever-wedge bound). Ejection is kill + drain: the pod stays in
        the job — the machine is fine, the process was stuck — and the
        restaged generation respawns it from the last checkpoint."""
        if not self.procs or self.running is None or self._draining:
            return
        mine = self.running.get_pod(self.pod.pod_id)
        if mine is None:
            return
        stage = self.running.stage
        now = time.time()
        beats: Dict[str, dict] = {}
        for name, meta in self._hb_watch.snapshot().items():
            try:
                payload = json.loads(meta.value)
            except ValueError:
                continue
            if payload.get("stage") == stage:
                beats[name] = payload
        my_keys = [
            "%s.%d" % (self.pod.pod_id, w.rank_in_pod) for w in mine.workers
        ]
        for key in my_keys:
            if key in beats:
                self._m_hb_age.observe(
                    now - float(beats[key].get("ts", now)),
                    worker=key.rpartition(".")[2],
                )
        stalled = stalled_workers(
            beats, my_keys, now,
            abs_deadline=self.stall_abs,
            factor=self.stall_factor,
            floor=self.stall_floor,
        )
        if not stalled:
            return
        ages = ", ".join(
            "%s age=%.1fs step=%s" % (
                k.rpartition(".")[2],
                now - float(beats[k].get("ts", now)),
                beats[k].get("step"),
            )
            for k in stalled
        )
        logger.error(
            "pod %s straggler watchdog: worker(s) wedged [%s]; ejecting "
            "and restaging", self.pod.pod_id[:8], ages,
        )
        self._m_stragglers.inc()
        self._tracer.instant("straggler_ejected", stage=stage[:8], who=ages)
        obs_events.record(
            "straggler_ejected", fsync=True, stage=stage[:8], who=ages,
        )
        telemetry.record_event(
            self.client, self.job_env.job_id, stage, "straggler",
            self.pod.pod_id[:8],
        )
        self._kill_workers()
        self._trigger_drain("straggler ejected: %s" % ages, cause="straggler")

    def _handle_token(self) -> None:
        """A new drain token means: my running generation is obsolete."""
        token = self._drain_token()
        if token == self._handled_token:
            return
        self._handled_token = token
        if self._draining:
            # my workers are mid-emergency-checkpoint: killing them for the
            # new generation (which excludes this pod anyway) would throw
            # away exactly the work the drain budget exists to save
            return
        if self.running is not None and self.running.stage != token:
            if self.hot and self.procs and all(
                wp.proc.poll() is None for wp in self.procs
            ):
                # hot mode: live workers see the same token through their
                # own store watch and adopt the next generation in-process;
                # killing them here would throw away the warm runtime
                logger.info(
                    "pod %s drain %s: workers held for in-process restage",
                    self.pod.pod_id[:8], token[:8],
                )
                return
            logger.info(
                "pod %s draining stage %s for token %s",
                self.pod.pod_id[:8],
                self.running.stage[:8],
                token[:8],
            )
            with obs_trace.op_segment(
                "drain_kill", "restage", token,
                stage=token[:8], pod=self.pod.pod_id[:8],
            ):
                self._kill_workers()
                obs_events.record(
                    "killed", fsync=True, stage=token[:8],
                    pod=self.pod.pod_id[:8],
                )
            telemetry.record_event(
                self.client, self.job_env.job_id, token, "killed",
                self.pod.pod_id[:8],
            )

    def _adopt_cluster(self) -> None:
        if self._draining:
            return  # a draining pod never joins another generation
        published = self._published()
        if published is None:
            return
        mine = published.get_pod(self.pod.pod_id)
        if self.running is not None and self.running.stage == published.stage:
            self._enforce_hot_deadline(published)
            return
        if (
            self.hot
            and mine is not None
            and self.running is not None
            and self.procs
            and all(wp.proc.poll() is None for wp in self.procs)
            and not self.completed
            and self._worker_failure is None
            and published.stage == self._drain_token()
        ):
            # hand the generation over to the live workers: they re-enter
            # train.init in-process (reinit_for_stage) and must confirm
            # via the hotadopt store key before the grace deadline
            self.running = published
            self._note_membership(published)
            self._hot_deadline = time.time() + self.hot_grace
            self._m_hot_handoffs.inc()
            with obs_trace.use(
                obs_trace.op_context("restage", published.stage)
            ):
                self._tracer.instant("hot_handoff", stage=published.stage[:8])
            telemetry.record_event(
                self.client, self.job_env.job_id, published.stage,
                "hot-handoff", self.pod.pod_id[:8],
            )
            logger.info(
                "pod %s handed stage %s to live workers (deadline %.0fs)",
                self.pod.pod_id[:8], published.stage[:8], self.hot_grace,
            )
            return
        if self.running is not None:
            self._kill_workers()
        if mine is None:
            return  # not part of this generation; keep waiting
        if self.completed:
            return  # my work is done; don't respawn for resizes
        if (
            self._worker_failure is not None
            and published.stage == self._worker_failure[2]
        ):
            return  # don't crash-loop the generation that just failed
        if published.stage != self._drain_token():
            return  # stale publish; a newer drain is already in flight
        self.running = published
        self._note_membership(published)
        self._m_spawns.inc()
        with obs_trace.op_segment(
            "spawn_workers", "restage", published.stage,
            stage=published.stage[:8], world=published.world_size,
        ):
            obs_events.record(
                "spawn", fsync=True, stage=published.stage[:8],
                world=published.world_size, pod=self.pod.pod_id[:8],
            )
            self.procs = procs_mod.start_local_workers(
                published,
                mine,
                self.training_script,
                self.training_args,
                log_dir=self.job_env.log_dir,
                extra_env={
                    "EDL_JOB_ID": self.job_env.job_id,
                    "EDL_STORE_ENDPOINT": self.job_env.store_endpoint,
                    "EDL_CKPT_PATH": self.job_env.ckpt_path,
                    "EDL_COMPILE_CACHE_DIR": self.job_env.compile_cache_dir,
                    **self.extra_worker_env,
                },
                standby=self.standby_pool,
            )

    def _enforce_hot_deadline(self, published: Cluster) -> None:
        """After a hot handoff, every local worker must confirm it TOOK
        the handoff (hotadopt/{pod}.{rank} == stage, written before its
        jax.distributed re-init — which may legitimately block on a slow
        joiner) before the deadline; a miss means the worker is wedged in
        a dead collective or an abort, and falls back to kill + cold
        respawn of this generation."""
        if self._hot_deadline is None or not self.procs:
            self._hot_deadline = None
            return
        mine = published.get_pod(self.pod.pod_id)
        if mine is None:
            self._hot_deadline = None
            return
        snapshot = self._hotadopt_watch.snapshot()
        want = {
            "%s.%d" % (self.pod.pod_id, w.rank_in_pod) for w in mine.workers
        }
        adopted = {
            name
            for name, meta in snapshot.items()
            if name in want and meta.value == published.stage.encode()
        }
        if adopted == want:
            logger.info(
                "pod %s workers adopted stage %s in-process",
                self.pod.pod_id[:8], published.stage[:8],
            )
            telemetry.record_event(
                self.client, self.job_env.job_id, published.stage,
                "hot-adopted", self.pod.pod_id[:8],
            )
            self._hot_deadline = None
            self._hot_fallbacks = 0
            return
        if time.time() > self._hot_deadline:
            logger.warning(
                "pod %s workers missed the hot-adoption deadline for "
                "stage %s (%d/%d confirmed); falling back to respawn",
                self.pod.pod_id[:8], published.stage[:8],
                len(adopted), len(want),
            )
            self._hot_deadline = None
            self._kill_workers()
            self._wake()

    def _note_membership(self, published: Cluster) -> None:
        """Per-generation upkeep of the pod-scoped planes: the warmer
        learns the new world size, and the checkpoint replica holder
        GCs replicas superseded by the new membership."""
        self._note_stage_for_warmer(published)
        if self.ckpt_replicas is not None:
            try:
                self.ckpt_replicas.note_membership(published.pod_ids())
            except Exception as exc:  # noqa: BLE001 — GC is best-effort
                logger.warning("ckpt replica gc failed: %s", exc)

    def _note_stage_for_warmer(self, published: Cluster) -> None:
        """Kick proactive compile-cache warming for the OTHER world sizes
        the elastic window allows (see launch/warm.py) — the grow
        transition should land on a warm cache the first time."""
        if self.warmer is None:
            from edl_tpu.launch.warm import make_warmer_if_enabled

            self.warmer = make_warmer_if_enabled(
                self.job_env,
                self.pod.pod_id,
                self.training_script,
                self.training_args,
                self.extra_worker_env,
                self.prewarm,
            ) or False
        if self.warmer:
            self.warmer.note_world(published.world_size)

    def _kill_workers(self) -> None:
        if self.procs:
            procs_mod.terminate_local_workers(self.procs)
        self.procs = []
        self.running = None

    # -- main loop ---------------------------------------------------------

    def run(self) -> int:
        env = self.job_env
        logger.info("launching %s: %r", env, self.training_script)
        self.resource_reg = self.registry.register(
            RES_SERVICE, self.pod.pod_id, self.pod.to_json(), ttl=self.ttl
        )
        self._res_watch = self.registry.watch_service(RES_SERVICE, on_change=self._wake)
        self._rank_watch = self.registry.watch_service(RANK_SERVICE, on_change=self._wake)
        self._drain_watch = self.registry.watch_service(DRAIN_SERVICE, on_change=self._wake)
        self._cluster_watch = self.registry.watch_service(CLUSTER_SERVICE, on_change=self._wake)
        self._status_watch = self.registry.watch_service(STATUS_SERVICE, on_change=self._wake)
        self._job_watch = self.registry.watch_service(JOB_SERVICE, on_change=self._wake)
        self._hotadopt_watch = self.registry.watch_service(
            HOTADOPT_SERVICE, on_change=self._wake
        )
        self._preempt_watch = self.registry.watch_service(
            PREEMPT_SERVICE, on_change=self._wake
        )
        # the autoscaler's target-world docs: every launcher watches so
        # the leader reconciles promptly and victims see their release
        self._scale_watch = self.registry.watch_service(
            SCALE_SERVICE, on_change=self._wake
        )
        # no wake on heartbeats: they tick every step and the poll-interval
        # pass is plenty for a watchdog whose deadlines are seconds
        self._hb_watch = self.registry.watch_service(HEARTBEAT_SERVICE)
        # preemption notices arrive as SIGTERM (spot reclaim, k8s eviction)
        # or SIGUSR1 (operator-initiated); installable only from the main
        # thread — embedded/test launchers fall back to shutdown() semantics
        try:
            for signum in (signal.SIGTERM, signal.SIGUSR1):
                self._prev_handlers[signum] = signal.signal(
                    signum, self._on_preempt_signal
                )
        except ValueError:
            pass
        if self._obs is not None:
            # advertise the scrape target so edl-top finds it via the store
            obs_http.register_endpoint(
                self.client, env.job_id, "launcher", self.pod.pod_id[:8],
                self._obs.endpoint,
            )
        # An embedded store shares this process's registry, so its series
        # already ride the launcher endpoint registered above — a second
        # "store" registration would make every scraper that sums across
        # targets double-count this process.

        # cache exchange (train/aot.py): publish this pod's compile-cache
        # manifest + serve entry bytes, so a restaging or newly joined
        # peer pulls executables instead of compiling them. Pod-scoped
        # (survives worker restarts across stages); best-effort.
        if (
            env.compile_cache_dir
            and os.environ.get("EDL_CACHE_EXCHANGE", "1") != "0"
        ):
            try:
                from edl_tpu.train.aot import CacheExchange

                self.cache_exchange = CacheExchange(
                    env.compile_cache_dir, self.client, env.job_id,
                    self.pod.pod_id,
                ).start()
            except Exception as exc:  # noqa: BLE001 — a perf lever, never a gate
                logger.warning("cache exchange unavailable: %s", exc)

        # checkpoint replica holder (checkpoint/replicate.py): pod-scoped
        # like the cache exchange — replicas must survive worker restarts
        # across stages, and the whole point of holding a peer's shards
        # is outliving that peer. Leased peers registration so pushers
        # find only live holders.
        if self._ckpt_local_base:
            from edl_tpu.checkpoint.replicate import (
                PEERS_SERVICE,
                ReplicaServer,
                replica_count,
            )

            if replica_count() > 0:
                try:
                    self.ckpt_replicas = ReplicaServer(
                        os.path.join(
                            self._ckpt_local_base,
                            self.pod.pod_id + ".replicas",
                        ),
                        self.client, env.job_id, self.pod.pod_id,
                        ttl=self.ttl,
                    ).start()
                    self._ckpt_peers_reg = self.registry.register(
                        PEERS_SERVICE, self.pod.pod_id,
                        self.ckpt_replicas.endpoint.encode(), ttl=self.ttl,
                    )
                except Exception as exc:  # noqa: BLE001 — a durability
                    # lever for PEERS' checkpoints; this pod still trains
                    logger.warning("ckpt replica holder unavailable: %s", exc)

        try:
            return self._loop()
        finally:
            for signum, handler in self._prev_handlers.items():
                try:
                    signal.signal(signum, handler)
                except (ValueError, TypeError):
                    pass
            self._obs_gauges.release()
            obs_http.release_health("launcher", self._health_fn)
            self._kill_workers()
            if self.standby_pool is not None:
                self.standby_pool.stop()
            if self.warmer:
                self.warmer.stop()
            if self.cache_exchange is not None:
                self.cache_exchange.stop()
            if self._ckpt_peers_reg is not None:
                self._ckpt_peers_reg.stop(delete=True)
            if self.ckpt_replicas is not None:
                self.ckpt_replicas.stop()
            for reg in (self.rank_reg, self.resource_reg):
                if reg is not None:
                    reg.stop(delete=True)
            self.client.close()

    def _loop(self) -> int:  # edl: event-loop(launcher supervision: lease renewal stalls behind anything slow here — the PR-8 bug class)
        while not self._stop.is_set():
            if _FP_LOOP.armed:
                _FP_LOOP.fire(leader=int(self._m_leader.value() or 0))
            try:
                self._events.get(timeout=self.poll)
                while True:  # coalesce bursts
                    self._events.get_nowait()
            except queue.Empty:
                pass

            # job-level terminal state?
            job_meta = self._job_watch.snapshot().get("status")
            if job_meta is not None and job_meta.value == COMPLETE:
                logger.info("pod %s: job COMPLETE, exiting", self.pod.pod_id[:8])
                return 0

            # an externally published preempt/{us} key (the scaler's
            # leader releasing this pod) is a notice too — a held pod
            # with no workers has no other way to learn it must leave
            if (
                not self._draining
                and not self._preempt_notice.is_set()
                and self.pod.pod_id in self._draining_pods()
            ):
                logger.warning(
                    "pod %s: preempt notice found in store; draining",
                    self.pod.pod_id[:8],
                )
                self._preempt_notice.set()

            # a preemption notice turns the pass into a drain (idempotent:
            # repeat signals find _draining already set)
            if self._preempt_notice.is_set() and not self._draining:
                try:
                    self._begin_drain()
                except EdlStoreError as exc:
                    logger.warning(
                        "pod %s: drain bookkeeping failed (%s); draining "
                        "anyway", self.pod.pod_id[:8], exc,
                    )

            # Every duty below is level-triggered off watch snapshots, so
            # a store blip mid-pass is survivable by construction: log it,
            # let the next poll tick re-derive and retry. Crashing the
            # launcher on a transient EdlConnectionError would convert a
            # sub-TTL store outage into a full pod death.
            try:
                if not self._draining:
                    self._handle_token()
                    self._check_death()
                    if self.rank_reg is None:
                        self._race_rank()
                    leader = self._is_leader()
                    self._m_leader.set(1.0 if leader else 0.0)
                    if leader != self._was_leader:
                        # leader election is the causal root of every
                        # restage: make it a black-box fact edl-timeline
                        # can order the drain/publish chain against —
                        # and, when a token is in flight, a segment of
                        # that token's restage trace
                        token = self._handled_token
                        if leader and token:
                            with obs_trace.op_segment(
                                "election", "restage", token,
                                pod=self.pod.pod_id[:8],
                                slot=str(self.rank_slot),
                            ):
                                obs_events.record(
                                    "leader", fsync=True, leader=leader,
                                    pod=self.pod.pod_id[:8],
                                    slot=self.rank_slot,
                                )
                        else:
                            obs_events.record(
                                "leader", fsync=True, leader=leader,
                                pod=self.pod.pod_id[:8], slot=self.rank_slot,
                            )
                        self._was_leader = leader
                    if leader:
                        self._maybe_publish()
                        self._maybe_complete_job()
                    self._adopt_cluster()
                    self._check_stragglers()
            except EdlStoreError as exc:
                logger.warning(
                    "pod %s: store unavailable mid-pass (%s); retrying "
                    "next tick", self.pod.pod_id[:8], exc,
                )

            # (the cache exchange rescans its dir on its own thread —
            # sha256 over TPU-sized entries must never ride this loop)

            # supervise local workers
            if self.procs and self._draining:
                # a draining pod reaps workers INDIVIDUALLY: a rank that
                # finished its drain fast must not tear down a peer still
                # writing its emergency checkpoint. Any exit — drained or
                # crashed — is final here: no grace hold, no respawn.
                for wp in self.procs:
                    if wp.exit_code is None:
                        wp.exit_code = wp.proc.poll()
                exited = [wp for wp in self.procs if wp.exit_code is not None]
                if exited:
                    procs_mod.close_worker_logs(exited)
                    if any(wp.exit_code == DRAINED_EXIT for wp in exited):
                        self._drained_workers = True
                    self.procs = [
                        wp for wp in self.procs if wp.exit_code is None
                    ]
                    if not self.procs:
                        self.running = None
                        logger.info(
                            "pod %s: all workers down; drain complete",
                            self.pod.pod_id[:8],
                        )
                    self._wake()
            elif self.procs:
                code = procs_mod.watch_local_workers(self.procs)
                if code == 0 and not self.completed:
                    self.completed = True
                    procs_mod.close_worker_logs(self.procs)
                    self.procs = []
                    logger.info("pod %s workers COMPLETE", self.pod.pod_id[:8])
                    self._wake()
                elif code == DRAINED_EXIT:
                    # workers saw the preempt key before the launcher's own
                    # signal (delivery races): adopt their decision — flip
                    # into draining; the next pass reaps them individually
                    logger.info(
                        "pod %s worker drained before the launcher noticed; "
                        "joining the drain", self.pod.pod_id[:8],
                    )
                    self._preempt_notice.set()
                    self._wake()
                elif code == HOT_RESTAGE_EXIT and self.hot:
                    # a hot worker could not adopt in-process and asks for
                    # a cold respawn — a restage request, not a failure
                    # (bounded: RAPID repeated fallbacks become real
                    # failures; ones spaced out by recovered training decay)
                    now = time.time()
                    if now - self._hot_fallback_ts > 10 * self.hot_grace:
                        self._hot_fallbacks = 0
                    self._hot_fallback_ts = now
                    self._hot_fallbacks += 1
                    self._m_hot_fallbacks.inc()
                    self._hot_deadline = None
                    self._kill_workers()
                    if self._hot_fallbacks > 3:
                        logger.error(
                            "pod %s: %d consecutive hot-restage fallbacks; "
                            "treating as failure",
                            self.pod.pod_id[:8], self._hot_fallbacks,
                        )
                        return HOT_RESTAGE_EXIT
                    logger.info(
                        "pod %s worker requested respawn (hot-restage "
                        "fallback %d)",
                        self.pod.pod_id[:8], self._hot_fallbacks,
                    )
                    self._wake()
                elif code is not None and code != 0:
                    self._m_worker_failures.inc()
                    failed_stage = (
                        self.running.stage if self.running is not None else ""
                    )
                    grace = self.fail_grace
                    logger.warning(
                        "pod %s worker failed with exit code %d; holding "
                        "%.1fs for a restage before leaving",
                        self.pod.pod_id[:8], code, grace,
                    )
                    self._kill_workers()
                    self._worker_failure = (
                        code, time.time() + grace, failed_stage, grace
                    )
                    self._wake()
            if self.completed and not self._complete_published:
                # COMPLETE must survive a store blip: publish is retried
                # every tick until it lands (the key is permanent, so one
                # success is enough)
                try:
                    self.registry.set_permanent(
                        STATUS_SERVICE, self.pod.pod_id, COMPLETE
                    )
                    self._complete_published = True
                except EdlStoreError as exc:
                    logger.warning(
                        "pod %s: COMPLETE not yet published (%s); retrying",
                        self.pod.pod_id[:8], exc,
                    )
            if self._draining and (
                not self.procs or time.time() > self._drain_deadline
            ):
                return self._finish_drain()
            if self._worker_failure is not None:
                code, deadline, failed_stage, grace = self._worker_failure
                if self.running is not None and self.running.stage != failed_stage:
                    # restaged into a new generation: the crash was
                    # transition collateral, forget it
                    self._worker_failure = None
                elif time.time() > deadline:
                    logger.error(
                        "pod %s worker failed (exit %d) and membership "
                        "stayed stable for %.1fs; leaving job",
                        self.pod.pod_id[:8], code, grace,
                    )
                    return code
        return 0

    def shutdown(self) -> None:
        self._stop.set()
        self._wake()


def launch(
    job_env: JobEnv,
    training_script: str,
    training_args: Sequence[str] = (),
    **kwargs,
) -> int:
    return ElasticLauncher(job_env, training_script, training_args, **kwargs).run()


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.launch",
        description="Elastic TPU training launcher (≙ reference edl.collective.launch)",
    )
    parser.add_argument("--job_id", default=None)
    parser.add_argument("--store", default=None, help="store endpoint ip:port")
    parser.add_argument(
        "--embed_store",
        action="store_true",
        help="host the coordination store in this launcher if the port is free "
        "(first pod on the host wins; others connect)",
    )
    parser.add_argument(
        "--store_data_dir",
        default=None,
        help="durable state dir for the embedded store (snapshot + wal): a "
        "restarted store on the same dir recovers every key and lease",
    )
    parser.add_argument(
        "--store_replica_dir",
        default=None,
        help="shared-storage replica for the embedded store's snapshots "
        "(store-HOST loss recovery: a replacement embedded store on a "
        "fresh host with an empty data dir seeds itself from here)",
    )
    parser.add_argument(
        "--shards",
        type=int,
        default=int(os.environ.get("EDL_STORE_SHARDS", "1")),
        help="with --embed_store: partition the store keyspace over this "
        "many primaries (consecutive ports from --store's; shard map "
        "published under /store/shards/ so every client discovers the "
        "topology and routes by key). EDL_STORE_SHARDS also sets it. "
        "See DESIGN.md 'Sharded control plane'.",
    )
    parser.add_argument(
        "--store_standby",
        default=None,
        metavar="DATA_DIR",
        help="co-host a WARM-STANDBY store in this launcher (durable "
        "state under DATA_DIR): it live-replicates the primary at "
        "--store and promotes itself — with an epoch bump that fences "
        "the old primary — if the primary dies. Skipped on the pod that "
        "won the --embed_store bind (a standby co-located with its "
        "primary protects nothing). EDL_STORE_STANDBY=dir also enables.",
    )
    parser.add_argument(
        "--store_standby_priority",
        type=int,
        default=int(os.environ.get("EDL_STORE_STANDBY_PRIORITY", "1")),
        help="promotion order among standbys (1 = first in line)",
    )
    parser.add_argument("--nodes_range", default=None, help='"min:max" elastic window')
    parser.add_argument("--nproc_per_node", type=int, default=None)
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("--ckpt_path", default=None)
    parser.add_argument(
        "--compile_cache_dir",
        default=None,
        help="persistent XLA compilation cache shared across resizes "
        "(default: a job-scoped tmp dir; 'none' disables)",
    )
    parser.add_argument("--ttl", type=float, default=10.0, help="liveness lease TTL (s)")
    parser.add_argument(
        "--fail_grace",
        type=float,
        default=None,
        help="seconds a worker crash waits for a restage before the pod "
        "abandons the job (default: EDL_FAIL_GRACE or 3x the lease TTL). "
        "Remaining grace is exported as edl_launch_grace_remaining_seconds.",
    )
    parser.add_argument(
        "--drain_budget",
        type=float,
        default=None,
        help="seconds a preemption-noticed pod gives its workers for the "
        "emergency checkpoint before killing what remains (default: "
        "EDL_DRAIN_BUDGET or 10). SIGTERM/SIGUSR1 starts the drain.",
    )
    parser.add_argument(
        "--prewarm",
        action="store_true",
        help="warm the compile cache for the other world sizes in the "
        "elastic window via background shadow stages (CPU meshes; see "
        "edl_tpu/launch/warm.py). EDL_PREWARM=1 also enables.",
    )
    parser.add_argument(
        "--standby",
        action="store_true",
        help="keep pre-imported hot-standby worker shells so restages "
        "skip the python+jax cold start (launch/standby.py). "
        "EDL_STANDBY=1 also enables; EDL_STANDBY=0 force-disables.",
    )
    parser.add_argument(
        "--hot-restage",
        action="store_true",
        help="let surviving workers adopt new stages IN-PROCESS "
        "(jax.distributed shutdown/initialize cycle + checkpoint "
        "restore) instead of kill+respawn; dirty handovers fall back "
        "to respawn. EDL_HOT_RESTAGE=1 also enables.",
    )
    parser.add_argument("training_script")
    parser.add_argument("training_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    embedded = None
    embedded_shards = []
    standby = None
    if args.embed_store and args.store:
        from edl_tpu.utils.net import split_endpoint

        host, port = split_endpoint(args.store)
        try:
            from edl_tpu.store.server import StoreServer

            embedded = StoreServer(
                host="0.0.0.0", port=port, data_dir=args.store_data_dir,
                replica_dir=args.store_replica_dir, name="store-0",
            ).start()
            logger.info("embedded store serving on :%d", port)
        except OSError:
            logger.info("store port %d already bound; connecting as client", port)
        if embedded is not None and args.shards > 1:
            # sharded control plane: shard 0 (the meta shard, above) won
            # the bind; shards 1..N-1 take the consecutive ports, and
            # the map rows under /store/shards/ tell every client —
            # launchers, workers, edl-top — how to route by key
            from edl_tpu.store import shard as shard_mod
            from edl_tpu.store.client import StoreClient

            shard_eps = [["%s:%d" % (split_endpoint(args.store)[0], port)]]
            for i in range(1, args.shards):
                data_dir = (
                    os.path.join(args.store_data_dir, "shard-%d" % i)
                    if args.store_data_dir else None
                )
                try:
                    srv = StoreServer(
                        host="0.0.0.0", port=port + i, data_dir=data_dir,
                        name="store-%d" % i,
                    ).start()
                except OSError as exc:
                    # a half-started shard fleet must not leak: this pod
                    # won the meta bind, so nobody else is starting the
                    # fleet — a busy shard port is a misconfiguration,
                    # not a race to lose gracefully
                    for started in embedded_shards:
                        started.stop()
                    embedded.stop()
                    raise RuntimeError(
                        "--shards %d needs ports %d-%d free; port %d is "
                        "not (%s)" % (
                            args.shards, port, port + args.shards - 1,
                            port + i, exc,
                        )
                    ) from exc
                embedded_shards.append(srv)
                shard_eps.append(
                    ["%s:%d" % (split_endpoint(args.store)[0], port + i)]
                )
            seed = StoreClient(args.store, timeout=10.0)
            try:
                shard_mod.publish_shard_map(seed, shard_eps)
            finally:
                seed.close()
            logger.info(
                "store keyspace sharded over %d primaries (ports %d-%d)",
                args.shards, port, port + args.shards - 1,
            )
    standby_dir = args.store_standby or os.environ.get("EDL_STORE_STANDBY")
    if standby_dir and args.store and embedded is None:
        # supervise a co-hosted warm standby: it replicates the primary
        # live and takes over (epoch-fenced) if the primary dies. Only on
        # pods that do NOT host the primary — a standby sharing the
        # primary's failure domain protects nothing.
        from edl_tpu.store.server import StoreServer
        from edl_tpu.utils.net import get_host_ip

        standby = StoreServer(
            host="0.0.0.0",
            port=0,
            data_dir=standby_dir,
            follow=args.store,
            priority=args.store_standby_priority,
        )
        standby._advertise = "%s:%d" % (get_host_ip(), standby.port)
        standby.start()
        logger.info(
            "warm-standby store on :%d following %s (priority %d)",
            standby.port, args.store, args.store_standby_priority,
        )

    job_env = JobEnv(
        job_id=args.job_id,
        store_endpoint=args.store,
        nodes_range=args.nodes_range,
        nproc_per_node=args.nproc_per_node,
        log_dir=args.log_dir,
        ckpt_path=args.ckpt_path,
        compile_cache_dir=args.compile_cache_dir,
    )
    try:
        return launch(
            job_env,
            args.training_script,
            args.training_args,
            ttl=args.ttl,
            prewarm=args.prewarm,
            standby=args.standby,
            hot_restage=args.hot_restage,
            fail_grace=args.fail_grace,
            drain_budget=args.drain_budget,
        )
    finally:
        if standby is not None:
            standby.stop()
        for srv in embedded_shards:
            srv.stop()
        if embedded is not None:
            embedded.stop()


if __name__ == "__main__":
    sys.exit(main())
