"""Proactive XLA compile-cache warming for anticipated world sizes.

The elastic window makes resize targets *predictable*: the launcher knows
``nodes_range``, so every world size the job can ever be resized to is
enumerable up front. The persistent compilation cache
(:func:`edl_tpu.train.context.enable_compilation_cache`) only pays off on
*revisited* world sizes — the measured grow transition 2→4 cost 28.3 s of
downtime, 25.3 s of it a first-visit compile
(bench_results/resize_cpu_r03_recovery.json). This module removes the
first visit: while the current stage trains, a :class:`CacheWarmer`
thread spawns *shadow stages* — w short-lived worker processes with the
same script, env contract, and a private ``jax.distributed`` coordinator —
that run two train steps and exit (step 1 caches the host-placed-state
compile, step 2 the steady-state mesh-sharded one), populating the
shared cache with the executables the real w-sized stage will ask for.
When the resize lands, spawn→first-step hits a warm cache the first
time.

The reference never had this problem to solve: Paddle program *build* was
cheap, so its stop-resume restart cost no compile
(/root/reference/python/edl/collective/launch.py:200-244). XLA's
whole-program compilation is the TPU-native cost model, and prewarming is
its TPU-native answer.

Shadow stages need devices. On CPU meshes (tests, the resize bench,
``xla_force_host_platform_device_count`` simulations) devices are virtual
and free, so shadow stages are exact: same HLO, same process count, same
device assignment → same cache key. On real TPU the chips are owned by
the live stage, so shadow stages cannot run; warming is CPU-gated
(``EDL_PREWARM_FORCE=1`` overrides for single-host multi-chip setups
where spare chips exist).

Worker-side contract: the warm processes run the SAME training script
with ``EDL_WARM_ONLY=1``; :func:`edl_tpu.train.context.warm_only` reads
it, and ``ElasticTrainer.fit`` (or a hand-rolled loop, see
tools/resize_bench_worker.py) exits 0 after the second completed step —
no checkpoint writes, no store traffic (``EDL_STORE_ENDPOINT`` is
cleared), no data-layer registration.

Cross-pod dedupe rides the store: each size is claimed under
``/{job}/warm/{world}`` — a LEASED registration while the shadow stage
runs (a killed pod's claim lease-expires, so survivors retry), flipped
to a permanent ``done:`` record on success so no pod ever re-warms it.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Dict, List, Optional, Sequence

from edl_tpu.cluster.job_env import JobEnv
from edl_tpu.cluster.model import Cluster, Pod, Worker
from edl_tpu.launch.process import worker_command, worker_env
from edl_tpu.store.client import StoreClient
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import find_free_ports, get_host_ip

logger = get_logger("launch.warm")

WARM_SERVICE = "warm"


def anticipated_world_sizes(job_env: JobEnv) -> List[int]:
    """Every world size the elastic window allows: pods × nproc for each
    pod count in [min_nodes, max_nodes]."""
    return sorted(
        {p * job_env.nproc_per_node
         for p in range(job_env.min_nodes, job_env.max_nodes + 1)}
    )


def _platform_allows_shadow(extra_worker_env: Dict[str, str]) -> bool:
    if os.environ.get("EDL_PREWARM_FORCE") == "1":
        return True
    platform = extra_worker_env.get(
        "JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "")
    )
    return platform.strip().lower() == "cpu"


class CacheWarmer:
    """Background warmer owned by one launcher (pod) process.

    ``note_world(w)`` (called whenever a stage is adopted) records the
    live world size and kicks the thread; the thread walks the pending
    sizes largest-grow-first, claims each through the store, runs one
    shadow stage at a time (host-wide lock), and stops when every
    anticipated size is warmed or the job-wide budget is spent.
    """

    def __init__(
        self,
        job_env: JobEnv,
        pod_id: str,
        training_script: str,
        training_args: Sequence[str] = (),
        extra_worker_env: Optional[Dict[str, str]] = None,
        client: Optional[StoreClient] = None,
        max_sizes: Optional[int] = None,
        warm_timeout: float = 900.0,
    ) -> None:
        self.job_env = job_env
        self.pod_id = pod_id
        self.training_script = training_script
        self.training_args = list(training_args)
        self.extra_worker_env = dict(extra_worker_env or {})
        self._client = client  # edl: guarded-by(self._mu)
        self._owns_client = client is None
        self.max_sizes = max_sizes or int(
            os.environ.get("EDL_PREWARM_MAX", "4")
        )
        self.warm_timeout = warm_timeout
        # guards _pending and _client (launcher + warmer threads): stop()
        # closes the lazily-dialed client the warmer thread creates
        self._mu = threading.Lock()
        self._pending = set(anticipated_world_sizes(job_env))
        self._attempts: Dict[int, int] = {}
        self._current_world = 0
        self._budget = self.max_sizes
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._procs: List[subprocess.Popen] = []
        self._thread: Optional[threading.Thread] = None
        self.warmed: List[int] = []

    # -- lifecycle ---------------------------------------------------------

    def note_world(self, world: int) -> None:
        """The live stage compiles ``world`` itself — drop it and wake."""
        self._current_world = world
        with self._mu:
            self._pending.discard(world)
        if self._thread is None and not self._stop.is_set():
            self._thread = threading.Thread(
                target=self._run, name="cache-warmer", daemon=True
            )
            self._thread.start()
        self._kick.set()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        self._kill_procs()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        with self._mu:
            owns, client = self._owns_client, self._client
            if owns:
                self._client = None
        if owns and client is not None:
            client.close()

    @staticmethod
    def _max_shadow_world() -> int:
        """Largest shadow stage worth spawning on this host (process
        count, not devices). ``EDL_PREWARM_MAX_WORLD`` overrides."""
        return int(os.environ.get("EDL_PREWARM_MAX_WORLD", "32"))

    # -- store claims ------------------------------------------------------

    def _store(self) -> Optional[StoreClient]:
        with self._mu:
            client = self._client
        if client is not None or not self.job_env.store_endpoint:
            return client
        # dial OUTSIDE the lock: note_world() rides the launcher
        # supervision loop and must never wait behind a 10s connect
        try:
            client = StoreClient(self.job_env.store_endpoint, timeout=10.0)
        except EdlStoreError:
            return None
        with self._mu:
            if self._client is None:
                self._client = client
                return client
            existing = self._client
        client.close()  # lost a (theoretical) publish race
        return existing

    def _global_claims(self):
        """Job-wide claim counts ``(done, in_progress)`` across all pods."""
        client = self._store()
        if client is None:
            used = self.max_sizes - max(self._budget, 0)
            return used, 0
        from edl_tpu.discovery.registry import Registry

        try:
            entries = Registry(client, self.job_env.job_id).get_service(
                WARM_SERVICE
            )
        except EdlStoreError:
            return 0, 0
        done = sum(1 for e in entries if e.value.startswith(b"done:"))
        return done, len(entries) - done

    def _claim(self, world: int):
        """Claim ``world`` with a LEASED registration: a pod killed
        mid-warm releases its claim via lease expiry, so the size stays
        warmable by the survivors. Returns ``(claim, holder)`` where
        ``claim`` is the held Registration, True (no store — single-pod
        usage, nothing to dedupe), or None (another pod holds it; then
        ``holder`` is that pod's claim value — ``done:<pod>`` once the
        size is cached for good). Store errors propagate
        (``EdlStoreError``) so the caller can retry rather than
        permanently skip the size."""
        client = self._store()
        if client is None:
            return True, None
        from edl_tpu.discovery.registry import Registry

        reg, holder = Registry(client, self.job_env.job_id).register_if_absent(
            WARM_SERVICE,
            str(world),
            self.pod_id.encode(),
            ttl=max(30.0, self.warm_timeout / 10),
        )
        return reg, holder

    def _finish_claim(self, world: int, reg, ok: bool) -> None:
        """Success: convert the leased claim to a permanent ``done:``
        record (the size is cached for the job's lifetime; other pods
        stop retrying it). Failure: delete so any pod may retry."""
        if reg is True:
            return
        if ok:
            client = self._store()
            if client is not None:
                from edl_tpu.discovery.registry import Registry

                try:
                    # detach the lease first (permanent put), then stop
                    # the keeper without deleting
                    Registry(client, self.job_env.job_id).set_permanent(
                        WARM_SERVICE, str(world),
                        b"done:" + self.pod_id.encode(),
                    )
                except EdlStoreError:
                    pass
            reg.stop(delete=False)
        else:
            reg.stop(delete=True)

    # -- the warm loop -----------------------------------------------------

    def _run(self) -> None:
        # Let the LIVE stage finish its own cold compile before spawning
        # shadow work: warming that races the stage it serves slows both
        # (measured on a shared-core host: the live first compile went
        # 12 s -> 37 s next to an undelayed 4-proc shadow stage).
        delay = float(os.environ.get("EDL_PREWARM_DELAY", "15"))
        if self._stop.wait(timeout=delay):
            return
        while not self._stop.is_set():
            self._kick.wait(timeout=5.0)
            self._kick.clear()
            if self._stop.is_set():
                return
            with self._mu:
                empty = not self._pending
            if empty or self._budget <= 0:
                return
            done, in_progress = self._global_claims()
            if done >= self.max_sizes:
                # job-wide budget: EDL_PREWARM_MAX counts sizes warmed by
                # ANY pod (per-pod budgets let co-located pods multiply
                # shadow work and overlap live transitions)
                return
            if done + in_progress >= self.max_sizes:
                # budget would be met IF the in-progress warms finish —
                # but a SIGKILLed holder's lease expires, so keep the
                # thread alive and re-check instead of exiting for good
                continue
            # Largest feasible grow first: a grow is the expensive
            # first-visit (new hardware idling through a cold compile),
            # the largest world is the costliest compile, and resizes
            # routinely jump straight to the target size. Shrink sizes
            # follow largest (nearest) first. Oversized shadow stages
            # are skipped outright — a wide elastic window must not
            # spawn hundreds of procs here.
            with self._mu:
                feasible = [
                    w for w in self._pending
                    if w <= self._max_shadow_world()
                ]
                if not feasible:
                    return
                grows = [w for w in feasible if w > self._current_world]
                world = max(grows) if grows else max(feasible)
                self._pending.discard(world)
            try:
                claim, holder = self._claim(world)
            except EdlStoreError as exc:
                # transient store trouble (restart, reconnect): the size
                # was claimed by nobody — requeue and retry
                logger.warning("warm: claim world=%d errored (%s)", world, exc)
                self._requeue(world)
                continue
            if claim is None:
                if holder is not None and holder.startswith(b"done:"):
                    # another pod finished this size: drop it for good
                    logger.info("warm: world=%d already cached elsewhere", world)
                else:
                    # leased in-progress claim: if its holder dies, the
                    # lease expires and a later retry here picks it up
                    logger.info(
                        "warm: world=%d being warmed by another pod", world
                    )
                    self._requeue(world)
                continue
            lock = self._host_lock()
            if lock is False:
                # another pod on this host is mid-warm; requeue and retry
                self._finish_claim(world, claim, ok=False)
                self._requeue(world)
                continue
            try:
                self._budget -= 1
                ok = self._warm_one(world)
            except Exception as exc:  # degrade, never kill the warmer
                logger.warning("warm: world=%d failed (%s)", world, exc)
                ok = False
            finally:
                if lock is not None:
                    lock.stop(delete=True)
            self._finish_claim(world, claim, ok)
            if ok:
                self.warmed.append(world)
            else:
                # one retry: refund the budget and requeue so a transient
                # failure (port race, worker crash) doesn't silently
                # disable prewarming for the rest of the job
                attempts = self._attempts.get(world, 0) + 1
                self._attempts[world] = attempts
                if attempts < 2:
                    self._budget += 1
                    self._requeue(world)
            self._kick.set()

    def _requeue(self, world: int) -> None:
        """Put ``world`` back in the pending pool and pace the retry."""
        with self._mu:
            self._pending.add(world)
        if self._stop.wait(timeout=2.0):
            return
        self._kick.set()

    def _host_lock(self):
        """One warm stage per HOST at a time: concurrent shadow stages
        from co-located pods oversubscribe the same cores and slow every
        compile (measured: a 3-proc warm took 66 s next to a concurrent
        4-proc one on a shared host). Returns a held Registration, None
        (no store → single launcher assumed), or False (lock busy)."""
        client = self._store()
        if client is None:
            return None
        from edl_tpu.discovery.registry import Registry

        try:
            reg, _holder = Registry(client, self.job_env.job_id).register_if_absent(
                WARM_SERVICE + "_lock",
                get_host_ip(),
                self.pod_id.encode(),
                ttl=max(30.0, self.warm_timeout / 10),
            )
        except EdlStoreError:
            # transient store trouble must NOT bypass the one-warm-per-
            # host serialization: report busy so the caller retries
            return False
        return reg if reg is not None else False

    def _warm_one(self, world: int) -> bool:
        """Spawn one shadow stage of ``world`` workers; True on success."""
        addr = get_host_ip()
        try:
            ports = find_free_ports(world)
        except OSError:
            return False
        pod = Pod(
            addr=addr,
            workers=[
                Worker(endpoint="%s:%d" % (addr, ports[i]), rank_in_pod=i)
                for i in range(world)
            ],
        )
        cluster = Cluster.from_pods([pod], stage="warm-%d" % world)
        extra = {
            **self.extra_worker_env,
            "EDL_JOB_ID": self.job_env.job_id,
            "EDL_WARM_ONLY": "1",
            "EDL_STORE_ENDPOINT": "",
            "EDL_CKPT_PATH": "",
            "EDL_COMPILE_CACHE_DIR": self.job_env.compile_cache_dir,
        }
        t0 = time.time()
        log_files = []
        if self.job_env.log_dir:
            os.makedirs(self.job_env.log_dir, exist_ok=True)
        try:
            # shadow compiles yield cores to the live stage; on hosts
            # where warming must outrace an imminent resize (single-core
            # CI, bench rigs) EDL_PREWARM_NICE=0 makes it compete
            nice = os.environ.get("EDL_PREWARM_NICE", "10")
            for worker in pod.workers:
                env = worker_env(cluster, pod, worker, extra)
                cmd = [
                    "nice", "-n", nice,
                    *worker_command(self.training_script, self.training_args),
                ]
                log_file = None
                if self.job_env.log_dir:
                    log_file = open(
                        os.path.join(
                            self.job_env.log_dir,
                            "warmlog.%d.%d" % (world, worker.global_rank),
                        ),
                        "ab",
                    )
                    log_files.append(log_file)
                self._procs.append(
                    subprocess.Popen(
                        cmd,
                        env=env,
                        stdout=log_file or subprocess.DEVNULL,
                        stderr=subprocess.STDOUT if log_file
                        else subprocess.DEVNULL,
                        start_new_session=True,
                    )
                )
            logger.info(
                "warm: shadow stage world=%d spawned (%d procs)",
                world, len(self._procs),
            )
            deadline = time.time() + self.warm_timeout
            codes = [None] * len(self._procs)
            while time.time() < deadline and not self._stop.is_set():
                for i, proc in enumerate(self._procs):
                    if codes[i] is None:
                        codes[i] = proc.poll()
                if all(c is not None for c in codes):
                    break
                time.sleep(0.25)
            ok = all(c == 0 for c in codes)
            if ok:
                logger.info(
                    "warm: world=%d cached in %.1fs", world, time.time() - t0
                )
            else:
                logger.warning(
                    "warm: world=%d failed (exit codes %s)", world, codes
                )
            return ok
        finally:
            self._kill_procs()
            for f in log_files:
                f.close()

    def _kill_procs(self) -> None:
        # start_new_session put each shadow worker in its own session, so
        # killing the process GROUP reaps forked descendants too (data
        # loaders etc.) — same teardown contract as the live workers'
        # terminate_local_workers
        import signal as _signal

        for proc in self._procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, _signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    try:
                        proc.kill()
                    except OSError:
                        pass
        for proc in self._procs:
            try:
                proc.wait(timeout=5.0)
            except (subprocess.TimeoutExpired, OSError):
                pass
        self._procs = []


def make_warmer_if_enabled(
    job_env: JobEnv,
    pod_id: str,
    training_script: str,
    training_args: Sequence[str],
    extra_worker_env: Dict[str, str],
    prewarm: bool,
) -> Optional[CacheWarmer]:
    """Launcher hook: a :class:`CacheWarmer` when prewarming makes sense.

    Enabled by the ``--prewarm`` flag or ``EDL_PREWARM=1``; requires a
    compile cache dir, more than one anticipated size, and a platform
    where shadow stages can run (CPU, or ``EDL_PREWARM_FORCE=1``).
    """
    if not (prewarm or os.environ.get("EDL_PREWARM") == "1"):
        return None
    if not job_env.compile_cache_dir:
        logger.info("prewarm requested but compile cache disabled; skipping")
        return None
    if len(anticipated_world_sizes(job_env)) <= 1:
        return None
    if not _platform_allows_shadow(extra_worker_env):
        logger.info(
            "prewarm skipped: shadow stages need free devices (CPU meshes); "
            "on TPU the live stage owns the chips (EDL_PREWARM_FORCE=1 to "
            "override on hosts with spare chips)"
        )
        return None
    return CacheWarmer(
        job_env, pod_id, training_script, training_args, extra_worker_env
    )
