"""Teacher model distribution: fetch params by URI with checksum caching.

Capability parity with the reference's HDFS teacher fetch
(``download_hdfs_file``, reference python/edl/distill/utils.py:20, env
``PADDLE_DISTILL_HDFS_{NAME,UGI,PATH}``): a teacher daemon starting on a
fresh host pulls its serving params from shared storage before it can
register. Here the source is a URI — a local path, ``file://``,
``http(s)://``, or ``gs://`` — with an optional sha256 that both
verifies integrity and keys a local cache, so restarting teachers (the
normal state of affairs in an elastic fleet) never re-download.

Env contract (mirrors the reference's):

    EDL_DISTILL_MODEL_URI       where to fetch the params from
    EDL_DISTILL_MODEL_SHA256    optional integrity/cache checksum
    EDL_DISTILL_MODEL_CACHE     cache dir (default ~/.cache/edl_tpu/models)

The fetched artifact is opaque bytes to this module; the flagship use is
a flax ``serialization.to_bytes`` msgpack of ``{"params", "batch_stats"}``
(see examples/distill_teacher.py).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional

from edl_tpu.utils.log import get_logger

logger = get_logger(__name__)

_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "edl_tpu", "models"
)
_CHUNK = 1 << 20


class FetchError(RuntimeError):
    """Model fetch failed (bad URI, transport error, checksum mismatch)."""


def sha256_of(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def _verify(path: str, sha256: Optional[str]) -> None:
    if sha256 is None:
        return
    got = sha256_of(path)
    if got != sha256.lower():
        raise FetchError(
            "checksum mismatch for %s: want %s got %s" % (path, sha256, got)
        )


def _tmp_for(dest: str) -> str:
    # per-process temp file in the destination dir: concurrent fetchers of
    # the same URI each write privately and the os.replace really is atomic
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(dest) + ".", suffix=".part",
        dir=os.path.dirname(dest),
    )
    os.close(fd)
    return tmp


def _http_download(uri: str, dest: str, timeout: float, retries: int) -> None:
    last: Optional[Exception] = None
    for attempt in range(retries):
        tmp = _tmp_for(dest)
        try:
            with urllib.request.urlopen(uri, timeout=timeout) as resp, open(
                tmp, "wb"
            ) as out:
                shutil.copyfileobj(resp, out, _CHUNK)
                # fsync before the atomic rename: a torn model file that
                # *looks* complete would fail sha256 verification only
                # after a worker already spent its restage budget on it
                out.flush()
                os.fsync(out.fileno())
            os.replace(tmp, dest)
            return
        except Exception as exc:  # noqa: BLE001 — urllib raises many types
            last = exc
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if (
                isinstance(exc, urllib.error.HTTPError)
                and 400 <= exc.code < 500
            ):
                break  # 404/403 won't get better with retries
            logger.warning(
                "fetch attempt %d/%d for %s failed: %s",
                attempt + 1, retries, uri, exc,
            )
            time.sleep(min(2.0 ** attempt, 10.0))
    raise FetchError("download failed for %s: %s" % (uri, last))


def _gs_download(uri: str, dest: str) -> None:
    gsutil = shutil.which("gsutil")
    if gsutil is None:
        raise FetchError(
            "gs:// URI %s requires gsutil on PATH (not available in this "
            "environment); serve the artifact over http(s) instead" % uri
        )
    tmp = _tmp_for(dest)
    proc = subprocess.run(
        [gsutil, "cp", uri, tmp], capture_output=True, text=True
    )
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise FetchError(
            "gsutil cp %s failed: %s" % (uri, proc.stderr[-400:])
        )
    os.replace(tmp, dest)


def fetch_model(
    uri: str,
    sha256: Optional[str] = None,
    cache_dir: Optional[str] = None,
    timeout: float = 600.0,
    retries: int = 3,
) -> str:
    """Fetch ``uri`` into the local cache and return the local path.

    Local paths (and ``file://``) are verified in place and returned
    without copying. Remote URIs land in
    ``{cache}/{sha256-or-uri-hash}/{basename}``; a cached file whose
    checksum still matches short-circuits the download entirely.
    """
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    if "://" not in uri:
        if not os.path.exists(uri):
            raise FetchError("local model path %s does not exist" % uri)
        _verify(uri, sha256)
        return uri

    cache_dir = cache_dir or os.environ.get(
        "EDL_DISTILL_MODEL_CACHE", _DEFAULT_CACHE
    )
    key = (sha256 or hashlib.sha256(uri.encode()).hexdigest())[:32]
    name = os.path.basename(uri.split("?", 1)[0]) or "model"
    dest_dir = os.path.join(cache_dir, key)
    dest = os.path.join(dest_dir, name)
    if os.path.exists(dest):
        try:
            _verify(dest, sha256)
            logger.info("model cache hit: %s", dest)
            return dest
        except FetchError:
            logger.warning("cached %s fails checksum; re-fetching", dest)
            os.unlink(dest)

    os.makedirs(dest_dir, exist_ok=True)
    scheme = uri.split("://", 1)[0]
    if scheme in ("http", "https"):
        _http_download(uri, dest, timeout, retries)
    elif scheme == "gs":
        _gs_download(uri, dest)
    else:
        raise FetchError("unsupported scheme %r in %s" % (scheme, uri))
    try:
        _verify(dest, sha256)
    except FetchError:
        os.unlink(dest)  # never leave a corrupt artifact in the cache
        raise
    logger.info("fetched %s -> %s", uri, dest)
    return dest


def fetch_from_env() -> Optional[str]:
    """Fetch the teacher model named by ``EDL_DISTILL_MODEL_URI`` (the
    reference reads its HDFS coordinates from env the same way); returns
    None when unset so callers can fall back to fresh init."""
    uri = os.environ.get("EDL_DISTILL_MODEL_URI")
    if not uri:
        return None
    return fetch_model(uri, sha256=os.environ.get("EDL_DISTILL_MODEL_SHA256"))
