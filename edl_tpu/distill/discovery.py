"""Teacher discovery + load balancing for the distillation layer.

Capability parity with the reference's discovery stack (it ships two —
etcd/gRPC ``BalanceTable`` (python/edl/distill/balance_table.py) and the
redis/epoll twin (python/edl/distill/redis/) — which exist only to offer a
choice of external store; here ONE stack over the edl_tpu coordination
store covers both):

- **teacher side**: :class:`TeacherRegister` registers an endpoint under a
  service name once its port answers, then heartbeats via the store lease
  (≙ python/edl/discovery/register.py:29-143).
- **balancer**: :class:`BalanceTable` watches the teacher service and
  tracks registered student clients, assigning teachers to clients with
  the reference's greedy caps (balance_table.py:244-246):
  at most ``ceil(clients/teachers)`` clients per teacher and
  ``max(1, teachers/clients)`` teachers per client; client views are
  versioned so students only reconnect on real change.
- **student side**: :class:`DiscoveryClient` registers, heartbeats, and
  exposes ``get_servers() -> (version, [endpoints])``
  (≙ python/edl/distill/discovery_client.py).

The balancer runs *inside the store's keyspace*: assignments are written
to ``assign/{client_id}`` keys, so students watch their own key instead of
polling a bespoke RPC service — one server process fewer than the
reference, same behavior. A :class:`DiscoveryService` daemon hosts the
BalanceTable; multiple daemons shard service-names by consistent hash
(≙ the reference's ``__balance__`` self-registration + REDIRECT,
balance_table.py:376-391, 487-495) — a client simply connects to the shard
owner's store keyspace, no redirect round-trip needed because assignment
delivery is store-watch based.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from edl_tpu.discovery.consistent_hash import ConsistentHash
from edl_tpu.discovery.registry import Registry, ServerMeta
from edl_tpu.store.client import StoreClient
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.net import wait_until_alive

logger = get_logger("distill.discovery")

TEACHER_SERVICE = "distill/teachers/%s"  # % service_name
CLIENT_SERVICE = "distill/clients/%s"
ASSIGN_SERVICE = "distill/assign/%s"
BALANCER_SERVICE = "distill/balancers"
# circuit-breaker ejection: clients lease sick reports here, named
# "endpoint|client_id" so reports from different students coexist and a
# dead reporter's opinion expires with its lease
SICK_SERVICE = "distill/sick/%s"


DRAINING = b"draining"  # registration payload of a teacher on notice


class TeacherRegister:
    """Register a live teacher endpoint; the store lease is the heartbeat.

    Waits for the serving port to answer before registering (the
    reference's ``register.py:78`` does the same TCP probe).

    Graceful drain (health plane): :meth:`drain` flips the registration
    payload to ``draining`` — the balancer drops the endpoint from every
    assignment on the next watch tick, so students stop sending NEW work
    while in-flight predicts finish, instead of discovering the teacher
    via connection failures after it dies. When the hosting pod's id is
    known (``pod_id`` arg or ``EDL_POD_ID`` env), the register also
    watches the job's ``preempt/`` keyspace and drains itself the moment
    its pod is preemption-noticed."""

    def __init__(
        self,
        store_endpoint: str,
        job_id: str,
        service_name: str,
        teacher_endpoint: str,
        ttl: float = 10.0,
        wait_alive: float = 60.0,
        pod_id: Optional[str] = None,
    ) -> None:
        if not wait_until_alive(teacher_endpoint, timeout=wait_alive):
            raise TimeoutError(
                "teacher %s not accepting connections" % teacher_endpoint
            )
        self._client = StoreClient(store_endpoint)
        self._registry = Registry(self._client, job_id)
        self._endpoint = teacher_endpoint
        self._drained = False
        self._preempt_watch = None
        self._reg = self._registry.register(
            TEACHER_SERVICE % service_name,
            teacher_endpoint,
            b"1",
            ttl=ttl,
        )
        import os as _os

        pod_id = pod_id or _os.environ.get("EDL_POD_ID", "")
        if pod_id:
            from edl_tpu.cluster.contract import PREEMPT_SERVICE

            self._pod_id = pod_id
            try:
                self._preempt_watch = self._registry.watch_service(
                    PREEMPT_SERVICE, on_change=self._on_preempt
                )
            except Exception as exc:  # noqa: BLE001 — optional integration
                logger.warning("teacher preempt watch not armed: %s", exc)
        logger.info("teacher %s registered under %s", teacher_endpoint, service_name)

    def _on_preempt(self, snapshot) -> None:
        if self._pod_id in snapshot and not self._drained:
            logger.warning(
                "teacher %s: hosting pod %s preemption-noticed; draining",
                self._endpoint, self._pod_id[:8],
            )
            self.drain()

    def drain(self) -> None:
        """Graceful teacher drain: leave the balance set now, keep serving
        until the process actually stops."""
        if self._drained:
            return
        self._drained = True
        try:
            self._reg.update(DRAINING)
        except Exception as exc:  # noqa: BLE001 — a failed mark degrades
            # to the old behavior (students find out via dead connections)
            logger.warning("teacher drain mark failed: %s", exc)

    def stop(self) -> None:
        if self._preempt_watch is not None:
            try:
                self._preempt_watch.cancel()
            except Exception:  # noqa: BLE001
                pass
        self._reg.stop(delete=True)
        self._client.close()


class BalanceTable:
    """Greedy teacher↔client assignment with the reference's caps.

    Rebalance triggers: teacher add/remove (store watch), client add/remove
    (store watch). Assignments are published to ``assign/{client}`` keys as
    ``{"v": version, "servers": [...]}``; version bumps only when that
    client's list actually changed (reference balance_table.py versioned
    per-client views).
    """

    def __init__(self, registry: Registry, service_name: str) -> None:
        self._registry = registry
        self._service_name = service_name
        self._lock = threading.Lock()
        self._teachers: List[str] = []
        self._clients: List[str] = []
        self._sick: set = set()
        self._views: Dict[str, Tuple[int, List[str]]] = {}
        self._teacher_watch = registry.watch_service(
            TEACHER_SERVICE % service_name, on_change=self._on_teachers
        )
        self._client_watch = registry.watch_service(
            CLIENT_SERVICE % service_name, on_change=self._on_clients
        )
        self._sick_watch = registry.watch_service(
            SICK_SERVICE % service_name, on_change=self._on_sick
        )

    # -- watch callbacks ---------------------------------------------------

    def _on_teachers(self, servers: Dict[str, ServerMeta]) -> None:
        with self._lock:
            # draining teachers leave the balance set on NOTICE (their
            # registration payload flips), not on connection failure —
            # the reader sheds them while their in-flight work finishes
            self._teachers = sorted(
                name for name, meta in servers.items()
                if meta.value != DRAINING
            )
        self._rebalance()

    def _on_clients(self, clients: Dict[str, ServerMeta]) -> None:
        with self._lock:
            self._clients = sorted(clients)
        self._rebalance()

    def _on_sick(self, reports: Dict[str, ServerMeta]) -> None:
        # report names are "endpoint|client_id"; any live report ejects
        # the endpoint (a breaker-opening client has hard evidence, and
        # the report's lease bounds how long a wrong opinion can stick)
        with self._lock:
            self._sick = {name.split("|", 1)[0] for name in reports}
        self._rebalance()

    # -- the greedy assignment --------------------------------------------

    @staticmethod
    def assign(
        teachers: Sequence[str], clients: Sequence[str]
    ) -> Dict[str, List[str]]:
        """Round-robin with the reference's caps (balance_table.py:244-246):
        ≤ ceil(clients/teachers) clients per teacher,
        max(1, teachers//clients) teachers per client."""
        out: Dict[str, List[str]] = {c: [] for c in clients}
        if not teachers or not clients:
            return out
        per_client = max(1, len(teachers) // len(clients))
        per_teacher_cap = math.ceil(
            len(clients) * per_client / len(teachers)
        )
        load = {t: 0 for t in teachers}
        ti = 0
        for c in clients:
            for _ in range(per_client):
                for _ in range(len(teachers)):  # find a non-full teacher
                    t = teachers[ti % len(teachers)]
                    ti += 1
                    if load[t] < per_teacher_cap:
                        out[c].append(t)
                        load[t] += 1
                        break
        return out

    def _rebalance(self) -> None:
        with self._lock:
            teachers = [t for t in self._teachers if t not in self._sick]
            if not teachers and self._teachers:
                # every teacher reported sick: keep routing to the raw set
                # rather than assigning nobody — per-client breakers still
                # shield each student, and "all sick" usually means the
                # fleet is overloaded, not dead
                teachers = list(self._teachers)
            clients = list(self._clients)
            assignment = self.assign(teachers, clients)
            changed = []
            for client, servers in assignment.items():
                old_version, old_servers = self._views.get(client, (0, None))
                if servers != old_servers:
                    version = old_version + 1
                    self._views[client] = (version, servers)
                    changed.append((client, version, servers))
            for gone in set(self._views) - set(clients):
                del self._views[gone]
                self._registry.remove(
                    ASSIGN_SERVICE % self._service_name, gone
                )
        for client, version, servers in changed:
            self._registry.set_permanent(
                ASSIGN_SERVICE % self._service_name,
                client,
                json.dumps({"v": version, "servers": servers}).encode(),
            )
        if changed:
            logger.info(
                "rebalanced %s: %d teacher(s) over %d client(s), %d view(s) changed",
                self._service_name,
                len(teachers),
                len(clients),
                len(changed),
            )

    def snapshot(self) -> Dict[str, Tuple[int, List[str]]]:
        with self._lock:
            return dict(self._views)

    def stop(self) -> None:
        self._teacher_watch.cancel()
        self._client_watch.cancel()
        self._sick_watch.cancel()


class DiscoveryService:
    """Daemon hosting BalanceTables for the services it owns.

    With replicas, ownership is sharded by consistent hash over the
    balancer ids (≙ reference balance_table.py:376-391): each daemon
    registers under ``distill/balancers`` and (re)claims the services that
    hash to it whenever the balancer set changes.
    """

    def __init__(
        self,
        store_endpoint: str,
        job_id: str,
        service_names: Sequence[str],
        balancer_id: Optional[str] = None,
        ttl: float = 10.0,
    ) -> None:
        self._client = StoreClient(store_endpoint)
        self._registry = Registry(self._client, job_id)
        self._service_names = list(service_names)
        self._balancer_id = balancer_id or ("balancer-%d" % id(self))
        self._tables: Dict[str, BalanceTable] = {}
        self._lock = threading.Lock()
        self._reg = self._registry.register(
            BALANCER_SERVICE, self._balancer_id, b"1", ttl=ttl
        )
        self._peer_watch = self._registry.watch_service(
            BALANCER_SERVICE, on_change=self._on_peers
        )

    def _on_peers(self, peers: Dict[str, ServerMeta]) -> None:
        ring = ConsistentHash(sorted(peers) or [self._balancer_id])
        mine = {
            s for s in self._service_names
            if ring.get_node(s) == self._balancer_id
        }
        with self._lock:
            for name in list(self._tables):
                if name not in mine:
                    self._tables.pop(name).stop()
            for name in mine:
                if name not in self._tables:
                    self._tables[name] = BalanceTable(self._registry, name)
        logger.info(
            "balancer %s owns %d/%d service(s)",
            self._balancer_id,
            len(mine),
            len(self._service_names),
        )

    def owned_services(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    def table(self, name: str) -> Optional[BalanceTable]:
        with self._lock:
            return self._tables.get(name)

    def stop(self) -> None:
        with self._lock:
            tables, self._tables = list(self._tables.values()), {}
        for t in tables:
            t.stop()
        self._peer_watch.cancel()
        self._reg.stop(delete=True)
        self._client.close()


class DiscoveryClient:
    """Student-side discovery: register as a client, watch the assignment.

    ``get_servers()`` returns ``(version, endpoints)``; ``wait_servers()``
    blocks until a non-empty assignment arrives. The store lease is the
    heartbeat (≙ the reference's 2 s heartbeat thread,
    discovery_client.py:155)."""

    def __init__(
        self,
        store_endpoint: str,
        job_id: str,
        service_name: str,
        client_id: str,
        max_teachers: int = 0,
        ttl: float = 10.0,
        on_change: Optional[Callable[[int, List[str]], None]] = None,
    ) -> None:
        self._client = StoreClient(store_endpoint)
        self._registry = Registry(self._client, job_id)
        self._service_name = service_name
        self.client_id = client_id
        self._max = max_teachers
        self._cond = threading.Condition()
        self._version = 0
        self._servers: List[str] = []
        self._on_change = on_change
        self._ttl = ttl
        self._sick_lock = threading.Lock()
        self._sick_regs: Dict[str, object] = {}
        self._reg = self._registry.register(
            CLIENT_SERVICE % service_name, client_id, b"1", ttl=ttl
        )
        self._watch = self._registry.watch_service(
            ASSIGN_SERVICE % service_name, on_change=self._on_assign
        )

    def _on_assign(self, servers: Dict[str, ServerMeta]) -> None:
        meta = servers.get(self.client_id)
        if meta is None:
            return
        view = json.loads(meta.value.decode())
        endpoints = view["servers"]
        if self._max > 0:
            endpoints = endpoints[: self._max]
        with self._cond:
            if view["v"] == self._version:
                return
            self._version, self._servers = view["v"], endpoints
            self._cond.notify_all()
        if self._on_change is not None:
            self._on_change(view["v"], endpoints)

    def get_servers(self) -> Tuple[int, List[str]]:
        with self._cond:
            return self._version, list(self._servers)

    def wait_servers(self, timeout: float = 60.0) -> List[str]:
        deadline = time.time() + timeout
        with self._cond:
            while not self._servers:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        "no teachers assigned for %s" % self._service_name
                    )
                self._cond.wait(remaining)
            return list(self._servers)

    # -- circuit-breaker ejection ------------------------------------------

    def report_sick(self, endpoint: str) -> None:
        """Lease a sick report for ``endpoint`` (breaker opened here).
        The balancer ejects it from every client's assignment; the lease
        means the report dies with this client — a crashed reporter
        cannot permanently exile a healthy teacher."""
        with self._sick_lock:
            if endpoint in self._sick_regs:
                return
            self._sick_regs[endpoint] = self._registry.register(
                SICK_SERVICE % self._service_name,
                "%s|%s" % (endpoint, self.client_id),
                b"1",
                ttl=self._ttl,
            )
        logger.warning(
            "client %s reported %s sick", self.client_id, endpoint
        )

    def clear_sick(self, endpoint: str) -> None:
        """Withdraw this client's sick report (breaker closed)."""
        with self._sick_lock:
            reg = self._sick_regs.pop(endpoint, None)
        if reg is not None:
            reg.stop(delete=True)
            logger.info(
                "client %s cleared sick report for %s",
                self.client_id, endpoint,
            )

    def stop(self) -> None:
        with self._sick_lock:
            regs, self._sick_regs = list(self._sick_regs.values()), {}
        for reg in regs:
            try:
                reg.stop(delete=True)
            except Exception:  # noqa: BLE001 — best-effort on teardown
                pass
        self._watch.cancel()
        self._reg.stop(delete=True)
        self._client.close()
