"""User-facing DistillReader.

Capability parity with the reference's flagship user API
(python/edl/distill/distill_reader.py:68-390): wrap a sample /
sample-list / batch generator so each epoch's data streams through a
fleet of teacher predict servers, yielding the original fields with the
teacher's predictions appended.

Teachers come either fixed (``set_fixed_teacher``) or discovered
dynamically through the balance service (``set_dynamic_teacher`` / env).
Env contract (≙ the reference's ``PADDLE_DISTILL_*``,
distill_reader.py:37, 240-267):

    EDL_DISTILL_STORE          store endpoint for discovery
    EDL_DISTILL_JOB_ID         job scope in the store
    EDL_DISTILL_SERVICE_NAME   teacher service name
    EDL_DISTILL_MAX_TEACHER    cap on teachers used by this reader

Example::

    reader = DistillReader(feeds=("img",), fetchs=("logits",))
    reader.set_fixed_teacher("10.0.0.5:9000")
    reader.set_batch_generator(my_batches)
    for img, label, t_logits in reader():
        ...
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.distill.worker import DistillPipeline
from edl_tpu.utils.log import get_logger

logger = get_logger("distill.reader")

_FP_EPOCH = _fault_point(
    "distill.reader.epoch",
    "epoch start on the student side: delay or kill (student dies between "
    "epochs; the teacher fleet must shed its load cleanly)",
)


class _FixedDiscovery:
    def __init__(self, endpoints: Sequence[str]) -> None:
        self._endpoints = list(endpoints)

    def __call__(self) -> List[str]:
        return list(self._endpoints)

    def stop(self) -> None:
        pass


class _DynamicDiscovery:
    """Lazily connects a DiscoveryClient; safe to call from the manage loop."""

    def __init__(
        self,
        store_endpoint: str,
        job_id: str,
        service_name: str,
        max_teachers: int,
    ) -> None:
        self._args = (store_endpoint, job_id, service_name, max_teachers)
        self._client = None
        self._stopped = False
        self._lock = threading.Lock()

    def __call__(self) -> List[str]:
        with self._lock:
            if self._stopped:
                return []
            client = self._client
        if client is None:
            # dial OUTSIDE the lock with a double-checked publish (the
            # PR-9 warm/aot discipline): the first call connects to the
            # store, which can take seconds against a sick control
            # plane, and stop() must never wait behind it
            from edl_tpu.distill.discovery import DiscoveryClient

            store, job, service, cap = self._args
            client_id = "%s-%d-%d" % (
                socket.gethostname(), os.getpid(),
                int(time.time() * 1e6) % 10**6,
            )
            fresh = DiscoveryClient(
                store, job, service, client_id, max_teachers=cap
            )
            with self._lock:
                if self._client is None and not self._stopped:
                    self._client = fresh
                    extra = None
                else:
                    extra = fresh  # lost the race, or stopping
            if extra is not None:
                extra.stop()
        with self._lock:
            if self._client is None:
                return []  # stopped mid-dial
            _, servers = self._client.get_servers()
            return servers

    def report_sick(self, endpoint: str) -> None:
        """Breaker-open hook: tell the balancer this teacher is sick so
        *other* readers route around it too (lease-free ejection)."""
        with self._lock:
            client = self._client
        if client is not None:
            client.report_sick(endpoint)

    def clear_sick(self, endpoint: str) -> None:
        with self._lock:
            client = self._client
        if client is not None:
            client.clear_sick(endpoint)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._client is not None:
                self._client.stop()
                self._client = None


class DistillReader:
    def __init__(
        self,
        feeds: Sequence[str],
        fetchs: Optional[Sequence[str]] = None,
        teacher_batch_size: int = 128,
        require_num: int = 3,
        retry: int = 3,
        rpc_timeout: float = 30.0,
        copy_batches: bool = True,
        slo_ms: Optional[float] = None,
    ) -> None:
        """``slo_ms`` stamps a per-request deadline (wire field ``dl``)
        on every predict so teachers can shed work this reader would time
        out on anyway; None defers to ``EDL_SERVE_SLO_MS`` (0 = no
        deadline, the default — a training pipeline usually prefers slow
        answers over re-queues).

        ``copy_batches=False`` skips the defensive per-chunk memcpy in
        batch mode. The yielded arrays are then ALIASED, not copied, so
        the opt-in is safe only when (a) the generator never writes to a
        yielded array's memory after yielding it — fresh slices of a
        buffer that gets refilled in place also violate this — and (b)
        the consumer treats the fields it gets back as read-only (they
        view the generator's data). Steady-state read-only datasets (the
        common case: yield slices of one persistent array) qualify."""
        self._feeds = list(feeds)
        self._fetchs = list(fetchs) if fetchs is not None else None
        self._tbs = teacher_batch_size
        self._require_num = require_num
        self._retry = retry
        self._rpc_timeout = rpc_timeout
        self._copy_batches = copy_batches
        self._slo_ms = slo_ms
        self._discovery = None
        self._generator: Optional[Callable] = None
        self._mode: Optional[str] = None
        self._pipeline: Optional[DistillPipeline] = None
        self._maybe_env_teacher()

    # -- teacher configuration --------------------------------------------

    def _maybe_env_teacher(self) -> None:
        store = os.environ.get("EDL_DISTILL_STORE")
        service = os.environ.get("EDL_DISTILL_SERVICE_NAME")
        if store and service:
            self.set_dynamic_teacher(
                store,
                os.environ.get("EDL_DISTILL_JOB_ID", "distill"),
                service,
                int(os.environ.get("EDL_DISTILL_MAX_TEACHER", "0")),
            )

    def set_fixed_teacher(self, *endpoints: str) -> "DistillReader":
        self._discovery = _FixedDiscovery(endpoints)
        return self

    def set_dynamic_teacher(
        self,
        store_endpoint: str,
        job_id: str = "distill",
        service_name: str = "teacher",
        max_teachers: int = 0,
    ) -> "DistillReader":
        self._discovery = _DynamicDiscovery(
            store_endpoint, job_id, service_name, max_teachers
        )
        return self

    # -- generator configuration ------------------------------------------

    def set_sample_generator(self, gen: Callable) -> "DistillReader":
        self._generator, self._mode = gen, "sample"
        return self

    def set_sample_list_generator(self, gen: Callable) -> "DistillReader":
        self._generator, self._mode = gen, "sample_list"
        return self

    def set_batch_generator(self, gen: Callable) -> "DistillReader":
        self._generator, self._mode = gen, "batch"
        return self

    # -- iteration ---------------------------------------------------------

    def _ensure_pipeline(self) -> DistillPipeline:
        if self._pipeline is None:
            if self._generator is None:
                raise ValueError("no generator set; call set_*_generator first")
            if self._discovery is None:
                raise ValueError(
                    "no teachers: call set_fixed_teacher/set_dynamic_teacher "
                    "or set EDL_DISTILL_STORE + EDL_DISTILL_SERVICE_NAME"
                )
            self._pipeline = DistillPipeline(
                self._generator,
                self._mode,
                self._feeds,
                self._fetchs,
                self._discovery,
                teacher_batch_size=self._tbs,
                require_num=self._require_num,
                retry=self._retry,
                rpc_timeout=self._rpc_timeout,
                copy_batches=self._copy_batches,
                slo_ms=self._slo_ms,
            )
        return self._pipeline

    def __call__(self):
        if _FP_EPOCH.armed:
            _FP_EPOCH.fire()
        return self._accounted_epoch(self._ensure_pipeline().epoch())

    @staticmethod
    def _accounted_epoch(epoch_iter):
        """Attribute time blocked on the teacher fleet to ``data_wait``
        in the goodput ledger — but ONLY when nobody else is driving the
        ledger (state ``None``, i.e. a standalone student script).
        Inside ``ElasticTrainer`` the reader is drained by the prefetch
        feeder thread while the main thread owns the ledger's
        train/data_wait flap; two threads writing one state machine
        would mislabel train time as data_wait, so the embedded case
        defers entirely to the trainer's own accounting (which already
        charges blocked ``next()`` time to data_wait)."""
        from edl_tpu.obs import events as obs_events
        from edl_tpu.obs import goodput as obs_goodput

        led = obs_goodput.ledger()
        n = 0
        while True:
            if led.state() is None:
                with led.phase("data_wait", cause="distill"):
                    try:
                        item = next(epoch_iter)
                    except StopIteration:
                        break
            else:
                try:
                    item = next(epoch_iter)
                except StopIteration:
                    break
            n += 1
            yield item
        obs_events.record("distill_epoch_end", batches=n)

    def stop(self) -> None:
        if self._pipeline is not None:
            self._pipeline.stop()
            self._pipeline = None
        if self._discovery is not None:
            self._discovery.stop()
