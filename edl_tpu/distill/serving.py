"""Teacher inference serving: a JAX model behind the wire protocol.

Replaces the reference's dependency on Paddle Serving
(python/edl/distill/distill_worker.py:23, 228-291 ``PaddlePredictServer``)
with an in-tree server speaking the same framed-msgpack protocol as every
other edl_tpu service.

TPU-first design points (not in the reference):

- **bucketed batch padding**: XLA compiles one program per input shape, so
  a teacher fed raw student batches would recompile on every ragged final
  batch. The backend pads the batch dim up to a power-of-two bucket,
  runs the jitted apply, and slices the pad back off — compile count is
  O(log max_batch), steady-state is always a cache hit.
- **bf16 on the MXU**: the model computes in bf16 (model-level choice);
  predictions return as fp32 numpy for the student pipeline.

Request:  ``{"i": n, "m": "predict", "feeds": {name: ndarray}}``
Response: ``{"i": n, "ok": true, "fetchs": {name: ndarray}}``

Serving resilience (DESIGN.md "Serving resilience plane"): the server
runs a deadline-aware admission test before touching the backend.
Requests may stamp ``dl`` (remaining deadline budget, milliseconds,
relative so clocks need not agree); past the bounded admission window
(``EDL_SERVE_QUEUE``) or when the estimated wait already blows the
deadline (or ``EDL_SERVE_SLO_MS`` when no ``dl`` came), the request is
shed with an explicit :class:`EdlOverloadError` — early, before any
decode/dispatch burns compute. Work whose deadline expired while queued
for the device is dropped at dispatch for the same reason. Every
response (success or shed) advertises ``qd`` (queue depth) and ``ew``
(estimated wait, ms) so clients can weigh their balancing by real
backlog instead of connection counts.
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.rpc.ndarray import decode_tree, encode_tree_zc
from edl_tpu.rpc.wire import (
    TC_FIELD,
    pack_frame,
    pack_frame_buffers,
    read_frame_blocking,
    send_buffers,
    server_span,
)

_TC = obs_trace.PROPAGATION
from edl_tpu.utils.exceptions import (
    EdlOverloadError,
    deserialize_exception,
    serialize_exception,
)
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.timeline import make_timeline

logger = get_logger("distill.serving")

_FP_SERVE = _fault_point(
    "distill.serving.predict",
    "teacher-side predict: delay (overloaded teacher), drop (conn reset "
    "mid-request), or kill (the teacher process dies)",
)

_M_SERVE_REQUESTS = obs_metrics.counter(
    "edl_distill_serve_requests_total", "predict RPCs served by this teacher"
)
_M_SERVE_ERRORS = obs_metrics.counter(
    "edl_distill_serve_errors_total", "predict RPCs that raised"
)
_M_SERVE_SECONDS = obs_metrics.histogram(
    "edl_distill_serve_predict_seconds",
    "teacher-side predict latency (dispatch+fetch, device time included)",
)
_M_SHED = obs_metrics.counter(
    "edl_distill_shed_total",
    "predict requests shed by admission control, by cause and teacher port",
)
# labeled (not callback-bound) so several in-process teachers each get
# their own series — edl-top's SERVE panel keys on the port label
_G_QDEPTH = obs_metrics.gauge(
    "edl_distill_serve_queue_depth",
    "admitted-but-unfinished predicts, by teacher port",
)
_G_EST_WAIT = obs_metrics.gauge(
    "edl_distill_serve_est_wait_ms",
    "estimated queue wait advertised in responses, by teacher port",
)

Feeds = Dict[str, np.ndarray]


def _env_int(raw: Optional[str], default: int) -> int:
    try:
        return int(raw or default)
    except ValueError:
        return default


def _env_float(raw: Optional[str], default: float) -> float:
    try:
        return float(raw or default)
    except ValueError:
        return default


class _Admission:
    """The deadline-aware admission test (Tail-at-Scale load shedding).

    Tracks admitted-but-unfinished requests and an EWMA of service time;
    the estimated wait for a newcomer is ``depth * ewma`` (the backend
    serializes on the device lock, so backlog is roughly linear).
    ``try_admit`` sheds when the bounded queue is full or when the
    newcomer's predicted completion already misses its deadline —
    shedding EARLY is the whole point: a request doomed to time out must
    not occupy queue slots other requests could meet their SLO in."""

    def __init__(self, limit: int, slo_ms: float) -> None:
        self.limit = limit
        self.slo_ms = slo_ms
        self._lock = threading.Lock()
        self._inflight = 0
        self._ewma_s = 0.0

    def depth(self) -> int:
        with self._lock:
            return self._inflight

    def est_wait_ms(self) -> float:
        with self._lock:
            return self._inflight * self._ewma_s * 1000.0

    def snapshot(self) -> Tuple[int, float]:
        with self._lock:
            return self._inflight, self._inflight * self._ewma_s * 1000.0

    def try_admit(
        self, deadline_at: Optional[float], now: float
    ) -> Optional[Tuple[str, int, float]]:
        """Admit (returns None, depth incremented) or shed (returns
        ``(cause, qdepth, est_wait_ms)``, depth untouched)."""
        with self._lock:
            qd = self._inflight
            ew_ms = qd * self._ewma_s * 1000.0
            if self.limit > 0 and qd >= self.limit:
                return ("queue", qd, ew_ms)
            if deadline_at is not None:
                # predicted completion = queue ahead + own service time
                predicted = now + (qd + 1) * self._ewma_s
                if predicted > deadline_at:
                    return ("deadline", qd, ew_ms)
            self._inflight += 1
            return None

    def done(self, service_s: Optional[float]) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if service_s is not None:
                self._ewma_s = (
                    service_s if self._ewma_s == 0.0
                    else 0.8 * self._ewma_s + 0.2 * service_s
                )


def _grow_socket_buffers(sock: socket.socket, size: int = 4 << 20) -> None:
    """Teacher batches are multi-MB; default 64-256KB socket buffers force
    many extra syscall round-trips per frame. The kernel clamps to its
    rmem_max/wmem_max, so this is best-effort."""
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, size)
        except OSError:
            pass


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return min(b, max(max_batch, n))


class JaxPredictBackend:
    """Wrap a jitted ``apply(feeds) -> fetchs`` with batch-bucket padding.

    Split into a non-blocking ``dispatch`` (jax's async dispatch enqueues
    the device work and returns device arrays immediately) and a blocking
    ``fetch`` (device→numpy), so callers can overlap one request's device
    compute with another's host-side marshaling — the chip never idles
    waiting for socket/encode work (``PredictServer`` locks only the
    dispatch)."""

    def __init__(
        self,
        apply_fn: Callable[[Feeds], Dict[str, np.ndarray]],
        max_batch: int = 1024,
    ) -> None:
        import jax

        self._apply = jax.jit(apply_fn)
        self._max_batch = max_batch

    def dispatch(self, feeds: Feeds):
        """Enqueue the padded device call; returns an opaque handle."""
        n = next(iter(feeds.values())).shape[0] if feeds else 0
        if n == 0:
            return (0, {})
        bucket = _bucket(n, self._max_batch)
        if bucket != n:
            feeds = {
                k: np.concatenate(
                    [v, np.repeat(v[-1:], bucket - n, axis=0)], axis=0
                )
                for k, v in feeds.items()
            }
        return (n, self._apply(feeds))

    def fetch(self, handle) -> Dict[str, np.ndarray]:
        """Block until the dispatched work is done; numpy results."""
        import jax

        n, out = handle
        if n == 0:
            return {}
        out = jax.tree.map(lambda x: np.asarray(x, np.float32), out)
        return {k: v[:n] for k, v in out.items()}

    def __call__(self, feeds: Feeds) -> Dict[str, np.ndarray]:
        return self.fetch(self.dispatch(feeds))


class NopPredictBackend:
    """Returns no predictions — the reference's fake teacher for pipeline
    tests (``_TestNopPaddlePredictServer``, distill_worker.py:306-315)."""

    def __call__(self, feeds: Feeds) -> Dict[str, np.ndarray]:
        return {}


class EchoPredictBackend:
    """Deterministic fake teacher: prediction = per-sample feature sum.

    Lets tests assert sample↔prediction pairing survives the concurrent
    pipeline's reordering (stronger than the reference's NOP fake)."""

    def __call__(self, feeds: Feeds) -> Dict[str, np.ndarray]:
        out = {}
        for name, arr in feeds.items():
            flat = np.asarray(arr).reshape(arr.shape[0], -1)
            # float64 ACCUMULATOR without materializing a float64 copy of
            # the batch: this backend exists to isolate pipeline overhead,
            # so its own cost must stay negligible at large batches
            out["echo_" + name] = flat.sum(
                axis=1, dtype=np.float64
            ).astype(np.float32)
        return out


class CoalescingBackend:
    """Cross-request megabatching: concat concurrent predicts into one
    device call.

    The TPU teacher's throughput comes from big batches on the MXU, but
    each student connection sends ``teacher_batch_size`` rows at a time
    (reference distill_worker.py:487 slices student batches small). With
    many student workers attached, per-request inference wastes the chip.
    This wrapper makes the batching dynamic and server-side: callers
    enqueue and block; a dedicated cohort-runner thread (lazily started)
    waits up to ``max_wait_ms`` for requests to accumulate (ending early
    at ``max_rows``), concatenates feeds along axis 0, runs the wrapped
    backend ONCE, and splits the fetches back per caller, FIFO — no
    caller waits more than ``max_wait_ms`` plus the device calls queued
    ahead of it. Requests whose feed keys differ run in separate
    cohorts. Thread-safe by design (``thread_safe = True`` tells
    ``PredictServer`` to skip its serializing lock — otherwise callers
    could never coalesce).

    Composes with ``JaxPredictBackend``'s bucket padding: the cohort's
    total row count is what gets padded, so N small student requests hit
    one big compiled bucket instead of N small ones.
    """

    thread_safe = True

    def __init__(
        self,
        backend: Callable[[Feeds], Dict[str, np.ndarray]],
        max_rows: int = 1024,
        max_wait_ms: float = 2.0,
    ) -> None:
        self._backend = backend
        self._max_rows = max_rows
        self._max_wait = max_wait_ms / 1000.0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self.batches_run = 0  # observability: device calls issued
        self.requests_served = 0
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_distill_coalesce_batches_count",
             "device calls issued by the coalescer", lambda: self.batches_run),
            ("edl_distill_coalesce_requests_count",
             "caller requests coalesced", lambda: self.requests_served),
        ))

    def close(self) -> None:
        """Stop the cohort-runner thread (queued requests still complete).
        Without this the daemon thread pins the backend — and its device
        buffers — for the process lifetime. ``PredictServer.stop`` calls
        it automatically."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        self._obs_gauges.release()  # free this instance from the registry

    def __call__(self, feeds: Feeds) -> Dict[str, np.ndarray]:
        rows = next(iter(feeds.values())).shape[0] if feeds else 0
        item = {
            "feeds": feeds,
            "rows": rows,
            "keys": tuple(sorted(feeds)),
            "event": threading.Event(),
            "result": None,
            "error": None,
        }
        with self._cond:
            if self._closed:
                raise RuntimeError("CoalescingBackend is closed")
            # a dedicated cohort-runner (lazily started) keeps caller
            # latency bounded: a caller-as-leader design starves the
            # leader whenever new requests keep arriving mid-cohort
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run_loop, name="edl-coalesce", daemon=True
                )
                self._worker.start()
            self._queue.append(item)
            self._cond.notify_all()
        item["event"].wait()
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    def _run_loop(self) -> None:
        # one cohort's device work may stay IN FLIGHT (dispatched, not
        # fetched) while the runner collects and dispatches the next —
        # only when the wrapped backend exposes the dispatch/fetch split
        # and only while more work is queued (an in-flight cohort is
        # always resolved before the runner blocks, so no caller can be
        # left waiting on an idle pipeline)
        pending = None  # (cohort, handle) dispatched but not delivered
        while True:
            with self._cond:
                while not self._queue:
                    if pending is not None:
                        break
                    if self._closed:
                        return
                    self._cond.wait()
                if not self._queue:
                    # drained: resolve the in-flight cohort and re-wait
                    cohort = None
                else:
                    if pending is None:
                        # no cohort in flight: wait out the coalescing
                        # window. With one IN FLIGHT, take what is queued
                        # RIGHT NOW instead — waiting here would delay the
                        # pending cohort's delivery past the documented
                        # max_wait latency bound (requests kept arriving
                        # during the in-flight dispatch, so there is
                        # already a cohort's worth of accumulation).
                        deadline = time.time() + self._max_wait
                        while True:
                            rows = sum(i["rows"] for i in self._queue)
                            left = deadline - time.time()
                            if rows >= self._max_rows or left <= 0:
                                break
                            self._cond.wait(left)
                    # one cohort = longest same-keys prefix within max_rows
                    # (order preserved: a later mismatched request waits
                    # its turn)
                    cohort = []
                    taken_rows = 0
                    for it in self._queue:
                        if cohort and it["keys"] != cohort[0]["keys"]:
                            break
                        if cohort and taken_rows + it["rows"] > self._max_rows:
                            break
                        cohort.append(it)
                        taken_rows += it["rows"]
                    del self._queue[: len(cohort)]
            if cohort:
                handle = self._dispatch_cohort(cohort)
            if pending is not None:
                self._deliver(*pending)
                pending = None
            if cohort:
                if handle is not None and self._queue:
                    pending = (cohort, handle)  # overlap with the next
                else:
                    self._deliver(cohort, handle)

    def _dispatch_cohort(self, cohort: List[dict]):
        """Enqueue the cohort's device work; returns a handle, or None if
        the work already failed/completed synchronously (result/error set
        on the items; _deliver(cohort, None) finishes up)."""
        try:
            if len(cohort) == 1:
                merged = cohort[0]["feeds"]
            else:
                keys = cohort[0]["feeds"].keys()
                merged = {
                    k: np.concatenate([it["feeds"][k] for it in cohort])
                    for k in keys
                }
            dispatch = getattr(self._backend, "dispatch", None)
            if dispatch is not None:
                return dispatch(merged)
            self._split_results(cohort, self._backend(merged))
            return None
        except Exception as exc:  # noqa: BLE001 — deliver to every waiter
            for it in cohort:
                it["error"] = exc
            return None

    def _deliver(self, cohort: List[dict], handle) -> None:
        try:
            if handle is not None:
                self._split_results(cohort, self._backend.fetch(handle))
        except Exception as exc:  # noqa: BLE001 — deliver to every waiter
            for it in cohort:
                it["error"] = exc
        finally:
            for it in cohort:
                it["event"].set()

    def _split_results(
        self, cohort: List[dict], fetchs: Dict[str, np.ndarray]
    ) -> None:
        self.batches_run += 1
        self.requests_served += len(cohort)
        off = 0
        for it in cohort:
            n = it["rows"]
            it["result"] = {k: v[off : off + n] for k, v in fetchs.items()}
            off += n


class PredictServer:
    """Thread-per-connection predict server.

    Connection handling is not the bottleneck (inference is); a blocking
    thread design keeps the hot path simple. ``backend`` is any callable
    ``feeds -> fetchs``; calls are serialized under a lock because the
    device is the contended resource — unless the backend declares
    ``thread_safe = True`` (``CoalescingBackend``), in which case
    concurrent connection threads are let through so they can coalesce.
    """

    def __init__(
        self,
        backend: Callable[[Feeds], Dict[str, np.ndarray]],
        host: str = "0.0.0.0",
        port: int = 0,
        queue_limit: Optional[int] = None,
        slo_ms: Optional[float] = None,
    ) -> None:
        self._backend = backend
        # admission plane: queue_limit bounds admitted-but-unfinished
        # requests (0 disables the bound); slo_ms is the implied deadline
        # for requests that stamp no "dl" (0 disables the implied test)
        self._admission = _Admission(
            _env_int(os.environ.get("EDL_SERVE_QUEUE", "64"), 64)
            if queue_limit is None else queue_limit,
            _env_float(os.environ.get("EDL_SERVE_SLO_MS", "0"), 0.0)
            if slo_ms is None else slo_ms,
        )
        self._backend_lock = (
            contextlib.nullcontext()
            if getattr(backend, "thread_safe", False)
            else threading.Lock()
        )
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._host = host
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    @property
    def endpoint(self) -> str:
        """Routable address for registration: wildcard binds advertise this
        host's real IP so students on other hosts can connect."""
        from edl_tpu.utils.net import get_host_ip

        host = self._host if self._host not in ("", "0.0.0.0") else get_host_ip()
        return "%s:%d" % (host, self.port)

    def start(self) -> "PredictServer":
        # teacher processes are long-lived job members: mount /metrics +
        # /healthz when EDL_OBS_PORT opts them in
        self._health_fn = lambda: {
            "predict_port": self.port,
            "requests": _M_SERVE_REQUESTS.value(),
        }
        self._obs = obs_http.start_from_env(
            "teacher", health_fn=self._health_fn
        )
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="edl-predict-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        health_fn = getattr(self, "_health_fn", None)
        if health_fn is not None:
            obs_http.release_health("teacher", health_fn)
        close_backend = getattr(self._backend, "close", None)
        if callable(close_backend):
            close_backend()
        # shutdown before close: a thread blocked in accept() pins the
        # kernel file description, so close() alone leaves the socket in
        # LISTEN and the port unbindable until that accept returns.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # close live connections too: lingering ESTABLISHED sockets would
        # otherwise hold the port and block a same-port teacher restart
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    @staticmethod
    def _check_deadline(deadline_at: Optional[float]) -> None:
        """Drop expired work at dispatch: the client has given up by
        now, so running the backend would burn device time nobody reads."""
        if deadline_at is not None and time.monotonic() > deadline_at:
            raise EdlOverloadError("deadline expired while queued")

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(sock, addr), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, sock: socket.socket, addr) -> None:
        # legacy stderr lines only (feed_tracer=False): the predict
        # interval is span-recorded directly below, always-on
        timeline = make_timeline(feed_tracer=False)
        tracer = obs_trace.get_tracer()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _grow_socket_buffers(sock)
        with self._conns_lock:
            self._conns.add(sock)
        try:
            while not self._stop.is_set():
                req = read_frame_blocking(sock)
                rid = req.get("i", 0)
                method = req.get("m")
                if method == "ping":
                    sock.sendall(pack_frame({"i": rid, "ok": True}))
                    continue
                if _FP_SERVE.armed:
                    # port ctx lets a chaos rule target ONE teacher of an
                    # in-process fleet (match={"port": ...})
                    _FP_SERVE.fire(method=str(method), port=self.port)
                if method != "predict":
                    sock.sendall(
                        pack_frame(
                            {"i": rid, "ok": False,
                             "err": {"etype": "EdlInternalError",
                                     "detail": "unknown method %r" % method}}
                        )
                    )
                    continue
                # -- admission test (shed EARLY: before any decode) --------
                now = time.monotonic()
                dl_ms = req.get("dl")  # remaining deadline budget, ms
                deadline_at = None
                if isinstance(dl_ms, (int, float)) and dl_ms > 0:
                    deadline_at = now + float(dl_ms) / 1000.0
                elif self._admission.slo_ms > 0:
                    deadline_at = now + self._admission.slo_ms / 1000.0
                shed = self._admission.try_admit(deadline_at, now)
                if shed is not None:
                    cause, qd, ew = shed
                    _M_SHED.inc(cause=cause, port=str(self.port))
                    _G_QDEPTH.set(qd, port=str(self.port))
                    _G_EST_WAIT.set(ew, port=str(self.port))
                    exc = EdlOverloadError(
                        "shed (%s): queue %d, est wait %.0f ms"
                        % (cause, qd, ew),
                        qdepth=qd, est_wait_ms=ew,
                    )
                    sock.sendall(pack_frame({
                        "i": rid, "ok": False, "qd": qd,
                        "ew": round(ew, 3),
                        "err": serialize_exception(exc),
                    }))
                    continue
                service_s = None
                try:
                    # arrays arrive pre-resolved from the EDL2 frame
                    feeds = decode_tree(req.get("feeds", {}))
                    t0 = time.monotonic()
                    # per-method server latency + caller-linked span when
                    # the student stamped a "tc" trace context
                    with server_span(
                        "predict", req.get(TC_FIELD), server="distill"
                    ):
                        dispatch = getattr(self._backend, "dispatch", None)
                        if dispatch is not None:
                            # lock only the enqueue: connection B's device
                            # work overlaps connection A's result fetch +
                            # encode + socket send (the 9.4%-above-floor
                            # gap VERDICT r4 measured was exactly this
                            # host time serialized against the chip)
                            with self._backend_lock:
                                self._check_deadline(deadline_at)
                                timeline.reset()
                                handle = dispatch(feeds)
                            fetchs = self._backend.fetch(handle)
                            timeline.record("predict")
                        else:
                            with self._backend_lock:
                                self._check_deadline(deadline_at)
                                timeline.reset()
                                fetchs = self._backend(feeds)
                                timeline.record("predict")
                    dt = time.monotonic() - t0
                    service_s = dt
                    _M_SERVE_REQUESTS.inc()
                    _M_SERVE_SECONDS.observe(dt)
                    tracer.record("teacher_predict", t0, dt)
                    qd, ew = self._admission.snapshot()
                    payload, atts = encode_tree_zc(
                        {"i": rid, "ok": True, "fetchs": fetchs,
                         "qd": qd - 1, "ew": round(ew, 3)}
                    )
                    buffers = pack_frame_buffers(payload, atts)
                except EdlOverloadError as exc:
                    # deadline expired while queued for the device: the
                    # backend never saw it — a shed, not a server error
                    _M_SHED.inc(cause="expired", port=str(self.port))
                    qd, ew = self._admission.snapshot()
                    buffers = [
                        pack_frame(
                            {"i": rid, "ok": False, "qd": qd - 1,
                             "ew": round(ew, 3),
                             "err": serialize_exception(exc)}
                        )
                    ]
                except Exception as exc:  # noqa: BLE001 — report to client
                    logger.exception("predict failed")
                    _M_SERVE_ERRORS.inc()
                    buffers = [
                        pack_frame(
                            {"i": rid, "ok": False,
                             "err": serialize_exception(exc)}
                        )
                    ]
                finally:
                    self._admission.done(service_s)
                    qd, ew = self._admission.snapshot()
                    _G_QDEPTH.set(qd, port=str(self.port))
                    _G_EST_WAIT.set(ew, port=str(self.port))
                # send outside the try: a mid-send socket error must hit the
                # outer handler and close the (now desynced) connection, not
                # append an error frame into a half-sent EDL2 frame
                send_buffers(sock, buffers)
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass


class PredictClient:
    """Blocking predict client; one TCP connection, sequential requests.

    Retries are the *pipeline's* job (predict_loop re-queues failed tasks,
    matching reference distill_worker.py:437-446); the client only raises.
    """

    def __init__(self, endpoint: str, timeout: float = 30.0) -> None:
        self.endpoint = endpoint
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _grow_socket_buffers(self._sock)
        self._next_id = 0
        # the teacher's advertised backlog, refreshed by every response
        # (success or shed) — queue-aware balancing reads these
        self.last_qdepth = 0
        self.last_wait_ms = 0.0

    def predict(
        self, feeds: Feeds, deadline_s: Optional[float] = None
    ) -> Dict[str, np.ndarray]:
        """One predict RPC. ``deadline_s`` (remaining budget, seconds) is
        stamped as the relative ``dl`` wire field so the teacher can shed
        at admission / drop expired work; a shed surfaces as
        :class:`EdlOverloadError` (alive server saying back off), every
        other failure stays :class:`ConnectionError` (dead/unknown)."""
        self._next_id += 1
        rid = self._next_id
        req = {"i": rid, "m": "predict", "feeds": feeds}
        if deadline_s is not None and deadline_s > 0:
            req["dl"] = round(deadline_s * 1000.0, 1)
        # trace propagation: one attr load disarmed (wire discipline)
        if _TC.armed:
            tc = obs_trace.inject()
            if tc is not None:
                req[TC_FIELD] = tc
        payload, atts = encode_tree_zc(req)
        send_buffers(self._sock, pack_frame_buffers(payload, atts))
        resp = read_frame_blocking(self._sock)
        qd = resp.get("qd")
        if isinstance(qd, (int, float)):
            self.last_qdepth = int(qd)
        ew = resp.get("ew")
        if isinstance(ew, (int, float)):
            self.last_wait_ms = float(ew)
        if not resp.get("ok"):
            err = resp.get("err", {})
            exc = deserialize_exception(err)
            if isinstance(exc, EdlOverloadError):
                exc.qdepth = self.last_qdepth
                exc.est_wait_ms = self.last_wait_ms
                raise exc
            raise ConnectionError(
                "predict failed at %s: %s" % (self.endpoint, err.get("detail"))
            )
        return decode_tree(resp.get("fetchs", {}))

    def ping(self) -> bool:
        self._next_id += 1
        self._sock.sendall(pack_frame({"i": self._next_id, "m": "ping"}))
        return bool(read_frame_blocking(self._sock).get("ok"))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
