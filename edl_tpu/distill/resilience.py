"""Client-side serving resilience: retry budgets, hedged predicts,
per-teacher circuit breakers (Dean & Barroso, *The Tail at Scale*).

These primitives are shared by the two client paths of the distill
plane — the training pipeline (:mod:`edl_tpu.distill.worker`, which may
never drop a batch and so converts every failure into a bounded retry or
a re-queue) and the serving-style load driver
(:mod:`edl_tpu.distill.slo`, which records an explicit shed/timeout
verdict instead). Three ideas, one invariant each:

- :class:`FractionBudget` — secondary work (retries, hedges) is earned
  by primary work at a fixed fraction, never granted per-call. A fleet
  of workers cannot retry-storm a sick teacher *by construction*: with
  ratio ``r`` and burst ``b``, secondaries ≤ ``r × primaries + b``.
- :class:`HedgePolicy` — a backup RPC to a *different* teacher is
  launched only after the p95-tracked hedge delay (slower than 95% of
  recent primaries ⇒ probably stuck), metered and budget-capped so
  hedging adds tail insurance, not baseline load.
- :class:`BreakerBoard` — per-teacher circuit breakers: consecutive
  failures/overloads trip the breaker open, a half-open probe is let
  through after the cooldown, one success closes it. Open breakers veto
  the endpoint in :class:`~edl_tpu.distill.worker.ServerPool` and are
  reported to discovery so :class:`~edl_tpu.distill.discovery.
  BalanceTable` routes *other* students around the sick teacher without
  waiting for its lease to expire.

Env knobs (all read at construction):

    EDL_RETRY_BUDGET      retry tokens earned per primary (default 0.25)
    EDL_HEDGE_BUDGET      hedge tokens earned per primary (default 0.10;
                          0 disables hedging)
    EDL_HEDGE_MIN_MS      hedge-delay floor, ms (default 20)
    EDL_BREAKER_FAILURES  consecutive failures that trip a breaker
                          (default 5)
    EDL_BREAKER_OPEN_S    open duration before the half-open probe
                          (default 5)
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.log import get_logger

logger = get_logger("distill.resilience")

_M_RETRY_DENIED = obs_metrics.counter(
    "edl_distill_retry_denied_total",
    "retries refused because the retry budget was empty",
)
_M_HEDGES = obs_metrics.counter(
    "edl_distill_hedges_total", "backup predicts launched by the hedger"
)
_M_HEDGE_WINS = obs_metrics.counter(
    "edl_distill_hedge_wins_total",
    "hedged predicts where the backup answered first",
)
_M_BREAKER_TRANSITIONS = obs_metrics.counter(
    "edl_distill_breaker_transitions_total",
    "circuit breaker state transitions, by destination state",
)
_G_BREAKER_OPEN = obs_metrics.gauge(
    "edl_distill_breaker_open",
    "1 while a teacher's circuit breaker is open/half-open, by teacher",
)


def _env_float(raw: Optional[str], default: float) -> float:
    try:
        return float(raw or default)
    except ValueError:
        return default


def _env_int(raw: Optional[str], default: int) -> int:
    try:
        return int(raw or default)
    except ValueError:
        return default


class FractionBudget:
    """Token bucket where primaries earn secondary-work tokens.

    Each :meth:`note_primary` deposits ``ratio`` tokens (capped at
    ``burst``); each secondary must :meth:`try_spend` a whole token.
    The cap is what makes storms impossible: a burst of failures can
    spend at most ``burst`` tokens ahead of what primaries earned."""

    def __init__(self, ratio: float, burst: float = 10.0) -> None:
        self.ratio = max(0.0, ratio)
        self._burst = max(1.0, burst)
        self._lock = threading.Lock()
        # start with the burst: a cold pipeline's first failures may
        # retry (connection establishment is the flakiest moment), the
        # steady state is still ratio-bound
        self._tokens = self._burst if self.ratio > 0 else 0.0
        self.primaries = 0
        self.spent = 0

    def note_primary(self) -> None:
        with self._lock:
            self.primaries += 1
            self._tokens = min(self._burst, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            return False


class RetryBudget(FractionBudget):
    """The pipeline-wide retry budget (``EDL_RETRY_BUDGET``)."""

    def __init__(
        self, ratio: Optional[float] = None, burst: float = 10.0
    ) -> None:
        super().__init__(
            _env_float(os.environ.get("EDL_RETRY_BUDGET", "0.25"), 0.25)
            if ratio is None else ratio,
            burst,
        )

    def try_spend(self) -> bool:
        ok = super().try_spend()
        if not ok:
            _M_RETRY_DENIED.inc()
        return ok


# -- hedging -------------------------------------------------------------------


class HedgePolicy:
    """p95-tracked hedge delay + budget-capped hedge permission.

    ``delay_s()`` is None until enough primary latencies accumulated —
    a cold pipeline must not hedge on a guess. The budget is the same
    fraction-of-primaries construction as retries, so
    ``edl_distill_hedges_total ≤ ratio × primaries + burst`` always."""

    _MIN_SAMPLES = 8
    _WINDOW = 256

    def __init__(
        self,
        budget_ratio: Optional[float] = None,
        min_delay_ms: Optional[float] = None,
        burst: float = 5.0,
    ) -> None:
        ratio = (
            _env_float(os.environ.get("EDL_HEDGE_BUDGET", "0.10"), 0.10)
            if budget_ratio is None else budget_ratio
        )
        self.budget = FractionBudget(ratio, burst)
        self._floor_s = (
            _env_float(os.environ.get("EDL_HEDGE_MIN_MS", "20"), 20.0)
            if min_delay_ms is None else min_delay_ms
        ) / 1000.0
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._i = 0
        self.hedges = 0
        self.wins = 0

    @property
    def enabled(self) -> bool:
        return self.budget.ratio > 0

    def note_primary(self) -> None:
        """Each primary request earns hedge budget at the ratio."""
        self.budget.note_primary()

    def note_latency(self, seconds: float) -> None:
        with self._lock:
            if len(self._lat) < self._WINDOW:
                self._lat.append(seconds)
            else:
                self._lat[self._i % self._WINDOW] = seconds
            self._i += 1

    def delay_s(self) -> Optional[float]:
        """The current hedge delay: p95 of recent primary latencies,
        floored; None while cold or disabled."""
        if not self.enabled:
            return None
        with self._lock:
            if len(self._lat) < self._MIN_SAMPLES:
                return None
            xs = sorted(self._lat)
        p95 = xs[min(len(xs) - 1, int(0.95 * len(xs)))]
        return max(p95, self._floor_s)

    def try_hedge(self) -> bool:
        if not self.enabled or not self.budget.try_spend():
            return False
        with self._lock:
            self.hedges += 1
        _M_HEDGES.inc()
        return True

    def note_win(self, backup_won: bool) -> None:
        if backup_won:
            with self._lock:
                self.wins += 1
            _M_HEDGE_WINS.inc()


def hedged_call(
    primary_fn: Callable[[], object],
    hedge_delay_s: Optional[float],
    backup_factory: Callable[[], Optional[Callable[[], object]]],
    policy: Optional[HedgePolicy] = None,
) -> Tuple[object, bool, bool]:
    """Run ``primary_fn``; if it is still running after ``hedge_delay_s``,
    ask ``backup_factory`` for a backup callable (it returns None when no
    second teacher is available) and race them — first *success* wins,
    the loser is ignored (the caller closes its transport, which unblocks
    the losing thread). Returns ``(result, backup_won,
    primary_abandoned)``; ``primary_abandoned`` means the primary was
    still in flight when the call returned, so its connection is desynced
    and must be discarded.

    Budget metering happens in the caller-supplied ``backup_factory``
    via ``policy.try_hedge()`` — the factory is only invoked after the
    delay actually elapsed, so hedges are only spent on real tail
    latencies."""
    results: "queue.Queue" = queue.Queue()

    def run(tag: str, fn: Callable[[], object]) -> None:
        try:
            results.put((tag, True, fn()))
        except BaseException as exc:  # noqa: BLE001 — raced to the caller
            results.put((tag, False, exc))

    threading.Thread(
        target=run, args=("primary", primary_fn),
        name="edl-hedge-primary", daemon=True,
    ).start()
    if hedge_delay_s is not None:
        try:
            tag, ok, val = results.get(timeout=hedge_delay_s)
            if ok:
                return val, False, False
            raise val
        except queue.Empty:
            pass
    else:
        tag, ok, val = results.get()
        if ok:
            return val, False, False
        raise val

    backup_fn = backup_factory()
    if backup_fn is None:
        tag, ok, val = results.get()  # no hedge possible: wait it out
        if ok:
            return val, False, False
        raise val
    threading.Thread(
        target=run, args=("backup", backup_fn),
        name="edl-hedge-backup", daemon=True,
    ).start()
    failures = 0
    while True:
        tag, ok, val = results.get()
        if ok:
            backup_won = tag == "backup"
            if policy is not None:
                policy.note_win(backup_won)
            return val, backup_won, backup_won
        failures += 1
        if failures >= 2:
            raise val


# -- circuit breakers ----------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _Breaker:
    __slots__ = ("state", "fails", "opened_at", "probe_inflight")

    def __init__(self) -> None:
        self.state = CLOSED
        self.fails = 0
        self.opened_at = 0.0
        self.probe_inflight = False


class BreakerBoard:
    """Per-teacher circuit breakers with half-open probing.

    State machine: CLOSED --(``failures`` consecutive failures or
    overloads)--> OPEN --(``open_s`` elapsed)--> HALF_OPEN --(one probe
    succeeds)--> CLOSED, or --(probe fails)--> OPEN again. ``admits()``
    is the pool's veto predicate: False while OPEN and while a half-open
    probe is already in flight, so exactly one request at a time tests a
    recovering teacher.

    Transitions are metered (``edl_distill_breaker_open{teacher}``,
    ``edl_distill_breaker_transitions_total{to}``), flight-recorded as
    ``breaker_open``/``breaker_close`` causal instants, and surfaced to
    the optional ``on_open``/``on_close`` callbacks (the pipeline wires
    these to discovery's sick-reporting so the balancer ejects the
    teacher fleet-wide)."""

    def __init__(
        self,
        failures: Optional[int] = None,
        open_s: Optional[float] = None,
        on_open: Optional[Callable[[str], None]] = None,
        on_close: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.failures = (
            _env_int(os.environ.get("EDL_BREAKER_FAILURES", "5"), 5)
            if failures is None else failures
        )
        self.open_s = (
            _env_float(os.environ.get("EDL_BREAKER_OPEN_S", "5"), 5.0)
            if open_s is None else open_s
        )
        self._lock = threading.Lock()
        self._breakers: Dict[str, _Breaker] = {}
        self._on_open = on_open
        self._on_close = on_close

    def _get(self, endpoint: str) -> _Breaker:
        b = self._breakers.get(endpoint)
        if b is None:
            b = self._breakers[endpoint] = _Breaker()
        return b

    def _transition(self, endpoint: str, b: _Breaker, to: str) -> None:
        b.state = to
        _M_BREAKER_TRANSITIONS.inc(to=to)
        _G_BREAKER_OPEN.set(0.0 if to == CLOSED else 1.0, teacher=endpoint)

    def admits(self, endpoint: str) -> bool:
        """Pure veto check — consumes nothing. Never-seen endpoints are
        admitted (breakers exist only once traffic flowed)."""
        now = time.monotonic()
        with self._lock:
            b = self._breakers.get(endpoint)
            if b is None or b.state == CLOSED:
                return True
            if b.state == OPEN:
                if now - b.opened_at < self.open_s:
                    return False
                self._transition(endpoint, b, HALF_OPEN)
                return not b.probe_inflight
            return not b.probe_inflight  # HALF_OPEN

    def starting(self, endpoint: str) -> None:
        """An attempt against ``endpoint`` begins; a HALF_OPEN breaker
        marks it as THE probe (no second request until it concludes)."""
        with self._lock:
            b = self._breakers.get(endpoint)
            if b is not None and b.state == HALF_OPEN:
                b.probe_inflight = True

    def record_success(self, endpoint: str) -> None:
        closed = False
        with self._lock:
            b = self._get(endpoint)
            b.fails = 0
            b.probe_inflight = False
            if b.state != CLOSED:
                self._transition(endpoint, b, CLOSED)
                closed = True
        if closed:
            obs_events.record("breaker_close", teacher=endpoint)
            logger.info("breaker closed for %s", endpoint)
            if self._on_close is not None:
                try:
                    self._on_close(endpoint)
                except Exception as exc:  # noqa: BLE001 — advisory hook
                    logger.warning("breaker on_close failed: %s", exc)

    def record_failure(self, endpoint: str, overload: bool = False) -> None:
        """A failed (or shed — ``overload=True``) attempt. Overloads
        count toward the trip threshold like failures: a teacher
        shedding everything it is offered is not serving this client."""
        opened = False
        with self._lock:
            b = self._get(endpoint)
            b.fails += 1
            b.probe_inflight = False
            if b.state == HALF_OPEN or (
                b.state == CLOSED and b.fails >= self.failures
            ):
                b.opened_at = time.monotonic()
                self._transition(endpoint, b, OPEN)
                opened = True
            elif b.state == OPEN:
                b.opened_at = time.monotonic()
        if opened:
            obs_events.record(
                "breaker_open", teacher=endpoint, overload=bool(overload)
            )
            logger.warning(
                "breaker OPEN for %s (%d consecutive %s)",
                endpoint, self.failures if b.fails >= self.failures else 1,
                "overloads/failures" if overload else "failures",
            )
            if self._on_open is not None:
                try:
                    self._on_open(endpoint)
                except Exception as exc:  # noqa: BLE001 — advisory hook
                    logger.warning("breaker on_open failed: %s", exc)

    def state(self, endpoint: str) -> str:
        with self._lock:
            b = self._breakers.get(endpoint)
            return b.state if b is not None else CLOSED

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return {e: b.state for e, b in self._breakers.items()}

    def forget(self, endpoint: str) -> None:
        """Drop state (and the gauge series) for a departed teacher."""
        with self._lock:
            self._breakers.pop(endpoint, None)
