"""Closed-loop serving driver with per-request SLO verdicts.

The training pipeline (:mod:`edl_tpu.distill.worker`) may never drop a
batch, so it converts every failure into a retry or a re-queue. A
serving workload is the opposite: every request gets exactly one
explicit **verdict** —

- ``ok``     answered within the SLO
- ``late``   answered, but past the SLO (an SLO miss, not a loss)
- ``shed``   the fleet refused it (:class:`EdlOverloadError`) — by
  design, the cheap outcome under overload
- ``error``  no teacher could answer it (connection failures after the
  budgeted retry)

so goodput-vs-shed accounting is exact and the chaos plane can assert
"zero requests lost without an explicit verdict" as an invariant rather
than a hope.

Arrival is **paced** (one request every ``1/qps`` seconds, issued by a
fixed worker pool): latency is measured from the request's *scheduled*
arrival, not from when a worker got around to sending it, so client-side
queueing counts against the SLO — the coordinated-omission-free
measurement. The driver reuses the worker pipeline's resilience kit
(:mod:`edl_tpu.distill.resilience`): per-teacher circuit breakers,
queue-depth-weighted endpoint choice from the ``qd`` advertisements,
p95-hedged backups, and fraction-of-primaries retry/hedge budgets.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from edl_tpu.distill.resilience import (
    BreakerBoard,
    HedgePolicy,
    RetryBudget,
    hedged_call,
)
from edl_tpu.distill.serving import PredictClient
from edl_tpu.utils.exceptions import EdlOverloadError
from edl_tpu.utils.log import get_logger

logger = get_logger("distill.slo")

VERDICTS = ("ok", "late", "shed", "error")


class Verdict:
    __slots__ = (
        "seq", "t_s", "endpoint", "verdict", "latency_ms", "hedged",
        "backup_won", "cause",
    )

    def __init__(
        self,
        seq: int,
        t_s: float,
        endpoint: Optional[str],
        verdict: str,
        latency_ms: float,
        hedged: bool = False,
        backup_won: bool = False,
        cause: str = "",
    ) -> None:
        assert verdict in VERDICTS, verdict
        self.seq = seq
        self.t_s = t_s
        self.endpoint = endpoint
        self.verdict = verdict
        self.latency_ms = latency_ms
        self.hedged = hedged
        self.backup_won = backup_won
        self.cause = cause


class SloDriver:
    """Drive ``qps`` paced predict requests for ``duration_s`` against a
    (possibly changing) teacher fleet and account every one.

    ``endpoints_fn`` is polled per request — pass a lambda over
    ``DiscoveryClient.get_servers()`` for a live fleet or over a static
    list for a bench. ``make_feeds(seq)`` builds the request payload."""

    def __init__(
        self,
        endpoints_fn: Callable[[], Sequence[str]],
        make_feeds: Callable[[int], Dict[str, np.ndarray]],
        qps: float,
        duration_s: float,
        slo_ms: float,
        concurrency: int = 8,
        rpc_timeout: float = 5.0,
        seed: int = 0,
        breakers: Optional[BreakerBoard] = None,
        hedge: Optional[HedgePolicy] = None,
        retry_budget: Optional[RetryBudget] = None,
    ) -> None:
        assert qps > 0 and duration_s > 0 and slo_ms > 0
        self._endpoints_fn = endpoints_fn
        self._make_feeds = make_feeds
        self._qps = float(qps)
        self._duration = float(duration_s)
        self.slo_ms = float(slo_ms)
        self._concurrency = max(1, int(concurrency))
        self._rpc_timeout = rpc_timeout
        self._rng = random.Random(seed)
        self.breakers = breakers if breakers is not None else BreakerBoard()
        self.hedge = hedge if hedge is not None else HedgePolicy()
        self.retry_budget = (
            retry_budget if retry_budget is not None else RetryBudget()
        )
        self._lock = threading.Lock()
        self.verdicts: List[Verdict] = []
        self._qdepth: Dict[str, float] = {}   # endpoint -> advertised depth
        self._inflight: Dict[str, int] = {}   # endpoint -> our in-flight
        self._next_seq = 0
        self._issued = 0
        self._t0 = 0.0

    # -- endpoint choice ---------------------------------------------------

    def _choose(self, exclude: Optional[str] = None) -> Optional[str]:
        """Breaker-admitted endpoint with the smallest (our in-flight +
        teacher-advertised) queue; random tie-break so equal teachers
        share load."""
        candidates = [
            e for e in self._endpoints_fn()
            if e != exclude and self.breakers.admits(e)
        ]
        if not candidates:
            return None
        with self._lock:
            def weight(e: str) -> float:
                return self._inflight.get(e, 0) + self._qdepth.get(e, 0.0)

            low = min(weight(e) for e in candidates)
            best = [e for e in candidates if weight(e) <= low]
            pick = best[self._rng.randrange(len(best))]
            self._inflight[pick] = self._inflight.get(pick, 0) + 1
        return pick

    def _done(self, endpoint: str, client: Optional[PredictClient]) -> None:
        with self._lock:
            n = self._inflight.get(endpoint, 0)
            if n > 0:
                self._inflight[endpoint] = n - 1
            if client is not None:
                self._qdepth[endpoint] = float(client.last_qdepth)

    # -- one request -------------------------------------------------------

    def _predict_on(
        self, clients: Dict[str, PredictClient], endpoint: str,
        feeds: Dict[str, np.ndarray], deadline_s: float,
    ):
        client = clients.get(endpoint)
        if client is None:
            client = clients[endpoint] = PredictClient(
                endpoint, timeout=self._rpc_timeout
            )
        try:
            out = client.predict(feeds, deadline_s=deadline_s)
        except (ConnectionError, OSError):
            # connection state is garbage now; redial next time
            clients.pop(endpoint, None)
            try:
                client.close()
            except OSError:
                pass
            raise
        return out, client

    def _one_attempt(
        self, clients: Dict[str, PredictClient], endpoint: str,
        feeds: Dict[str, np.ndarray], deadline_s: float, hinfo: Dict,
    ):
        """One (possibly hedged) attempt against ``endpoint``. Backups
        use a one-shot connection to another teacher, like the worker."""
        self.breakers.starting(endpoint)

        def primary():
            return self._predict_on(clients, endpoint, feeds, deadline_s)

        delay = self.hedge.delay_s()
        try:
            if delay is None:
                t0 = time.monotonic()
                out, client = primary()
                self.hedge.note_latency(time.monotonic() - t0)
            else:
                def backup_factory():
                    alt = self._choose(exclude=endpoint)
                    if alt is None:
                        return None
                    if not self.hedge.try_hedge():
                        self._done(alt, None)
                        return None
                    hinfo["hedged"] = True

                    def backup():
                        try:
                            bclient = PredictClient(
                                alt, timeout=self._rpc_timeout
                            )
                        except OSError:
                            self._done(alt, None)
                            raise
                        try:
                            out = bclient.predict(
                                feeds, deadline_s=deadline_s
                            )
                            return out, bclient
                        finally:
                            self._done(alt, bclient)
                            bclient.close()

                    return backup

                t0 = time.monotonic()
                (out, client), backup_won, abandoned = hedged_call(
                    primary, delay, backup_factory, policy=self.hedge
                )
                if backup_won:
                    hinfo["backup_won"] = True
                if not backup_won:
                    self.hedge.note_latency(time.monotonic() - t0)
                if abandoned:
                    # the primary connection still has an answer (or a
                    # failure) in flight: desynced, drop it
                    stale = clients.pop(endpoint, None)
                    if stale is not None:
                        try:
                            stale.close()
                        except OSError:
                            pass
                    client = None
        except EdlOverloadError:
            self.breakers.record_failure(endpoint, overload=True)
            raise
        except (ConnectionError, OSError):
            self.breakers.record_failure(endpoint)
            raise
        if client is not None or not hinfo.get("backup_won"):
            self.breakers.record_success(endpoint)
        self._done(endpoint, client)
        return out

    def _issue(
        self, seq: int, due: float, clients: Dict[str, PredictClient]
    ) -> Verdict:
        feeds = self._make_feeds(seq)
        deadline_s = self.slo_ms / 1000.0
        self.retry_budget.note_primary()
        self.hedge.note_primary()
        t_sched = due          # latency clock starts at SCHEDULED arrival
        attempts = 0
        endpoint = None
        last_failed = None
        last_cause = ""
        while True:
            attempts += 1
            # deadline propagation means REMAINING budget: schedule slip
            # and failed attempts eat it, so a request that can no longer
            # make its SLO is shed (here or at the teacher's admission
            # test) instead of burning fleet compute on a doomed answer
            remaining_s = deadline_s - (time.monotonic() - t_sched)
            if remaining_s <= 0:
                return Verdict(
                    seq, t_sched - self._t0, endpoint, "shed",
                    (time.monotonic() - t_sched) * 1e3,
                    cause="expired",
                )
            # a retry avoids the teacher that just failed us: a freshly
            # dead teacher has the LOWEST weight (its in-flight just
            # drained), so without the exclusion we would re-pick it
            endpoint = self._choose(exclude=last_failed)
            if endpoint is None:
                # nobody admitted: brief wait for a breaker to half-open
                # or discovery to deliver, then explicit error verdict
                if attempts <= 2 and self.retry_budget.try_spend():
                    time.sleep(min(0.05, deadline_s / 4))
                    continue
                return Verdict(
                    seq, t_sched - self._t0, None, "error",
                    (time.monotonic() - t_sched) * 1e3,
                    cause="no_endpoint",
                )
            hinfo: Dict = {}
            try:
                self._one_attempt(
                    clients, endpoint, feeds, remaining_s, hinfo
                )
            except EdlOverloadError as exc:
                self._done(endpoint, None)
                with self._lock:
                    self._qdepth[endpoint] = float(exc.qdepth)
                return Verdict(
                    seq, t_sched - self._t0, endpoint, "shed",
                    (time.monotonic() - t_sched) * 1e3,
                    hedged=bool(hinfo.get("hedged")), cause="overload",
                )
            except (ConnectionError, OSError) as exc:
                self._done(endpoint, None)
                last_failed = endpoint
                last_cause = type(exc).__name__
                if self.retry_budget.try_spend():
                    continue  # budgeted retry on a different teacher
                return Verdict(
                    seq, t_sched - self._t0, endpoint, "error",
                    (time.monotonic() - t_sched) * 1e3,
                    hedged=bool(hinfo.get("hedged")), cause=last_cause,
                )
            latency_ms = (time.monotonic() - t_sched) * 1e3
            verdict = "ok" if latency_ms <= self.slo_ms else "late"
            return Verdict(
                seq, t_sched - self._t0, endpoint, verdict, latency_ms,
                hedged=bool(hinfo.get("hedged")),
                backup_won=bool(hinfo.get("backup_won")),
            )

    # -- the paced run -----------------------------------------------------

    def _worker(self) -> None:
        clients: Dict[str, PredictClient] = {}
        period = 1.0 / self._qps
        total = int(round(self._qps * self._duration))
        try:
            while True:
                with self._lock:
                    if self._next_seq >= total:
                        return
                    seq = self._next_seq
                    self._next_seq += 1
                    self._issued += 1
                due = self._t0 + seq * period
                now = time.monotonic()
                if due > now:
                    time.sleep(due - now)
                v = self._issue(seq, due, clients)
                with self._lock:
                    self.verdicts.append(v)
        finally:
            for client in clients.values():
                try:
                    client.close()
                except OSError:
                    pass

    def run(self) -> Dict:
        self._t0 = time.monotonic()
        threads = [
            threading.Thread(
                target=self._worker, name="slo-driver-%d" % i, daemon=True
            )
            for i in range(self._concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - self._t0
        return self.summary(wall)

    def summary(self, wall_s: Optional[float] = None) -> Dict:
        verdicts = list(self.verdicts)
        counts = {k: 0 for k in VERDICTS}
        for v in verdicts:
            counts[v.verdict] += 1
        answered = sorted(
            v.latency_ms for v in verdicts if v.verdict in ("ok", "late")
        )

        def pct(q: float) -> Optional[float]:
            if not answered:
                return None
            idx = min(
                len(answered) - 1, int(q * (len(answered) - 1) + 0.5)
            )
            return round(answered[idx], 3)

        issued = len(verdicts)
        wall = wall_s if wall_s else self._duration
        primaries = max(1, self.hedge.budget.primaries or issued or 1)
        per_endpoint: Dict[str, Dict[str, int]] = {}
        for v in verdicts:
            if v.endpoint:
                row = per_endpoint.setdefault(
                    v.endpoint, {k: 0 for k in VERDICTS}
                )
                row[v.verdict] += 1
        return {
            "requests": issued,
            "offered_qps": round(self._qps, 2),
            "wall_s": round(wall, 3),
            "slo_ms": self.slo_ms,
            "verdicts": counts,
            # goodput: in-SLO answers per second — THE serving headline
            "serve_qps": round(counts["ok"] / max(wall, 1e-9), 2),
            "serve_p50_ms": pct(0.5),
            "serve_p99_ms": pct(0.99),
            "serve_shed_pct": round(
                100.0 * counts["shed"] / max(1, issued), 2
            ),
            "serve_hedge_ratio": round(
                self.hedge.hedges / primaries, 4
            ),
            "hedges": self.hedge.hedges,
            "hedge_wins": self.hedge.wins,
            "retries_spent": self.retry_budget.spent,
            "breakers": self.breakers.snapshot(),
            "per_endpoint": per_endpoint,
        }
