"""Student-side distill pipeline: reader → predict pool → ordered fetch.

Behavior parity with the reference's hot path
(python/edl/distill/distill_worker.py): tasks of ``teacher_batch_size``
samples flow through a pool of predict workers bounded by a semaphore of
``2*require_num + 2`` in-flight tasks; epoch ends are coordinated by a
poison-pill protocol carrying the epoch's task count; failed tasks are
re-queued for other workers (3 RPC retries each); the fetch side restores
task order before yielding.

Deliberate re-design (SURVEY §7 hard parts): the reference uses forked
processes and documents a fork-vs-logging deadlock it must tiptoe around
(distill_reader.py:360-369). Here the pipeline is **threads**: the student
side only does RPC I/O and numpy regrouping (both release the GIL); the
actual FLOPs run on the teacher servers. That removes every fork hazard,
makes teardown exact, and lets the NOP-backend test (reference
distill_reader_test.py) run hundreds of epochs in seconds.

Teacher membership is a :class:`ServerPool` the manage loop updates from
discovery; a worker whose teacher left the pool (or died) drops it and
acquires a live one — the reference's stop-event + server-recycling
behavior (distill_worker.py:57-133) without the event plumbing.
"""

from __future__ import annotations

import heapq
import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.distill.resilience import (
    BreakerBoard,
    HedgePolicy,
    RetryBudget,
    hedged_call,
)
from edl_tpu.distill.serving import PredictClient
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.exceptions import EdlOverloadError
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.retry import retry_call
from edl_tpu.utils.timeline import make_timeline

logger = get_logger("distill.worker")

_FP_PREDICT = _fault_point(
    "distill.predict",
    "student-side predict RPC: delay or drop (teacher looks sick; the "
    "retry/re-queue/cooldown machinery takes over)",
)

_M_PREDICT = obs_metrics.histogram(
    "edl_distill_predict_seconds",
    "teacher predict RPC latency seen by the student pipeline",
)
_M_TASKS = obs_metrics.counter(
    "edl_distill_tasks_total", "tasks completed by the predict pool"
)
_M_REQUEUES = obs_metrics.counter(
    "edl_distill_task_requeues_total", "tasks re-queued after a sick teacher"
)
_M_COOLDOWNS = obs_metrics.counter(
    "edl_distill_teacher_cooldowns_total", "teacher endpoints put in cooldown"
)


@dataclass
class Task:
    task_id: int
    unit_id: int            # index of the user-level unit (sample list/batch)
    last_in_unit: bool      # task completes its unit
    feeds: Dict[str, np.ndarray]          # what the teacher sees
    payload: List[Tuple]                  # the original samples
    fetchs: Optional[Dict[str, np.ndarray]] = None  # teacher predictions


@dataclass
class _PoisonPill:
    epoch: int
    feed_count: int         # tasks emitted this epoch


class ServerPool:
    """Live teacher endpoints with least-loaded acquisition and cooldown.

    ``version`` bumps on every membership change; workers re-check their
    endpoint against the pool each task, so retired teachers drain within
    one task.

    Resilience hooks: ``admit`` is an external veto predicate (the
    breaker board's ``admits``) consulted by :meth:`acquire` and
    :meth:`has` — an open breaker makes a teacher invisible without
    discovery churn; :meth:`note_qdepth` feeds the teacher-advertised
    queue depths into acquisition, so "least loaded" weighs real backlog
    (this client's in-flight count + everyone else's advertised queue),
    not just this client's own connections."""

    _COOLDOWN = 10.0
    _QDEPTH_TTL = 10.0  # advertised depths older than this are stale

    def __init__(
        self,
        cooldown: Optional[float] = None,
        admit: Optional[Callable[[str], bool]] = None,
    ) -> None:
        if cooldown is not None:
            self._COOLDOWN = cooldown
        self._cond = threading.Condition()
        self._endpoints: List[str] = []
        self._load: Dict[str, int] = {}
        self._bad_until: Dict[str, float] = {}
        self._qdepth: Dict[str, Tuple[float, float]] = {}  # (depth, ts)
        self._admit = admit if admit is not None else (lambda _e: True)
        self.version = 0
        self._closed = False

    def note_qdepth(self, endpoint: str, depth: float) -> None:
        with self._cond:
            self._qdepth[endpoint] = (float(depth), time.time())

    def _advertised(self, endpoint: str, now: float) -> float:
        depth, ts = self._qdepth.get(endpoint, (0.0, 0.0))
        return depth if now - ts <= self._QDEPTH_TTL else 0.0

    def update(self, endpoints: Sequence[str]) -> None:
        with self._cond:
            fresh = sorted(set(endpoints))
            if fresh == self._endpoints:
                return
            self._endpoints = fresh
            self._load = {e: self._load.get(e, 0) for e in fresh}
            # prune only *expired* cooldowns — a sick teacher that flaps out
            # of one discovery poll and back must not shed its cooldown
            now = time.time()
            self._bad_until = {
                e: t for e, t in self._bad_until.items() if t > now
            }
            self.version += 1
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def mark_bad(self, endpoint: str) -> None:
        """Put an endpoint in cooldown.  It stays a pool member (so it
        re-admits itself in :meth:`acquire` once the cooldown lapses, with
        no discovery churn required), but ``has`` reports it absent so
        workers holding a client for it drop it within one task."""
        with self._cond:
            self._bad_until[endpoint] = time.time() + self._COOLDOWN
            self._load.pop(endpoint, None)
            if endpoint in self._endpoints:
                self.version += 1
                self._cond.notify_all()

    def has(self, endpoint: str) -> bool:
        with self._cond:
            return (
                endpoint in self._endpoints
                and self._bad_until.get(endpoint, 0) <= time.time()
                and self._admit(endpoint)
            )

    def acquire(
        self,
        timeout: Optional[float] = None,
        exclude: Optional[str] = None,
    ) -> Optional[str]:
        """Least-loaded live endpoint, or None on close/timeout.

        ``exclude`` skips one endpoint — hedged backups must land on a
        *different* teacher than the primary they are racing."""
        deadline = None if timeout is None else time.time() + timeout
        with self._cond:
            while True:
                if self._closed:
                    return None
                now = time.time()
                ok = [
                    e for e in self._endpoints
                    if e != exclude
                    and self._bad_until.get(e, 0) <= now
                    and self._admit(e)
                ]
                if ok:
                    pick = min(
                        ok,
                        key=lambda e: self._load.get(e, 0)
                        + self._advertised(e, now),
                    )
                    self._load[pick] = self._load.get(pick, 0) + 1
                    return pick
                remaining = None if deadline is None else deadline - now
                if remaining is not None and remaining <= 0:
                    return None
                # Bounded wait even with timeout=None: cooldown expiry
                # (_bad_until lapsing) never notifies the condition, so an
                # unbounded wait would hang forever once every teacher is in
                # cooldown and membership is stable.  Wake at the earliest
                # cooldown deadline (or 0.5 s) and re-check.
                wake = 0.5
                pending = [
                    t - now for t in self._bad_until.values() if t > now
                ]
                if pending:
                    wake = min(wake, max(min(pending), 0.01))
                if remaining is not None:
                    wake = min(wake, remaining)
                self._cond.wait(wake)

    def release(self, endpoint: str) -> None:
        with self._cond:
            if endpoint in self._load and self._load[endpoint] > 0:
                self._load[endpoint] -= 1


class DistillPipeline:
    """The concurrent engine behind :class:`DistillReader`.

    ``generator_fn`` is re-invoked once per epoch. ``discover`` is called
    periodically by the manage loop and returns the current teacher
    endpoints."""

    def __init__(
        self,
        generator_fn: Callable,
        mode: str,                       # sample | sample_list | batch
        feeds: Sequence[str],
        fetchs: Optional[Sequence[str]],
        discover: Callable[[], Sequence[str]],
        teacher_batch_size: int = 128,
        require_num: int = 3,
        retry: int = 3,
        discover_interval: float = 1.0,
        rpc_timeout: float = 30.0,
        copy_batches: bool = True,
        slo_ms: Optional[float] = None,
    ) -> None:
        assert mode in ("sample", "sample_list", "batch"), mode
        self._generator_fn = generator_fn
        self._mode = mode
        self._feeds = list(feeds)
        self._fetchs = list(fetchs) if fetchs is not None else None
        self._discover = discover
        self._tbs = teacher_batch_size
        self._require_num = require_num
        self._retry = retry
        self._discover_interval = discover_interval
        self._rpc_timeout = rpc_timeout
        self._copy_batches = copy_batches
        if slo_ms is None:
            try:
                slo_ms = float(os.environ.get("EDL_SERVE_SLO_MS", "0") or 0)
            except ValueError:
                slo_ms = 0.0
        self._slo_s = max(0.0, float(slo_ms)) / 1000.0

        self._task_queue: "queue.Queue" = queue.Queue()
        self._out_queue: "queue.Queue" = queue.Queue()
        self._sem = threading.Semaphore(2 * require_num + 2)
        # resilience plane: breakers veto endpoints in the pool, the retry
        # budget caps in-place RPC retries fleet-wide (re-queues are NOT
        # retries: the epoch contract is exactly-once delivery, so a task
        # that gives up its retries moves to another teacher instead of
        # being dropped), and the hedge policy races a budget-capped
        # backup predict once the primary is past its tracked p95.
        self.breakers = BreakerBoard(
            on_open=self._on_breaker_open, on_close=self._on_breaker_close
        )
        self.retry_budget = RetryBudget(burst=float(2 * require_num + 2))
        self.hedge = HedgePolicy()
        self._pool = ServerPool(admit=self.breakers.admits)
        self._stop = threading.Event()
        self._epoch_consumed = threading.Event()
        self._counter_lock = threading.Lock()
        self._processed = 0          # tasks completed in the current epoch
        self._started = False
        self._threads: List[threading.Thread] = []
        self._error: Optional[BaseException] = None
        # legacy EDL_TIMELINE stderr lines only — the predict interval is
        # span-recorded directly below, so the shim must not feed the
        # tracer too (every op would land in the ring twice)
        self._timeline = make_timeline(feed_tracer=False)
        self._tracer = obs_trace.get_tracer()
        # queue depths sampled at scrape time — THE live signal for "is
        # the student starved or the teacher pool behind"; released on
        # stop() so the registry can't pin a dead pipeline's queues
        # (and their buffered ndarrays).
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_distill_task_queue_depth",
             "tasks waiting for a predict worker", self._task_queue.qsize),
            ("edl_distill_out_queue_depth",
             "predicted tasks awaiting ordered fetch", self._out_queue.qsize),
        ))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._threads.append(
            threading.Thread(target=self._manage_loop, name="distill-manage", daemon=True)
        )
        self._threads.append(
            threading.Thread(target=self._reader_loop, name="distill-reader", daemon=True)
        )
        for i in range(self._require_num):
            self._threads.append(
                threading.Thread(
                    target=self._predict_loop, name="distill-predict-%d" % i, daemon=True
                )
            )
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self._pool.close()
        self._epoch_consumed.set()
        # release any reader blocked on the semaphore
        self._sem.release()
        self._obs_gauges.release()

    def _fail(self, exc: BaseException) -> None:
        if self._error is None:
            self._error = exc
        self.stop()

    # -- breaker → discovery ejection ---------------------------------------

    def _on_breaker_open(self, endpoint: str) -> None:
        """A tripped breaker ejects the teacher twice over: locally the
        pool's admit veto hides it at once, and — when discovery supports
        it — a sick report lets :class:`BalanceTable` route *other*
        readers around it without waiting for its lease to expire."""
        report = getattr(self._discover, "report_sick", None)
        if report is not None:
            try:
                report(endpoint)
            except Exception as exc:  # noqa: BLE001 — advisory path
                logger.warning("sick report for %s failed: %s", endpoint, exc)

    def _on_breaker_close(self, endpoint: str) -> None:
        clear = getattr(self._discover, "clear_sick", None)
        if clear is not None:
            try:
                clear(endpoint)
            except Exception as exc:  # noqa: BLE001 — advisory path
                logger.warning("sick clear for %s failed: %s", endpoint, exc)

    # -- manage loop (teacher membership) ----------------------------------

    def _manage_loop(self) -> None:
        while not self._stop.is_set():
            try:
                endpoints = list(self._discover())
                self._pool.update(endpoints)
            except Exception as exc:  # noqa: BLE001 — discovery may flap
                logger.warning("discovery failed: %s", exc)
            self._stop.wait(self._discover_interval)

    # -- reader loop (epochs → tasks) --------------------------------------

    def _reader_loop(self) -> None:
        ids = itertools.count()
        epoch = 0
        try:
            while not self._stop.is_set():
                count = 0
                for task in self._cut_tasks(ids):
                    self._sem.acquire()
                    if self._stop.is_set():
                        return
                    self._task_queue.put(task)
                    count += 1
                self._task_queue.put(_PoisonPill(epoch, count))
                self._epoch_consumed.wait()
                self._epoch_consumed.clear()
                epoch += 1
        except BaseException as exc:  # noqa: BLE001 — surface via fetch side
            logger.exception("reader loop failed")
            self._fail(exc)

    def _cut_tasks(self, ids):
        """Regroup the user generator's units into teacher-sized tasks
        (≙ reference read_sample/_list/_batch, distill_worker.py:531-610).
        A task never spans two sample_list/batch units, so the fetch side
        can reassemble exact unit boundaries. In sample mode the unit IS
        one sample, so tasks group ``teacher_batch_size`` consecutive
        samples (reference read_sample accumulates across yields,
        distill_worker.py:531-563) — one RPC per sample would waste the
        teacher's MXU on batch-1 inference.

        Batch mode stays in array land end-to-end: tasks carry array
        slices (no per-sample Python tuples), which is where the
        student-side pipeline overhead went in profiling — two O(batch)
        Python loops per unit. Each chunk is copied ONCE here (array-level
        memcpy): the task must own its buffers, both because generators
        may legally reuse a yield buffer and because the fetch side hands
        payload arrays straight back to the consumer. ``copy_batches=
        False`` (DistillReader opt-in) skips that memcpy for generators
        that guarantee fresh buffers per yield — at 256-row image batches
        the copy is a measurable slice of the per-batch overhead."""
        if self._mode == "sample":
            chunk: List[Tuple] = []

            def sample_task(samples):
                tid = next(ids)
                return Task(
                    task_id=tid,
                    unit_id=tid,  # sample-mode tasks are their own unit
                    last_in_unit=True,
                    feeds=self._stack_feeds(samples),
                    payload=samples,
                )

            for unit in self._generator_fn():
                # copy each field NOW: generators may legally reuse their
                # yield buffer, and this task only ships at chunk boundary
                chunk.append(tuple(np.asarray(f).copy() for f in unit))
                if len(chunk) == self._tbs:
                    yield sample_task(chunk)
                    chunk = []
            if chunk:
                yield sample_task(chunk)
            return
        for unit_id, unit in enumerate(self._generator_fn()):
            if self._mode == "batch":
                arrays = tuple(np.asarray(a) for a in unit)
                n = arrays[0].shape[0]
                for a in arrays[1:]:
                    if a.shape[0] != n:
                        raise ValueError(
                            "batch unit %d has mismatched leading dims: %r"
                            % (unit_id, [x.shape for x in arrays])
                        )
                for start in range(0, n, self._tbs):
                    if self._copy_batches:
                        chunk = tuple(
                            a[start : start + self._tbs].copy() for a in arrays
                        )
                    else:
                        chunk = tuple(a[start : start + self._tbs] for a in arrays)
                    yield Task(
                        task_id=next(ids),
                        unit_id=unit_id,
                        last_in_unit=start + self._tbs >= n,
                        feeds={
                            name: chunk[j]
                            for j, name in enumerate(self._feeds)
                        },
                        payload=chunk,
                    )
                continue
            samples = self._unit_to_samples(unit)
            for start in range(0, len(samples), self._tbs):
                chunk = samples[start : start + self._tbs]
                yield Task(
                    task_id=next(ids),
                    unit_id=unit_id,
                    last_in_unit=start + self._tbs >= len(samples),
                    feeds=self._stack_feeds(chunk),
                    payload=chunk,
                )

    def _unit_to_samples(self, unit) -> List[Tuple]:
        if self._mode == "sample":
            return [tuple(unit)]
        return [tuple(s) for s in unit]

    def _stack_feeds(self, samples: List[Tuple]) -> Dict[str, np.ndarray]:
        return {
            name: np.stack([np.asarray(s[j]) for s in samples])
            for j, name in enumerate(self._feeds)
        }

    # -- predict loop ------------------------------------------------------

    def _predict_loop(self) -> None:
        client: Optional[PredictClient] = None
        endpoint: Optional[str] = None
        pool_version = -1
        try:
            while not self._stop.is_set():
                try:
                    item = self._task_queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                if isinstance(item, _PoisonPill):
                    with self._counter_lock:
                        done = self._processed >= item.feed_count
                        if done:
                            self._processed -= item.feed_count
                    if done:
                        self._out_queue.put(item)
                    else:
                        # tasks (incl. re-queued failures) still in flight
                        self._task_queue.put(item)
                        time.sleep(0.002)
                    continue

                # drop retired teachers between tasks
                if client is not None and (
                    self._pool.version != pool_version
                    and not self._pool.has(endpoint)
                ):
                    self._close_client(client, endpoint)
                    client, endpoint = None, None
                if client is None:
                    endpoint = self._pool.acquire()
                    pool_version = self._pool.version
                    if endpoint is None:  # pool closed
                        self._task_queue.put(item)
                        return
                    try:
                        client = PredictClient(endpoint, timeout=self._rpc_timeout)
                    except OSError as exc:
                        logger.warning("connect %s failed: %s", endpoint, exc)
                        _M_COOLDOWNS.inc()
                        self._pool.mark_bad(endpoint)
                        self._pool.release(endpoint)
                        client, endpoint = None, None
                        self._task_queue.put(item)
                        continue

                self.retry_budget.note_primary()
                self.hedge.note_primary()
                hstate = {"abandoned": False}

                def _attempt():
                    self._timeline.reset()
                    self.breakers.starting(endpoint)
                    t0 = time.monotonic()
                    try:
                        if _FP_PREDICT.armed:
                            _FP_PREDICT.fire(
                                task=item.task_id, endpoint=endpoint
                            )
                        if obs_trace.PROPAGATION.armed:
                            # span-scoped context: client.predict stamps
                            # this span's id into the frame, so the
                            # teacher-side handling span becomes its child
                            with obs_trace.child_span(
                                "distill_predict", task=item.task_id
                            ):
                                item.fetchs = self._predict_once(
                                    client, endpoint, item, hstate
                                )
                            _M_PREDICT.observe(time.monotonic() - t0)
                        else:
                            item.fetchs = self._predict_once(
                                client, endpoint, item, hstate
                            )
                            dt = time.monotonic() - t0
                            _M_PREDICT.observe(dt)
                            self._tracer.record(
                                "distill_predict", t0, dt, task=item.task_id
                            )
                    except EdlOverloadError:
                        self.breakers.record_failure(endpoint, overload=True)
                        self._pool.note_qdepth(endpoint, client.last_qdepth)
                        raise
                    except (ConnectionError, OSError):
                        self.breakers.record_failure(endpoint)
                        raise
                    if not hstate["abandoned"]:
                        # backup-won hedges say nothing about the primary:
                        # neither success nor failure is recorded for it
                        self.breakers.record_success(endpoint)
                        self._pool.note_qdepth(endpoint, client.last_qdepth)
                    self._timeline.record("task_predict", task=item.task_id)

                try:
                    retry_call(
                        _attempt,
                        what="distill.predict",
                        retry_on=(ConnectionError, OSError),
                        retries=max(0, self._retry - 1),
                        base_delay=0.02,
                        max_delay=0.2,
                        # give_up is polled once per caught failure; the
                        # short-circuit order means a breaker-vetoed
                        # endpoint costs no budget token, and an exhausted
                        # budget turns the failure into a re-queue (to a
                        # different teacher) instead of an in-place retry —
                        # fleet-wide retries stay ≤ ratio × primaries + burst
                        give_up=lambda: (
                            self._stop.is_set()
                            or not self.breakers.admits(endpoint)
                            or not self.retry_budget.try_spend()
                        ),
                        on_retry=lambda n, exc: logger.warning(
                            "predict on %s failed (attempt %d): %s",
                            endpoint, n, exc,
                        ),
                    )
                    ok = True
                except EdlOverloadError as exc:
                    # the teacher is alive and shedding — EdlOverloadError
                    # is deliberately not retry_on-shaped, so it lands here
                    # on the first shed. Re-queue: the epoch contract is
                    # exactly-once delivery, and breaker veto + advertised
                    # qdepth weighting steer the next attempt elsewhere.
                    logger.warning(
                        "predict on %s shed (qdepth=%d est_wait=%.0fms): %s",
                        endpoint, exc.qdepth, exc.est_wait_ms, exc,
                    )
                    _M_REQUEUES.inc()
                    self._task_queue.put(item)
                    time.sleep(0.02)  # don't hot-spin a fully shedding fleet
                    continue
                except (ConnectionError, OSError) as exc:
                    logger.warning(
                        "predict on %s exhausted %d attempts: %s",
                        endpoint, self._retry, exc,
                    )
                    ok = False
                if ok and hstate["abandoned"]:
                    # the backup won: the primary RPC is still in flight on
                    # this connection, so its frame stream is desynced.
                    # Closing it unblocks the abandoned thread; next task
                    # dials fresh. No cooldown — slow ≠ dead.
                    self._close_client(client, endpoint)
                    client, endpoint = None, None
                if ok:
                    _M_TASKS.inc()
                    # put-then-count under one lock: a pill holder checking
                    # processed >= feed_count must never observe the count
                    # before the task itself is in the out queue, or the pill
                    # could overtake the epoch's final task and end the epoch
                    # with a unit still in flight.
                    with self._counter_lock:
                        self._out_queue.put(item)
                        self._processed += 1
                else:
                    # teacher is sick: re-queue the task for someone else
                    # (reference distill_worker.py:437-446) and drop it
                    _M_REQUEUES.inc()
                    _M_COOLDOWNS.inc()
                    self._pool.mark_bad(endpoint)
                    self._close_client(client, endpoint)
                    client, endpoint = None, None
                    self._task_queue.put(item)
        except BaseException as exc:  # noqa: BLE001
            logger.exception("predict loop failed")
            self._fail(exc)
        finally:
            if client is not None:
                self._close_client(client, endpoint)

    def _predict_once(
        self,
        client: PredictClient,
        endpoint: str,
        item: Task,
        hstate: Dict[str, bool],
    ) -> Dict[str, np.ndarray]:
        """One predict RPC, hedged once the policy has a p95 to hedge at.

        The backup goes to a *different* teacher over a fresh one-shot
        connection (hedges are budget-rare; a connection cache is not
        worth the complexity). First success wins; a backup win marks the
        held client abandoned via ``hstate`` so the loop discards it."""
        deadline = self._slo_s if self._slo_s > 0 else None

        def primary():
            return client.predict(item.feeds, deadline_s=deadline)

        delay = self.hedge.delay_s()
        if delay is None:  # cold or disabled: plain call, seed the p95
            t0 = time.monotonic()
            out = primary()
            self.hedge.note_latency(time.monotonic() - t0)
            return out

        def backup_factory():
            # acquire BEFORE spending the token: no second teacher means
            # no hedge, and the budget should not be charged for it
            alt = self._pool.acquire(timeout=0.0, exclude=endpoint)
            if alt is None:
                return None
            if not self.hedge.try_hedge():
                self._pool.release(alt)
                return None
            logger.info(
                "hedging task %d: %s slow, backup to %s",
                item.task_id, endpoint, alt,
            )

            def backup():
                try:
                    bclient = PredictClient(alt, timeout=self._rpc_timeout)
                except OSError:
                    self._pool.release(alt)
                    raise
                try:
                    return bclient.predict(item.feeds, deadline_s=deadline)
                finally:
                    bclient.close()
                    self._pool.release(alt)

            return backup

        t0 = time.monotonic()
        out, backup_won, abandoned = hedged_call(
            primary, delay, backup_factory, policy=self.hedge
        )
        if not backup_won:
            self.hedge.note_latency(time.monotonic() - t0)
        if abandoned:
            hstate["abandoned"] = True
        return out

    def _close_client(self, client: PredictClient, endpoint: Optional[str]) -> None:
        client.close()
        if endpoint is not None:
            self._pool.release(endpoint)

    # -- fetch side (caller thread) ----------------------------------------

    def epoch(self):
        """Yield one epoch of units, in order, with predictions appended."""
        self.start()
        expected = getattr(self, "_next_expected", 0)
        pending: List[Tuple[int, Task]] = []
        assembling: List[Task] = []
        pill = None
        try:
            while True:
                if self._error is not None:
                    raise self._error
                if pill is not None and not pending:
                    break  # epoch complete and all tasks drained
                try:
                    item = self._out_queue.get(timeout=0.5)
                except queue.Empty:
                    continue
                if isinstance(item, _PoisonPill):
                    pill = item
                    continue
                heapq.heappush(pending, (item.task_id, item))
                while pending and pending[0][0] == expected:
                    _, task = heapq.heappop(pending)
                    expected += 1
                    self._sem.release()
                    assembling.append(task)
                    if task.last_in_unit:
                        yield from self._assemble(assembling)
                        assembling = []
        finally:
            self._next_expected = expected
            self._epoch_consumed.set()

    def _fetch_names(self, task: Task) -> List[str]:
        if self._fetchs is not None:
            return self._fetchs
        return sorted(task.fetchs or ())

    def _assemble(self, tasks: List[Task]):
        """Reassemble one user unit + teacher predictions, as a list of
        values to yield (≙ reference fetch_sample/_list/_batch,
        distill_worker.py:705-748). Sample mode yields one value per
        sample of its (multi-sample) task; the other modes yield one
        value per unit."""
        names = self._fetch_names(tasks[0])
        preds = [
            np.concatenate([t.fetchs[n] for t in tasks], axis=0)
            if len(tasks) > 1 else tasks[0].fetchs[n]
            for n in names
        ]
        if self._mode == "batch":
            # single-task units pass through with no further copy; the
            # payload arrays are task-owned copies under copy_batches=True
            # (the default) and READ-ONLY aliases of the generator's data
            # under the no-copy opt-in — nothing here may mutate them
            fields = tuple(
                np.concatenate([t.payload[j] for t in tasks], axis=0)
                if len(tasks) > 1 else tasks[0].payload[j]
                for j in range(len(tasks[0].payload))
            )
            return [fields + tuple(preds)]
        samples = [s for t in tasks for s in t.payload]
        per_sample = [
            tuple(s) + tuple(p[i] for p in preds)
            for i, s in enumerate(samples)
        ]
        if self._mode == "sample":
            return per_sample
        return [per_sample]
