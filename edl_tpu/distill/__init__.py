"""Elastic knowledge-distillation service layer.

TPU-native re-design of the reference's distillation pillar
(python/edl/distill/): a student-side :class:`DistillReader` streams
training batches through a dynamically discovered, load-balanced fleet of
teacher inference servers.

- ``serving``   — teacher predict server (JAX model behind the framed-TCP
  wire protocol; replaces Paddle Serving) + client + test backends.
- ``discovery`` — balance/discovery service: teachers register in the
  store, students get versioned, load-balanced teacher views.
- ``worker``    — the student-side multiprocessing pipeline (reader →
  predict pool → ordered fetch, poison-pill epoch protocol).
- ``reader``    — the user-facing DistillReader decorator.
- ``resilience`` — retry budgets, hedged predicts, circuit breakers
  (the Tail-at-Scale client toolkit shared by worker and slo driver).
- ``slo``       — closed-loop serving driver with per-request SLO
  verdicts (ok/late/shed/error), behind ``tools/serve_slo.py``.
"""

from edl_tpu.distill.fetch import FetchError, fetch_from_env, fetch_model
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.resilience import (
    BreakerBoard,
    FractionBudget,
    HedgePolicy,
    RetryBudget,
    hedged_call,
)
from edl_tpu.distill.serving import (
    CoalescingBackend,
    EchoPredictBackend,
    JaxPredictBackend,
    NopPredictBackend,
    PredictClient,
    PredictServer,
)

__all__ = [
    "DistillReader",
    "fetch_model",
    "fetch_from_env",
    "FetchError",
    "PredictServer",
    "PredictClient",
    "JaxPredictBackend",
    "NopPredictBackend",
    "CoalescingBackend",
    "EchoPredictBackend",
    "FractionBudget",
    "RetryBudget",
    "HedgePolicy",
    "hedged_call",
    "BreakerBoard",
]
