"""Job-scoped service registry over the coordination store.

Capability parity with the reference's etcd registry layer
(python/edl/discovery/etcd_client.py:52-257 ``EtcdClient`` +
python/edl/discovery/register.py:29-143 ``ServerRegister``):

- keys are ``/{job_id}/{service}/{name}`` with a value payload;
- a *registration* holds a lease (default TTL 10 s, matching the
  reference's liveness window) refreshed by a background keeper; if the
  lease is lost (store restart, network partition outliving the TTL) the
  registration re-registers itself and reports the incident;
- ``register_if_absent`` is the contended form used for rank racing;
- permanent (lease-less) puts record final status;
- ``watch_service`` delivers add/remove callbacks per server, resolving
  ``resync`` markers into a diff against a fresh read.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from edl_tpu.store.client import RESYNC, LeaseKeeper, StoreClient
from edl_tpu.utils.exceptions import EdlRegisterError, EdlStoreError
from edl_tpu.utils.log import get_logger
from edl_tpu.utils.retry import retry_call

logger = get_logger("discovery.registry")

DEFAULT_TTL = 10.0


@dataclass(frozen=True)
class ServerMeta:
    service: str
    name: str
    value: bytes
    mod_rev: int = 0


def _service_prefix(job_id: str, service: str) -> str:
    return "/%s/%s/" % (job_id, service)


class Registration:
    """A live, heartbeated registration. ``stop()`` to deregister."""

    def __init__(
        self,
        registry: "Registry",
        key: str,
        value: bytes,
        ttl: float,
        on_lost: Optional[Callable[[], None]],
        restore: bool = True,
    ) -> None:
        self._registry = registry
        self.key = key
        self.value = value
        self._ttl = ttl
        self._on_lost = on_lost
        self._restore = restore
        self._stopped = False
        self._keeper: Optional[LeaseKeeper] = None

    def _arm(self, lease: int) -> None:
        self._keeper = LeaseKeeper(
            self._registry._client, lease, self._ttl, on_lost=self._lost
        )

    def _lost(self) -> None:
        """Lease died under us: try to re-register, like the reference's
        heartbeat re-register loop (register.py:57-76).

        Contended keys (rank slots) must NOT auto-restore — blindly re-
        putting could steal a slot another pod legitimately won after our
        lease expired — so with ``restore=False`` the loss is only
        reported and the owner re-races."""
        if self._stopped:
            return
        if not self._restore:
            logger.warning("registration %s lost its lease", self.key)
            if self._on_lost is not None:
                self._on_lost()
            return
        logger.warning("registration %s lost its lease; re-registering", self.key)

        def _restore() -> None:
            # re-check before EVERY attempt: a stop() landing during the
            # backoff sleep must not be followed by a successful
            # re-register (resurrecting a key the owner just deleted,
            # with a LeaseKeeper nobody will ever stop)
            if self._stopped:
                raise EdlStoreError("registration stopped mid-restore")
            lease = self._registry._client.lease_grant(self._ttl)
            self._registry._client.put(self.key, self.value, lease=lease)
            if self._stopped:
                # lost the race after the put: undo rather than arm
                try:
                    self._registry._client.lease_revoke(lease)
                except EdlStoreError:
                    pass
                raise EdlStoreError("registration stopped mid-restore")
            self._arm(lease)

        try:
            # bound matches the reference's 45-retry give-up
            retry_call(
                _restore,
                what="register.restore",
                retry_on=(EdlStoreError,),
                retries=44,
                base_delay=0.1,
                max_delay=1.5,
                give_up=lambda: self._stopped,
            )
        except EdlStoreError:
            if self._stopped:
                return
            logger.error("registration %s could not be restored", self.key)
            if self._on_lost is not None:
                self._on_lost()
            return
        logger.info("registration %s restored", self.key)

    def update(self, value: bytes) -> None:
        """Overwrite the registration payload, keeping the same lease."""
        if self._keeper is None:
            raise EdlRegisterError("registration not armed")
        self.value = value
        self._registry._client.put(self.key, value, lease=self._keeper.lease)

    def stop(self, delete: bool = True) -> None:
        self._stopped = True
        if self._keeper is not None:
            self._keeper.stop(revoke=delete)


class ServiceWatch:
    """Watch one service's membership; add/rm callbacks like the
    reference's ``watch_service`` (etcd_client.py:116-170)."""

    def __init__(
        self,
        registry: "Registry",
        service: str,
        on_add: Optional[Callable[[ServerMeta], None]] = None,
        on_remove: Optional[Callable[[ServerMeta], None]] = None,
        on_change: Optional[Callable[[Dict[str, ServerMeta]], None]] = None,
    ) -> None:
        self._registry = registry
        self._service = service
        self._prefix = _service_prefix(registry.job_id, service)
        self._on_add = on_add
        self._on_remove = on_remove
        self._on_change = on_change
        self._lock = threading.Lock()
        self.servers: Dict[str, ServerMeta] = {}
        servers, rev = registry.get_service_with_revision(service)
        with self._lock:
            self.servers = {m.name: m for m in servers}
        for meta in servers:
            self._safe(self._on_add, meta)
        self._notify_change()
        self._watch = registry._client.watch(self._prefix, self._on_events, start_rev=rev)

    def _safe(self, fn, *args) -> None:
        if fn is None:
            return
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 — consumer bugs must not kill the watch
            logger.exception("service-watch callback failed for %s", self._service)

    def _name_of(self, key: str) -> str:
        return key[len(self._prefix):]

    def _on_events(self, events) -> None:
        changed = False
        for ev in events:
            if ev.type == RESYNC:
                changed |= self._resync()
                continue
            name = self._name_of(ev.key)
            if ev.type == "put":
                meta = ServerMeta(self._service, name, ev.value, ev.rev)
                with self._lock:
                    existed = name in self.servers
                    self.servers[name] = meta
                if not existed:
                    self._safe(self._on_add, meta)
                changed = True
            elif ev.type == "del":
                with self._lock:
                    meta = self.servers.pop(name, None)
                if meta is not None:
                    self._safe(self._on_remove, meta)
                    changed = True
        if changed:
            self._notify_change()

    def _resync(self) -> bool:
        servers, _ = self._registry.get_service_with_revision(self._service)
        fresh = {m.name: m for m in servers}
        with self._lock:
            old, self.servers = self.servers, fresh
        for name in fresh.keys() - old.keys():
            self._safe(self._on_add, fresh[name])
        for name in old.keys() - fresh.keys():
            self._safe(self._on_remove, old[name])
        return fresh != old

    def _notify_change(self) -> None:
        if self._on_change is not None:
            with self._lock:
                snapshot = dict(self.servers)
            self._safe(self._on_change, snapshot)

    def snapshot(self) -> Dict[str, ServerMeta]:
        with self._lock:
            return dict(self.servers)

    def cancel(self) -> None:
        self._watch.cancel()


class Registry:
    """All registry operations for one job, over one store client."""

    def __init__(self, client: StoreClient, job_id: str) -> None:
        self._client = client
        self.job_id = job_id

    # -- liveness-scoped registration -------------------------------------

    def register(
        self,
        service: str,
        name: str,
        value: bytes,
        ttl: float = DEFAULT_TTL,
        on_lost: Optional[Callable[[], None]] = None,
        restore: bool = True,
    ) -> Registration:
        key = _service_prefix(self.job_id, service) + name
        lease = self._client.lease_grant(ttl)
        self._client.put(key, value, lease=lease)
        reg = Registration(self, key, value, ttl, on_lost, restore)
        reg._arm(lease)
        return reg

    def register_if_absent(
        self,
        service: str,
        name: str,
        value: bytes,
        ttl: float = DEFAULT_TTL,
        on_lost: Optional[Callable[[], None]] = None,
        restore: bool = False,
    ) -> Tuple[Optional[Registration], Optional[bytes]]:
        """Contended registration (rank racing). Returns
        ``(registration, None)`` if we won, ``(None, holder_value)`` if the
        key already exists. Defaults to ``restore=False``: a lost contended
        slot is reported, never silently re-taken."""
        key = _service_prefix(self.job_id, service) + name
        lease = self._client.lease_grant(ttl)
        created, cur = self._client.put_if_absent(key, value, lease=lease)
        if not created:
            self._client.lease_revoke(lease)
            return None, cur
        reg = Registration(self, key, value, ttl, on_lost, restore)
        reg._arm(lease)
        return reg, None

    # -- permanent keys ----------------------------------------------------

    def set_permanent(self, service: str, name: str, value: bytes) -> None:
        self._client.put(_service_prefix(self.job_id, service) + name, value)

    def remove(self, service: str, name: str) -> bool:
        return self._client.delete(_service_prefix(self.job_id, service) + name)

    def remove_service(self, service: str) -> int:
        return self._client.delete_range(_service_prefix(self.job_id, service))

    # -- reads -------------------------------------------------------------

    def get_server(self, service: str, name: str) -> Optional[ServerMeta]:
        value, rev = self._client.get_with_rev(
            _service_prefix(self.job_id, service) + name
        )
        if value is None:
            return None
        return ServerMeta(service, name, value, rev)

    def get_service(self, service: str) -> List[ServerMeta]:
        return self.get_service_with_revision(service)[0]

    def get_service_with_revision(
        self, service: str
    ) -> Tuple[List[ServerMeta], int]:
        prefix = _service_prefix(self.job_id, service)
        kvs, rev = self._client.range(prefix)
        return [
            ServerMeta(service, k[len(prefix):], v, mr) for k, v, mr, _ in kvs
        ], rev

    # -- watches -----------------------------------------------------------

    def watch_service(
        self,
        service: str,
        on_add: Optional[Callable[[ServerMeta], None]] = None,
        on_remove: Optional[Callable[[ServerMeta], None]] = None,
        on_change: Optional[Callable[[Dict[str, ServerMeta]], None]] = None,
    ) -> ServiceWatch:
        return ServiceWatch(self, service, on_add, on_remove, on_change)
