"""Consistent-hash ring with virtual nodes and copy-on-write updates.

Capability parity with the reference's ring (python/edl/discovery/
consistent_hash.py:21-141): MD5 hashing, 300 virtual nodes per real node,
and single-writer copy-on-write so concurrent readers never take a lock —
mutation builds a fresh immutable ring snapshot and swaps it atomically.
Used to shard service names across balancer replicas (reference
balance_table.py:376-391).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class _Ring:
    """Immutable ring snapshot: sorted virtual-node hashes -> real node."""

    __slots__ = ("hashes", "owners", "nodes")

    def __init__(self, nodes: Sequence[str], vnodes: int) -> None:
        pairs = []
        for node in set(nodes):
            for i in range(vnodes):
                pairs.append((_hash("%s#%d" % (node, i)), node))
        pairs.sort()
        self.hashes = [h for h, _ in pairs]
        self.owners = [n for _, n in pairs]
        self.nodes = sorted(set(nodes))

    def get(self, key: str) -> Optional[str]:
        if not self.hashes:
            return None
        idx = bisect.bisect_right(self.hashes, _hash(key))
        if idx == len(self.hashes):
            idx = 0
        return self.owners[idx]

    def successors(self, key: str, k: int, exclude=()) -> List[str]:
        """Up to ``k`` DISTINCT ring successors of ``key``'s position,
        clockwise, skipping ``exclude`` — the replica-placement walk
        (e.g. checkpoint shards pushed to the K nodes after the owner)."""
        if not self.hashes or k <= 0:
            return []
        start = bisect.bisect_right(self.hashes, _hash(key))
        out: List[str] = []
        skip = set(exclude)
        for i in range(len(self.owners)):
            owner = self.owners[(start + i) % len(self.owners)]
            if owner in skip or owner in out:
                continue
            out.append(owner)
            if len(out) >= k:
                break
        return out


class ConsistentHash:
    def __init__(self, nodes: Iterable[str] = (), vnodes: int = 300) -> None:
        self._vnodes = vnodes
        self._ring = _Ring(list(nodes), vnodes)

    @property
    def nodes(self) -> List[str]:
        return list(self._ring.nodes)

    def add_node(self, node: str) -> None:
        self._ring = _Ring(self._ring.nodes + [node], self._vnodes)

    def remove_node(self, node: str) -> None:
        self._ring = _Ring(
            [n for n in self._ring.nodes if n != node], self._vnodes
        )

    def update_nodes(self, nodes: Iterable[str]) -> None:
        self._ring = _Ring(list(nodes), self._vnodes)

    def get_node(self, key: str) -> Optional[str]:
        return self._ring.get(key)

    def successors(self, key: str, k: int, exclude=()) -> List[str]:
        """See :meth:`_Ring.successors` (lock-free snapshot read)."""
        return self._ring.successors(key, k, exclude)

    def assign(self, keys: Iterable[str]) -> Dict[str, List[str]]:
        """Shard ``keys`` across nodes: node -> sorted keys it owns."""
        ring = self._ring
        out: Dict[str, List[str]] = {n: [] for n in ring.nodes}
        for key in sorted(keys):
            owner = ring.get(key)
            if owner is not None:
                out[owner].append(key)
        return out
