"""Peer-replicated checkpoint shards: shared-FS-free recovery tiers.

The paper's elastic contract resumes every membership change "from the
last HDFS/local checkpoint" — which puts ONE durable directory on the
critical path of every restore, and leaves nothing at all when that
directory is slow, partitioned, or gone. Gemini (SOSP '23) and CheckFreq
(FAST '21) show the fix this module implements: after every save, a
low-priority background thread pushes the pod's local checkpoint shards
to K ring-successor peers, so a killed pod's replacement recovers from
surviving pods at wire speed and the durable tier demotes to a
background backstop. Three pieces:

**The holder** (:class:`ReplicaServer`, launcher-owned, pod-scoped).
Receives digest-verified shard pushes into a replica dir
(``{src_pod}/{step}/{relpath}``), serves them back over the wire
(``ckpt_fetch``, byte-capped via the shared PR-8 transfer discipline in
``rpc/wire.read_entries_capped``), and publishes what it holds under the
``ckpt/replicas/{pod}`` store keyspace with a freshness rev — the
manifest IS the recovery map. Membership changes feed
:meth:`ReplicaServer.note_membership` so superseded replicas of departed
pods are garbage-collected.

**The pusher** (:class:`Replicator`, saver-side). Notified after each
``CheckpointManager.save``; a low-priority thread walks the finalized
step dir, picks K ring successors of its own pod on the existing
consistent-hash ring (``ckpt/peers`` registrations name the live
holders), and pushes chunked, digest-verified, budget-bounded
(``EDL_CKPT_REPL_BUDGET``) ``ckpt_push`` frames. It also mirrors the
step into the durable tier — the "background backstop" — and exports
``edl_ckpt_replica_lag_steps`` (latest saved step minus newest
peer-replicated step), the signal the ``ckpt-replica-stale`` monitor
rule watches. :meth:`Replicator.flush` is the synchronous form a
draining pod calls: per-pod and non-collective, it closes the
multi-pod-drain gap where ``emergency_save`` cannot run (Orbax saves
are collective).

**The assembler** (:func:`assemble_from_peers`, restore-side). Reads
the replica manifests, picks the newest complete step across holders,
fetches the missing shards (union across holders — a partially-holding
peer contributes what it has), digest-verifies every file, and lands
the step dir atomically in the local tier for a normal Orbax restore.
Any shortfall — dead holder, torn frame, digest mismatch (the
``ckpt.replicate.fetch`` corrupt drill) — abandons the assembly and the
restore degrades to the durable tier, never to a wedged worker; a
replica that assembles but fails Orbax's own restore is quarantined by
the PR-2 ``.corrupt`` rename path like any torn local version.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.log import get_logger

logger = get_logger("checkpoint.replicate")

PEERS_SERVICE = "ckpt/peers"        # leased: {pod_id} -> replica endpoint
REPLICAS_SERVICE = "ckpt/replicas"  # permanent: {holder} -> manifest json

_FP_PUSH = _fault_point(
    "ckpt.replicate.push",
    "one pushed checkpoint shard: corrupt (digest rejected at the "
    "holder), delay (slow replication), drop (peer unreachable — the "
    "step stays unreplicated and restore degrades to the durable tier)",
)
_FP_FETCH = _fault_point(
    "ckpt.replicate.fetch",
    "one fetched replica shard during peer-tier assembly: corrupt "
    "(digest mismatch -> assembly abandoned, restore degrades to the "
    "durable tier), delay, drop (holder unreachable mid-fetch)",
)

_M_LAG = obs_metrics.gauge(
    "edl_ckpt_replica_lag_steps",
    "latest saved step minus the newest step fully replicated to a peer "
    "(0 = every checkpoint this pod saved survives it)",
)
_M_BYTES = obs_metrics.counter(
    "edl_ckpt_replicate_bytes_total",
    "checkpoint shard bytes moved between pods, by dir (tx/rx)",
)
_M_PUSHES = obs_metrics.counter(
    "edl_ckpt_replica_pushes_total",
    "checkpoint replication passes, by outcome "
    "(ok/failed/no_peers/emergency)",
)
_M_HELD = obs_metrics.gauge(
    "edl_ckpt_replicas_held",
    "complete peer checkpoint replicas this pod holds (src x step)",
)

_PUSH_CHUNK_FILES = 16
_PUSH_CHUNK_BYTES = 48 << 20
_FETCH_CAP_BYTES = 64 << 20
_MANIFEST_NAME = ".manifest.json"


def replica_count() -> int:
    """K, the ring-successor fan-out (``EDL_CKPT_REPLICAS``, default 1;
    0 disables the whole replication plane)."""
    try:
        return max(0, int(os.environ.get("EDL_CKPT_REPLICAS", "1")))
    except ValueError:
        return 1


def repl_budget() -> float:
    """Seconds one replication/assembly pass may spend
    (``EDL_CKPT_REPL_BUDGET``, default 10)."""
    try:
        return float(os.environ.get("EDL_CKPT_REPL_BUDGET", "10"))
    except ValueError:
        return 10.0


def _safe_relpath(name: str) -> bool:
    """True for a holder/peer-supplied shard name that is a plain
    RELATIVE path with no dot-component — enforced on every direction a
    name crosses a trust boundary (push write, fetch read, assembly
    write): a hostile manifest naming ``../../...`` must never choose
    where shard bytes land."""
    if not name or name.startswith(("/", "\\")) or "\\" in name:
        return False
    parts = name.split("/")
    return all(p and not p.startswith(".") for p in parts)


def _digest_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def step_manifest(step_dir: str) -> Dict[str, Dict]:
    """``{relpath: {"sha": hex, "size": n}}`` for every file under one
    finalized checkpoint step dir — the unit of replication."""
    out: Dict[str, Dict] = {}
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for name in filenames:
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, step_dir).replace(os.sep, "/")
            if not _safe_relpath(rel):
                continue
            try:
                out[rel] = {
                    "sha": _digest_file(path),
                    "size": os.path.getsize(path),
                }
            except OSError:
                continue
    return out


def finalized_steps(root: str) -> List[int]:
    """Step numbers with a finalized (plain-int-named) dir under
    ``root``, ascending — Orbax finalizes by rename, so a temp or
    quarantined dir never matches."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(int(n) for n in names if n.isdigit())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def _write_shard(root: str, rel: str, data: bytes) -> bool:
    """Write one digest-verified shard atomically (tmp + fsync +
    rename) under ``root``; a SIGKILL mid-write must never leave a
    torn file behind a verified name."""
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = "%s.edlrepl.%d" % (path, os.getpid())
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return True
    except OSError as exc:
        logger.warning("replica shard write failed (%s): %s", rel, exc)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


# -- the holder ---------------------------------------------------------------


class ReplicaServer:
    """Pod-side replica holder: receives pushes, serves fetches,
    publishes its manifest. Owned by the LAUNCHER (pod-scoped, survives
    worker restarts across stages), sharing the launcher's store client
    for manifest publication."""

    def __init__(
        self,
        replica_dir: str,
        client,
        job_id: str,
        pod_id: str,
        keep: int = 2,
        host: str = "0.0.0.0",
        port: int = 0,
        ttl: float = 10.0,
    ) -> None:
        self.replica_dir = os.path.abspath(replica_dir)
        os.makedirs(self.replica_dir, exist_ok=True)
        self._client = client
        self.job_id = job_id
        self.pod_id = pod_id
        self._keep = max(1, keep)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._host = host
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        # guards held-replica bookkeeping + manifest publication: pushes
        # arrive on per-connection threads while the launcher's
        # supervision loop calls note_membership()
        self._mu = threading.Lock()
        # (src, step) -> manifest dict, complete replicas only
        self._held: Dict[Tuple[str, int], Dict] = {}  # edl: guarded-by(self._mu)
        self._rev = 0  # edl: guarded-by(self._mu)
        # the manifest is LEASED (launcher-ttl): a SIGKILLed holder's
        # advertisement must expire like its peers registration — its
        # replicas died with its machine, and a phantom manifest would
        # both pollute the freshness-first restore ordering and
        # over-state the lost-work bound newest_replicated_step reports
        self._ttl = ttl
        self._pub_lock = threading.Lock()  # serializes register/update
        self._manifest_reg = None  # edl: guarded-by(self._pub_lock)
        # (src, step) -> manifest of a push IN FLIGHT: detects a
        # re-saved same-numbered step (different bytes, same number —
        # the quarantine-then-resave path) so the previous replica
        # generation is voided instead of mixing with the new one
        self._inflight: Dict[Tuple[str, int], Dict] = {}  # edl: guarded-by(self._mu)
        self._load_held()

    @property
    def endpoint(self) -> str:
        from edl_tpu.utils.net import get_host_ip

        host = self._host if self._host not in ("", "0.0.0.0") else get_host_ip()
        return "%s:%d" % (host, self.port)

    def start(self) -> "ReplicaServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="edl-ckpt-replica", daemon=True
        )
        self._accept_thread.start()
        with self._mu:
            warm = bool(self._held)
        if warm:
            # a relaunched pod over a warm replica dir re-advertises what
            # it still holds — the replicas are the point of surviving
            self._publish()
        return self

    def stop(self) -> None:
        self._stop.set()
        # retract the manifest now (clean stop); SIGKILLed holders are
        # covered by the lease expiring
        with self._pub_lock:
            reg, self._manifest_reg = self._manifest_reg, None
        if reg is not None:
            try:
                reg.stop(delete=True)
            except Exception:  # noqa: BLE001 — best-effort retraction
                pass
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    # -- held-set bookkeeping ----------------------------------------------

    def _load_held(self) -> None:
        """Recover the held set from disk manifests (a relaunched pod
        keeps serving what the previous incarnation stored)."""
        try:
            srcs = os.listdir(self.replica_dir)
        except OSError:
            return
        found: Dict[Tuple[str, int], Dict] = {}
        for src in srcs:
            for step in finalized_steps(os.path.join(self.replica_dir, src)):
                mpath = os.path.join(
                    self.replica_dir, src, str(step), _MANIFEST_NAME
                )
                try:
                    with open(mpath) as fh:
                        found[(src, step)] = json.load(fh)
                except (OSError, ValueError):
                    continue
        with self._mu:
            self._held.update(found)
            _M_HELD.set(len(self._held))

    def held(self) -> List[Tuple[str, int]]:
        with self._mu:
            return sorted(self._held)

    def note_membership(self, live_pods) -> None:
        """Launcher hook on every adopted generation: drop replicas of
        DEPARTED sources once superseded — a live source's complete
        replica at an equal-or-newer step proves the job moved past the
        departed pod's state — and trim every source to its newest
        ``keep`` steps. A dead pod's newest un-superseded replica is
        exactly what recovery needs, so it is never dropped."""
        live = set(live_pods)
        with self._mu:
            newest_live = max(
                (s for (src, s) in self._held if src in live), default=None
            )
            drop: List[Tuple[str, int]] = []
            by_src: Dict[str, List[int]] = {}
            for src, step in self._held:
                by_src.setdefault(src, []).append(step)
            for src, steps in by_src.items():
                steps.sort()
                drop.extend((src, s) for s in steps[: -self._keep])
                if src not in live and newest_live is not None:
                    drop.extend(
                        (src, s)
                        for s in steps[-self._keep:]
                        if s <= newest_live
                    )
            for key in set(drop):
                self._held.pop(key, None)
        for src, step in set(drop):
            shutil.rmtree(
                os.path.join(self.replica_dir, src, str(step)),
                ignore_errors=True,
            )
        if drop:
            logger.info(
                "replica gc: dropped %d superseded replica(s)", len(set(drop))
            )
            self._publish()

    def _publish(self) -> None:
        """(Re)publish the leased manifest with a bumped freshness rev."""
        with self._mu:
            self._rev += 1
            payload = {
                "endpoint": self.endpoint,
                "rev": self._rev,
                "ts": time.time(),
                "replicas": {},
            }
            for (src, step), manifest in self._held.items():
                payload["replicas"].setdefault(src, {})[str(step)] = {
                    "files": manifest,
                    "complete": True,
                }
            _M_HELD.set(len(self._held))
            body = json.dumps(payload, sort_keys=True).encode()
        try:
            with self._pub_lock:
                if self._manifest_reg is None:
                    from edl_tpu.discovery.registry import Registry

                    self._manifest_reg = Registry(
                        self._client, self.job_id
                    ).register(
                        REPLICAS_SERVICE, self.pod_id, body, ttl=self._ttl
                    )
                else:
                    self._manifest_reg.update(body)
        except Exception as exc:  # noqa: BLE001 — a sick store delays the
            # next assembly's map, it never breaks the holder
            logger.debug("replica manifest publish failed: %s", exc)

    # -- serving ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(sock,), daemon=True
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        from edl_tpu.rpc.wire import pack_frame, read_frame_blocking

        try:
            with sock:
                sock.settimeout(30.0)
                while not self._stop.is_set():
                    req = read_frame_blocking(sock)
                    method = req.get("m")
                    if method == "ckpt_push":
                        resp = self._handle_push(req)
                    elif method == "ckpt_fetch":
                        resp = self._handle_fetch(req)
                    else:
                        resp = {
                            "ok": False,
                            "err": {"etype": "EdlStoreError",
                                    "detail": "unknown method"},
                        }
                    sock.sendall(pack_frame({"i": req.get("i", 0), **resp}))
        except Exception:  # noqa: BLE001 — a sick peer is its problem;
            pass  # the pusher/assembler re-dials or degrades a tier

    def _handle_push(self, req: dict) -> dict:
        from edl_tpu.rpc.wire import TC_FIELD, server_span

        src = str(req.get("src", ""))
        try:
            step = int(req.get("step", -1))
        except (TypeError, ValueError):
            step = -1
        manifest = req.get("manifest") or {}
        if not src or "/" in src or src.startswith(".") or step < 0:
            return {"ok": False, "err": {"etype": "EdlStoreError",
                                         "detail": "bad src/step"}}
        root = os.path.join(self.replica_dir, src, str(step))
        norm = {
            str(k): {"sha": (v or {}).get("sha"), "size": (v or {}).get("size")}
            for k, v in manifest.items()
        }
        with self._mu:
            prev = self._inflight.get((src, step)) or self._held.get(
                (src, step)
            )
            changed = prev is not None and prev != norm
            if changed:
                # a re-saved same-numbered step (crash -> quarantine ->
                # resave produces new bytes under an old number): the
                # previous replica generation is VOID — advertising its
                # digests against the new bytes would make every later
                # assembly fail digest checks and fall to durable
                self._held.pop((src, step), None)
            self._inflight[(src, step)] = norm
        if changed:
            shutil.rmtree(root, ignore_errors=True)
            logger.warning(
                "replica of %s step %d superseded by a re-push with a "
                "different manifest; previous generation dropped",
                src[:8], step,
            )
            self._publish()  # retract the void advertisement now
        rejected: List[str] = []
        received = 0
        with server_span("ckpt_push", req.get(TC_FIELD), server="ckptrepl"):
            for name, data in (req.get("entries") or {}).items():
                name = str(name)
                if (
                    not _safe_relpath(name)
                    or name not in manifest
                    or not isinstance(data, (bytes, bytearray))
                ):
                    rejected.append(name)
                    continue
                want = manifest[name].get("sha")
                if hashlib.sha256(bytes(data)).hexdigest() != want:
                    # corrupted in flight (the ckpt.replicate.push corrupt
                    # drill) or torn at the pusher: refuse — an incomplete
                    # replica is never published, and the pusher's step
                    # simply stays unreplicated
                    rejected.append(name)
                    continue
                if _write_shard(root, name, bytes(data)):
                    received += len(data)
                else:
                    rejected.append(name)
            _M_BYTES.inc(received, dir="rx")
        complete = self._check_complete(src, step, root, manifest)
        return {"ok": True, "complete": complete, "rejected": rejected}

    def _check_complete(
        self, src: str, step: int, root: str, manifest: dict
    ) -> bool:
        """Complete when every manifest file is on disk at its recorded
        size (bytes were digest-verified at write time)."""
        if not manifest:
            return False
        for name, meta in manifest.items():
            if not _safe_relpath(str(name)):
                return False
            path = os.path.join(root, str(name))
            try:
                if os.path.getsize(path) != int(meta.get("size", -1)):
                    return False
            except (OSError, TypeError, ValueError):
                return False
        with self._mu:
            known = (src, step) in self._held
            if not known:
                self._held[(src, step)] = {
                    str(k): {"sha": v.get("sha"), "size": v.get("size")}
                    for k, v in manifest.items()
                }
                self._inflight.pop((src, step), None)
        if not known:
            # the completeness marker lives as a dot-file so fetches
            # (bare-relpath-validated) can never serve it as a shard
            marker = os.path.join(root, _MANIFEST_NAME)
            tmp = "%s.%d" % (marker, os.getpid())
            try:
                with open(tmp, "w") as fh:
                    json.dump(manifest, fh, sort_keys=True)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, marker)
            except OSError:
                pass
            obs_events.record(
                "ckpt_replica", fsync=True, src=src[:8], step=step,
                holder=self.pod_id[:8],
            )
            logger.info(
                "holding complete replica of %s step %d", src[:8], step
            )
            self._publish()
        return True

    def _handle_fetch(self, req: dict) -> dict:
        from edl_tpu.rpc.wire import (
            TC_FIELD,
            read_entries_capped,
            server_span,
        )

        src = str(req.get("src", ""))
        step = str(req.get("step", ""))
        if not src or "/" in src or src.startswith(".") or not step.isdigit():
            return {"ok": False, "err": {"etype": "EdlStoreError",
                                         "detail": "bad src/step"}}
        root = os.path.join(self.replica_dir, src, step)
        with server_span("ckpt_fetch", req.get(TC_FIELD), server="ckptrepl"):
            entries, truncated, sent = read_entries_capped(
                [str(n) for n in (req.get("names") or ())],
                lambda name: (
                    os.path.join(root, name) if _safe_relpath(name) else None
                ),
                _FETCH_CAP_BYTES,
            )
            _M_BYTES.inc(sent, dir="tx")
        return {"ok": True, "entries": entries, "truncated": truncated}


# -- the pusher ---------------------------------------------------------------


class Replicator:
    """Saver-side background replication of finalized checkpoint steps.

    ``note_save(step)`` is called by :class:`CheckpointManager` after a
    save finalizes; a low-priority daemon thread then pushes the step's
    shards to K ring successors and mirrors it into the durable tier.
    ``flush(budget)`` runs one pass synchronously — the per-pod,
    non-collective emergency path a draining pod uses where the
    collective ``emergency_save`` cannot run."""

    def __init__(
        self,
        local_dir: str,
        client=None,
        endpoint: str = "",
        job_id: str = "",
        pod_id: str = "",
        k: Optional[int] = None,
        budget: Optional[float] = None,
        durable_path: Optional[str] = None,
    ) -> None:
        self.local_dir = os.path.abspath(local_dir)
        self._endpoint = endpoint
        self.job_id = job_id
        self.pod_id = pod_id
        self._k = replica_count() if k is None else max(0, int(k))
        self._budget = repl_budget() if budget is None else float(budget)
        self.durable_path = (
            os.path.abspath(durable_path) if durable_path else None
        )
        # _mu guards the cursor state + lazy client; _pass_lock serializes
        # whole replication passes between the thread and flush()
        self._mu = threading.Lock()
        self._pass_lock = threading.Lock()
        self._client = client  # edl: guarded-by(self._mu)
        self._owns_client = client is None
        self._pending: Optional[int] = None  # edl: guarded-by(self._mu)
        self._latest = -1  # edl: guarded-by(self._mu)
        self._replicated = -1  # edl: guarded-by(self._mu)
        # True after a pass that found NO registered peer holder: a lone
        # pod has nothing to replicate to, and its "lag" is not a
        # staleness signal an operator can act on — lag() reports 0 so
        # ckpt-replica-stale never pages a single-pod deployment
        self._no_peers = False  # edl: guarded-by(self._mu)
        self._mirrored = -1  # edl: guarded-by(self._mu)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- API ---------------------------------------------------------------

    def note_save(self, step: int) -> None:
        """A finalized step exists; replicate it soon (newest wins)."""
        with self._mu:
            self._latest = max(self._latest, int(step))
            if self._pending is None or step > self._pending:
                self._pending = int(step)
            _M_LAG.set(self._lag_locked())
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="edl-ckpt-replicator", daemon=True
                )
                self._thread.start()
        self._wake.set()

    def flush(self, budget_s: Optional[float] = None) -> bool:
        """Synchronously replicate the newest finalized step (emergency
        path — a drain budget bounds it). True when at least one peer
        holds a complete copy of the newest step."""
        steps = finalized_steps(self.local_dir)
        if not steps:
            return False
        step = steps[-1]
        with self._mu:
            self._latest = max(self._latest, step)
            already = self._replicated >= step
        if already:
            return True
        ok = self._replicate_pass(
            step, self._budget if budget_s is None else float(budget_s),
            emergency=True,
        )
        return ok

    @property
    def peers_armed(self) -> bool:
        """False for a mirror-only (k=0) replicator — emergency peer
        pushes have nothing to push to."""
        return self._k > 0

    def _lag_locked(self) -> int:
        if self._k <= 0 or self._latest < 0 or self._no_peers:  # edl: lock-free(every caller holds self._mu)
            return 0  # mirror-only / lone pod: nothing to lag behind
        return max(0, self._latest - max(self._replicated, 0))  # edl: lock-free(every caller holds self._mu)

    def lag(self) -> int:
        with self._mu:
            return self._lag_locked()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self._mu:
            owns, client = self._owns_client, self._client
            if owns:
                self._client = None
        if owns and client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass

    # -- the replication loop ----------------------------------------------

    def _run(self) -> None:
        try:
            # the replicator must lose CPU arbitration to the training
            # step it runs beside (same discipline as the AOT ladder)
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 10)
        except (AttributeError, OSError, ValueError):
            pass
        retries: Dict[int, int] = {}
        while not self._stop.is_set():
            self._wake.wait()
            self._wake.clear()
            if self._stop.is_set():
                return
            with self._mu:
                step, self._pending = self._pending, None
            if step is None:
                continue
            try:
                self._replicate_pass(step, self._budget)
            except Exception as exc:  # noqa: BLE001 — replication is a
                # durability lever, never a crash over training
                _M_PUSHES.inc(outcome="failed")
                logger.warning("checkpoint replication aborted: %s", exc)
                continue
            if not os.path.isdir(os.path.join(self.local_dir, str(step))):
                # an ASYNC save not finalized yet: re-arm bounded (a
                # finalize takes seconds; a step that never appears was
                # quarantined/aborted and must not spin forever)
                retries[step] = retries.get(step, 0) + 1
                if retries[step] <= 120 and not self._stop.wait(0.5):
                    with self._mu:
                        if self._pending is None or step > self._pending:
                            self._pending = step
                    self._wake.set()

    def _store(self):
        with self._mu:
            client = self._client
        if client is not None or not self._endpoint:
            return client
        # dial OUTSIDE the lock (the PR-9 lesson: a 5s connect must not
        # block note_save on the training thread)
        try:
            from edl_tpu.store.client import connect_store

            client = connect_store(self._endpoint, timeout=5.0)
        except Exception as exc:  # noqa: BLE001
            logger.debug("replicator: no store client (%s)", exc)
            return None
        with self._mu:
            if self._client is None:
                self._client = client
                return client
            existing = self._client
        try:
            client.close()  # lost the publish race
        except Exception:  # noqa: BLE001
            pass
        return existing

    def _peers(self) -> Dict[str, str]:
        """Live replica holders ``{pod_id: endpoint}`` (own pod excluded)."""
        client = self._store()
        if client is None or not self.job_id:
            return {}
        try:
            from edl_tpu.discovery.registry import Registry

            rows = Registry(client, self.job_id).get_service(PEERS_SERVICE)
        except Exception as exc:  # noqa: BLE001
            logger.debug("replicator: peer read failed: %s", exc)
            return {}
        return {
            m.name: m.value.decode()
            for m in rows
            if m.name != self.pod_id and m.value
        }

    def _targets(self, peers: Dict[str, str]) -> List[str]:
        """K ring successors of this pod among the live holders — the
        same consistent-hash ring the store shards and the distill
        balance tables ride."""
        from edl_tpu.discovery.consistent_hash import ConsistentHash

        ring = ConsistentHash([*peers, self.pod_id])
        return ring.successors(self.pod_id, self._k, exclude=(self.pod_id,))

    def _replicate_pass(
        self, step: int, budget_s: float, emergency: bool = False
    ) -> bool:
        # the deadline starts BEFORE the lock wait: an emergency flush
        # arriving while the background thread mirrors to a slow durable
        # FS must spend its drain budget waiting at most, never block
        # unboundedly past it (SIGKILL lands on schedule either way)
        t_end = time.monotonic() + max(0.5, budget_s)
        if emergency:
            if not self._pass_lock.acquire(
                timeout=max(0.1, t_end - time.monotonic())
            ):
                logger.warning(
                    "emergency replication could not interrupt a running "
                    "pass within the budget; the last pushed replica is "
                    "the recovery point"
                )
                return False
        else:
            self._pass_lock.acquire()
        try:
            return self._replicate_locked(
                step, max(0.5, t_end - time.monotonic()), emergency
            )
        finally:
            self._pass_lock.release()

    # edl: blocking-ok(hashing/dials under _pass_lock are the design: the lock serializes replication passes on the replicator's own low-prio thread, and the one latency-sensitive contender — emergency flush — acquires with a timeout budgeted BEFORE the wait, PR-12; audited for ISSUE 14)
    def _replicate_locked(
        self, step: int, budget_s: float, emergency: bool
    ) -> bool:
        t0 = time.monotonic()
        deadline = t0 + max(0.5, budget_s)
        with self._mu:
            pushed = self._replicated >= step
            mirrored = self._mirrored >= step
        if pushed and mirrored:
            # save() and wait() both note a sync save's step: the second
            # note must not re-hash and re-send the whole checkpoint
            return True
        step_dir = os.path.join(self.local_dir, str(step))
        if not os.path.isdir(step_dir):
            return False  # not finalized yet; the manager re-notes on wait()
        manifest = step_manifest(step_dir)
        if not manifest:
            return False
        if pushed:
            if not emergency:
                self._mirror_durable(step, step_dir, manifest)
            return True
        acked = False
        no_peers = False
        if self._k > 0:
            peers = self._peers()
            targets = self._targets(peers)
            if not targets:
                no_peers = True
                _M_PUSHES.inc(outcome="no_peers")
            for pod in targets:
                if time.monotonic() > deadline:
                    break
                if self._push_to(
                    peers[pod], step, step_dir, manifest, deadline
                ):
                    acked = True
        with self._mu:
            self._no_peers = no_peers
            if acked:
                self._replicated = max(self._replicated, step)
            _M_LAG.set(self._lag_locked())
        if acked:
            _M_PUSHES.inc(outcome="emergency" if emergency else "ok")
        elif self._k > 0 and not no_peers:
            _M_PUSHES.inc(outcome="failed")
        if self._k > 0:
            # mirror-only passes are not replication attempts: no
            # "failed" flight noise for a deliberately peer-less config
            obs_events.record(
                "ckpt_replicate", fsync=True, step=step,
                outcome="ok" if acked else "failed",
                emergency=emergency, dur=round(time.monotonic() - t0, 3),
            )
        # the durable tier is a background backstop: mirror AFTER the
        # wire-speed peer copies exist, inside whatever budget remains
        # (an emergency pass spends its whole budget on peers — the
        # durable tier is exactly what a drain cannot afford to wait on)
        if not emergency:
            self._mirror_durable(step, step_dir, manifest)
        return acked

    def _push_to(
        self, endpoint: str, step: int, step_dir: str,
        manifest: Dict[str, Dict], deadline: float,
    ) -> bool:
        from edl_tpu.rpc.wire import request_once

        names = sorted(manifest)
        complete = False
        span = obs_trace.child_span("ckpt_push", step=str(step))
        with span:
            while names:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                chunk: Dict[str, bytes] = {}
                size = 0
                while names and len(chunk) < _PUSH_CHUNK_FILES:
                    name = names[0]
                    try:
                        with open(os.path.join(step_dir, name), "rb") as fh:
                            data = fh.read()
                    except OSError:
                        return False  # step dir churned under us; give up
                    if chunk and size + len(data) > _PUSH_CHUNK_BYTES:
                        break
                    if _FP_PUSH.armed:
                        try:
                            data = _FP_PUSH.fire(data, name=name[:32])
                        except ConnectionError:
                            return False  # drop: peer "unreachable"
                    chunk[name] = data
                    size += len(data)
                    names.pop(0)
                try:
                    resp = request_once(
                        endpoint,
                        {"i": 1, "m": "ckpt_push", "src": self.pod_id,
                         "step": step, "manifest": manifest,
                         "entries": chunk},
                        timeout=max(0.5, min(remaining, 20.0)),
                    )
                except Exception as exc:  # noqa: BLE001
                    logger.debug("ckpt push to %s failed: %s", endpoint, exc)
                    return False
                if not resp.get("ok") or resp.get("rejected"):
                    logger.warning(
                        "ckpt push to %s rejected %d shard(s); step %d "
                        "stays unreplicated there",
                        endpoint, len(resp.get("rejected") or ()), step,
                    )
                    return False
                _M_BYTES.inc(size, dir="tx")
                complete = bool(resp.get("complete"))
        return complete

    def _mirror_durable(
        self, step: int, step_dir: str, manifest: Dict[str, Dict]
    ) -> None:
        """Copy the finalized step into the durable tier (tmp dir +
        atomic rename, per-file fsync) — the demoted backstop restore
        falls to when local and peer tiers both come up empty."""
        if self.durable_path is None:
            return
        with self._mu:
            if self._mirrored >= step:
                return
        dst = os.path.join(self.durable_path, str(step))
        if os.path.isdir(dst):
            with self._mu:
                self._mirrored = max(self._mirrored, step)
            return
        tmp = os.path.join(
            self.durable_path, ".mirror-%d-%d" % (step, os.getpid())
        )
        try:
            os.makedirs(self.durable_path, exist_ok=True)
            shutil.rmtree(tmp, ignore_errors=True)
            for rel in manifest:
                src = os.path.join(step_dir, rel)
                out = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(out), exist_ok=True)
                with open(src, "rb") as fin, open(out, "wb") as fout:
                    shutil.copyfileobj(fin, fout)
                    fout.flush()
                    os.fsync(fout.fileno())
            _fsync_dir(tmp)
            os.replace(tmp, dst)
            _fsync_dir(self.durable_path)
            with self._mu:
                self._mirrored = max(self._mirrored, step)
            obs_events.record("ckpt_mirror", step=step)
        except OSError as exc:
            logger.warning("durable mirror of step %d failed: %s", step, exc)
            shutil.rmtree(tmp, ignore_errors=True)


def make_replicator(
    local_dir: str, durable_path: Optional[str] = None
) -> Optional[Replicator]:
    """Saver-side replicator from the worker env contract, or None when
    there is nothing for it to do. ONE replicator per pod: in a
    multi-process pod every rank shares the pod-scoped local dir and
    calls the collective ``save()``, and N ranks each re-hashing and
    re-pushing the same shards would cost N× wire bytes and race the
    durable mirror — rank 0 *in the pod* owns the push.

    The DURABLE MIRROR is a purely local copy and must not be gated on
    the store/peer contract: a local tier with a durable path gets a
    mirror-only replicator (k=0) even without a store, a job id, or
    peer replication — otherwise `CheckpointManager(durable, local_dir=
    ssd)` outside the launcher env would silently never populate the
    durable path it was given."""
    if not local_dir:
        return None
    try:
        if int(os.environ.get("EDL_WORKER_RANK_IN_POD", "0") or 0) != 0:
            return None
    except ValueError:
        pass
    endpoint = os.environ.get("EDL_STORE_ENDPOINT", "")
    job_id = os.environ.get("EDL_JOB_ID", "")
    pod_id = os.environ.get("EDL_POD_ID", "")
    peers_armed = (
        replica_count() > 0 and endpoint and job_id and pod_id
    )
    if not peers_armed and not durable_path:
        return None
    return Replicator(
        local_dir,
        endpoint=endpoint if peers_armed else "",
        job_id=job_id,
        pod_id=pod_id,
        k=replica_count() if peers_armed else 0,
        durable_path=durable_path,
    )


# -- the assembler ------------------------------------------------------------


def read_replica_manifests(client, job_id: str) -> Dict[str, Dict]:
    """``{holder_pod: manifest}`` for every published replica manifest."""
    out: Dict[str, Dict] = {}
    prefix = "/%s/%s/" % (job_id, REPLICAS_SERVICE)
    try:
        rows, _rev = client.range(prefix)
    except Exception as exc:  # noqa: BLE001
        logger.debug("replica manifest read failed: %s", exc)
        return out
    for key, value, _c, _m in rows:
        try:
            out[key[len(prefix):]] = json.loads(value)
        except ValueError:
            continue
    return out


def newest_replicated_step(client, job_id: str) -> Optional[int]:
    """The newest step any holder advertises a COMPLETE replica of —
    the bound on lost work when a pod and its durable tier both die."""
    best: Optional[int] = None
    for manifest in read_replica_manifests(client, job_id).values():
        for steps in (manifest.get("replicas") or {}).values():
            for step_s, info in steps.items():
                if not info.get("complete") or not str(step_s).isdigit():
                    continue
                step = int(step_s)
                if best is None or step > best:
                    best = step
    return best


def _candidates_from_manifests(
    manifests: Dict[str, Dict],
) -> List[Tuple[int, str, List[Tuple[str, Dict[str, Dict]]]]]:
    """``[(step, src, [(endpoint, files), ...])]`` newest step first;
    holders of the same (src, step) are merged so assembly can take the
    union across partially-holding peers."""
    merged: Dict[Tuple[int, str], List[Tuple[str, Dict]]] = {}
    for manifest in manifests.values():
        endpoint = manifest.get("endpoint", "")
        if not endpoint:
            continue
        for src, steps in (manifest.get("replicas") or {}).items():
            for step_s, info in steps.items():
                if not info.get("complete") or not str(step_s).isdigit():
                    continue
                files = info.get("files") or {}
                if not files:
                    continue
                merged.setdefault((int(step_s), src), []).append(
                    (endpoint, files)
                )
    return [
        (step, src, holders)
        for (step, src), holders in sorted(merged.items(), reverse=True)
    ]


def _fetch_chunk(
    endpoint: str, src: str, step: int, names: List[str], timeout: float
) -> Tuple[Dict[str, bytes], List[str]]:
    from edl_tpu.rpc.wire import request_once

    try:
        resp = request_once(
            endpoint,
            {"i": 1, "m": "ckpt_fetch", "src": src, "step": step,
             "names": names},
            timeout=min(timeout, 30.0),
        )
    except Exception as exc:  # noqa: BLE001
        logger.debug("ckpt fetch from %s failed: %s", endpoint, exc)
        return {}, []
    if not resp.get("ok"):
        return {}, []
    return {
        str(n): bytes(d)
        for n, d in (resp.get("entries") or {}).items()
        if isinstance(d, (bytes, bytearray))
    }, [str(n) for n in (resp.get("truncated") or ())]


def peer_complete_steps(
    client=None, endpoint: str = "", job_id: str = "",
) -> List[int]:
    """Steps some holder advertises a COMPLETE replica of, newest
    first — the peek the restore ladder orders tiers by (freshness
    beats tier preference: a stale peer replica must not shadow a
    newer durable version)."""
    owns = False
    if client is None:
        if not endpoint:
            return []
        try:
            from edl_tpu.store.client import connect_store

            client = connect_store(endpoint, timeout=5.0)
            owns = True
        except Exception as exc:  # noqa: BLE001
            logger.debug("replica peek: no store (%s)", exc)
            return []
    try:
        return sorted(
            {
                step
                for step, _src, _holders in _candidates_from_manifests(
                    read_replica_manifests(client, job_id)
                )
            },
            reverse=True,
        )
    finally:
        if owns:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass


def assemble_from_peers(
    into_dir: str,
    client=None,
    endpoint: str = "",
    job_id: str = "",
    deadline: Optional[float] = None,
    step: Optional[int] = None,
) -> Optional[int]:
    """Rebuild a completely-replicated checkpoint step from peer
    holders into ``into_dir`` (the local tier) — the newest one, or the
    pinned ``step``. Returns the step number on success, None when no
    complete step could be assembled — the caller then degrades to the
    durable tier. Every file is digest-verified against the manifest
    and the step dir lands by one atomic rename, so a SIGKILL or a torn
    fetch can never leave a half-step behind a real step name."""
    if not into_dir or not job_id:
        return None
    budget = repl_budget() if deadline is None else float(deadline)
    t_end = time.monotonic() + budget
    owns_client = False
    if client is None:
        if not endpoint:
            return None
        try:
            from edl_tpu.store.client import connect_store

            client = connect_store(endpoint, timeout=min(5.0, budget))
            owns_client = True
        except Exception as exc:  # noqa: BLE001
            logger.debug("ckpt assembly: no store (%s)", exc)
            return None
    try:
        # NOTE: the restoring pod's OWN holder manifest stays in play —
        # the holder is launcher-owned and pod-scoped, so a surviving
        # pod whose worker lost its local tier recovers from the
        # replicas its own pod holds, over loopback (a holder never
        # holds its own pod's checkpoints: the ring excludes self)
        manifests = read_replica_manifests(client, job_id)
        candidates = _candidates_from_manifests(manifests)
        for cand_step, src, holders in candidates:
            if step is not None and cand_step != step:
                continue
            if time.monotonic() > t_end:
                break
            if os.path.isdir(os.path.join(into_dir, str(cand_step))):
                return cand_step  # already present (raced another rank)
            got = _assemble_step(
                into_dir, src, cand_step, holders, t_end
            )
            if got is not None:
                return got
        return None
    finally:
        if owns_client:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass


def _assemble_step(
    into_dir: str,
    src: str,
    step: int,
    holders: List[Tuple[str, Dict[str, Dict]]],
    t_end: float,
) -> Optional[int]:
    # the union manifest: any holder's file set for a complete replica
    # is the full set, but a partially-reachable fleet may need several
    wanted: Dict[str, Dict] = {}
    for _endpoint, files in holders:
        for name, meta in files.items():
            if _safe_relpath(str(name)):
                wanted.setdefault(str(name), meta)
    if not wanted:
        return None
    os.makedirs(into_dir, exist_ok=True)
    tmp = os.path.join(into_dir, ".peer-%d-%d" % (step, os.getpid()))
    shutil.rmtree(tmp, ignore_errors=True)
    t0 = time.monotonic()
    missing = set(wanted)
    rx = 0
    bad = 0
    # restage-trace segment: the peer fetch is one hop of the restore
    # ladder on the restage critical path
    with obs_trace.child_span("ckpt_fetch", step=str(step), src=src[:8]):
        try:
            for endpoint, files in holders:
                names = sorted(missing & set(files))
                while names and time.monotonic() <= t_end:
                    chunk, names = names[:_PUSH_CHUNK_FILES], names[_PUSH_CHUNK_FILES:]
                    got, truncated = _fetch_chunk(
                        endpoint, src, step, chunk,
                        max(0.5, min(5.0, t_end - time.monotonic())),
                    )
                    if not got:
                        break  # holder sick/gone: try the next one
                    names.extend(truncated)
                    for name, data in got.items():
                        if name not in missing:
                            continue
                        if _FP_FETCH.armed:
                            try:
                                data = _FP_FETCH.fire(data, name=name[:32])
                            except ConnectionError:
                                bad += 1
                                continue
                        sha = hashlib.sha256(data).hexdigest()
                        if sha != wanted[name].get("sha"):
                            bad += 1
                            logger.warning(
                                "ckpt assembly: digest mismatch for %s; "
                                "shard dropped", name[:48],
                            )
                            continue
                        if _write_shard(tmp, name, data):
                            missing.discard(name)
                            rx += len(data)
                if not missing:
                    break
        except Exception as exc:  # noqa: BLE001 — assembly is a tier, not a gate
            logger.warning("ckpt assembly failed (%s); trying next tier", exc)
    _M_BYTES.inc(rx, dir="rx")
    if missing:
        # partial quorum: shards are unrecoverable from the live holders
        # — abandon; the durable tier owns this case
        logger.warning(
            "ckpt assembly of step %d incomplete (%d/%d shards, %d bad); "
            "degrading to the durable tier",
            step, len(wanted) - len(missing), len(wanted), bad,
        )
        shutil.rmtree(tmp, ignore_errors=True)
        obs_events.record(
            "ckpt_peer_fetch", fsync=True, step=step, outcome="incomplete",
            shards=len(wanted) - len(missing), want=len(wanted), bad=bad,
        )
        return None
    _fsync_dir(tmp)
    dst = os.path.join(into_dir, str(step))
    try:
        os.replace(tmp, dst)
    except OSError as exc:
        logger.warning("ckpt assembly rename failed: %s", exc)
        shutil.rmtree(tmp, ignore_errors=True)
        return None
    _fsync_dir(into_dir)
    obs_events.record(
        "ckpt_peer_fetch", fsync=True, step=step, outcome="ok",
        bytes=rx, dur=round(time.monotonic() - t0, 3),
    )
    logger.info(
        "assembled checkpoint step %d from peer replicas (%d shards, "
        "%d bytes, %.2fs)", step, len(wanted), rx, time.monotonic() - t0,
    )
    return step
