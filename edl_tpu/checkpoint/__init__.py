from edl_tpu.checkpoint.manager import (
    CheckpointManager,
    TrainStatus,
    abstract_like,
)
from edl_tpu.checkpoint.adjust import AdjustRegistry, linear_scaled_lr
from edl_tpu.checkpoint.replicate import (
    ReplicaServer,
    Replicator,
    assemble_from_peers,
)

__all__ = [
    "CheckpointManager",
    "TrainStatus",
    "abstract_like",
    "AdjustRegistry",
    "linear_scaled_lr",
    "ReplicaServer",
    "Replicator",
    "assemble_from_peers",
]
