"""Sharded checkpoint/resume across topology change.

Capability parity with the reference's checkpoint contract — the *only*
state carried across an elastic resize (reference
example/collective/resnet50/train_with_fleet.py:422-428, 563-570:
``fleet.save_check_point/load_check_point`` with ``TrainStatus(epoch)``,
rank-0 saves per epoch, atomic write-temp-then-rename with incrementing
version per doc/fault_tolerance.md:19-28) — rebuilt on Orbax:

- arrays are saved **sharded** from every host and restored under *any*
  new mesh/sharding (the template's shardings win), so resume across a
  4→8 or 8→4 host resize needs no gather/re-scatter step — this is where
  the TPU-native design beats the reference, whose resume is
  whole-checkpoint-per-rank;
- atomicity and version counting are Orbax's finalize protocol (same
  temp-then-rename semantics the reference documents);
- ``TrainStatus`` (epoch/step/world size + free-form meta) rides along as
  JSON, exactly the role of the reference's ``TrainStatus`` + the
  step-level offsets its WIP ``DataCheckpoint`` sketches
  (python/edl/collective/data_reader.py:63-84).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import goodput as obs_goodput
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import numerics as obs_numerics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.log import get_logger

logger = get_logger("checkpoint.manager")

_FP_SAVE = _fault_point(
    "ckpt.save",
    "before a checkpoint save: kill (crash mid-save -> torn temp dirs, "
    "the finalize protocol must keep the previous version good) or delay",
)
_FP_RESTORE = _fault_point(
    "ckpt.restore", "before a checkpoint restore: delay (slow storage)"
)
_FP_EMERGENCY = _fault_point(
    "ckpt.emergency",
    "before an emergency (drain-notice) checkpoint: delay (slow storage "
    "eats the drain budget) or kill (preemption lands mid-save; the torn "
    "version must quarantine on restore)",
)

_M_SAVE_SECONDS = obs_metrics.histogram(
    "edl_ckpt_save_seconds", "checkpoint save blocking time"
)
_M_RESTORE_SECONDS = obs_metrics.histogram(
    "edl_ckpt_restore_seconds", "checkpoint restore time"
)
_M_SAVES = obs_metrics.counter("edl_ckpt_saves_total", "checkpoints saved")
_M_RESTORES = obs_metrics.counter(
    "edl_ckpt_restores_total",
    "checkpoints restored, by source tier (local/peer/durable)",
)
_M_SAVE_BYTES = obs_metrics.counter(
    "edl_ckpt_save_bytes_total", "logical array bytes written to checkpoints"
)
_M_RESTORE_BYTES = obs_metrics.counter(
    "edl_ckpt_restore_bytes_total", "logical array bytes restored from checkpoints"
)
_M_SAVE_SIZE = obs_metrics.histogram(
    "edl_ckpt_save_size_bytes", "logical size of each saved checkpoint",
    buckets=obs_metrics.SIZE_BUCKETS,
)
_M_RESTORE_FALLBACKS = obs_metrics.counter(
    "edl_ckpt_restore_fallbacks_total",
    "unreadable checkpoint versions skipped during restore",
)
_M_EMERGENCY_SECONDS = obs_metrics.histogram(
    "edl_train_emergency_ckpt_seconds",
    "wall time of drain-notice emergency checkpoints (save + bounded wait)",
)
_M_EMERGENCY = obs_metrics.counter(
    "edl_ckpt_emergency_saves_total",
    "emergency checkpoint actions on a drain notice, by outcome "
    "(skipped/failed/finished/unfinished/replicated/replicate_failed)",
)


def _tree_bytes(tree) -> int:
    """Logical (unsharded) byte size of a state pytree; best-effort."""
    total = 0
    try:
        for leaf in jax.tree.leaves(tree):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
    except Exception:  # noqa: BLE001 — metrics must not fail a save
        pass
    return total


@dataclasses.dataclass
class TrainStatus:
    """Progress metadata carried inside every checkpoint."""

    epoch: int = -1
    step: int = 0
    world_size: int = 1
    sample_offset: int = 0  # samples consumed within the current epoch
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def next_epoch(self) -> int:
        return self.epoch + 1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainStatus":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls) if f.name in d})


def abstract_like(tree):
    """Abstract (shape/dtype/sharding) template of a live state pytree.

    Build the template from a *freshly initialized* state on the new mesh:
    its shardings describe where restored arrays should land, which is what
    makes cross-topology resume automatic.
    """

    def to_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(to_abstract, tree)


class CheckpointManager:
    """Epoch/step-versioned sharded checkpoints with retention — and,
    when a pod-local tier is armed, a multi-tier restore ladder.

    ``save`` is collective (all hosts write their shards; Orbax finalizes
    atomically); ``restore`` reshards onto the template's mesh. A missing
    or empty directory restores to ``(template-as-is, None)`` so first
    launch and resume share one code path — mirroring the reference's
    ``load_check_point`` returning a fresh ``TrainStatus`` when no
    checkpoint exists (train_with_fleet.py:428).

    **Checkpoint tiers** (DESIGN.md "Checkpoint tiers & peer
    replication"). With ``local_dir`` set (or ``EDL_CKPT_LOCAL_DIR`` in
    the env — the launcher derives a per-pod path from
    ``EDL_CKPT_LOCAL_BASE``), saves land in the pod-LOCAL tier at disk
    speed; a background :class:`~edl_tpu.checkpoint.replicate.Replicator`
    then pushes the finalized shards to K ring-successor peers and
    mirrors them into ``path``, which demotes to the durable backstop.
    ``restore`` walks the ladder — local dir → peer replicas (assembled
    from the ``ckpt/replicas/`` manifests) → durable tier — so a killed
    pod's replacement recovers with zero shared-FS reads whenever the
    surviving peers hold a complete replica. Restores are attributed per
    tier (``edl_ckpt_restores_total{tier}``, the goodput ``ckpt_restore``
    cause, and the flight record's ``tier`` field). Without a local
    tier, ``path`` is the single durable tier and behavior is exactly
    the classic one (restores labeled ``tier="durable"``).
    """

    def __init__(
        self,
        path: str,
        max_to_keep: int = 3,
        async_save: bool = False,
        local_dir: Optional[str] = None,
    ) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        if local_dir is None:
            local_dir = os.environ.get("EDL_CKPT_LOCAL_DIR", "")
        path = os.path.abspath(os.fspath(path))
        if local_dir:
            self.path = os.path.abspath(os.fspath(local_dir))
            self.durable_path: Optional[str] = path
            self._tier = "local"
        else:
            self.path = path
            self.durable_path = None
            self._tier = "durable"
        self._async = async_save
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(self.path, options=options)
        # the saver-side replication plane (peer push + durable mirror);
        # None unless the local tier AND the worker env contract are armed
        self._replicator = None
        if self.durable_path is not None:
            from edl_tpu.checkpoint import replicate as _replicate

            self._replicator = _replicate.make_replicator(
                self.path, durable_path=self.durable_path
            )

    # -- save --------------------------------------------------------------

    def save(self, state, status: TrainStatus, step: Optional[int] = None) -> int:
        ocp = self._ocp
        if step is None:
            step = int(status.step)
        if _FP_SAVE.armed:
            _FP_SAVE.fire(step=step)
        t0 = time.monotonic()
        # goodput: the BLOCKING portion of the save is checkpoint cost,
        # not train time (async saves return early by design).
        # child_span: inside a live operation (a drain's emergency save,
        # a restage) the save stitches to it; standalone it roots its own
        # ckpt_save trace — the operation-root taxonomy of DESIGN.md
        # "Distributed tracing"
        status_doc = status.to_dict()
        try:
            # resize continuity sentinel: the manifest carries a
            # {step, loss, param_norm} numerics fingerprint — restore
            # re-derives the norm (quarantining mismatches) and the
            # restaged worker's probe asserts loss continuity against it
            status_doc = obs_numerics.stamp_fingerprint(status_doc, state, step)
        except Exception as exc:  # noqa: BLE001 — the stamp must never fail a save
            logger.warning("numerics fingerprint stamp failed: %s", exc)
        with obs_trace.child_span("ckpt_save", step=str(step)):
            with obs_goodput.phase("ckpt_save"):
                self._mngr.save(
                    step,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardSave(state),
                        status=ocp.args.JsonSave(status_doc),
                    ),
                )
            dt = time.monotonic() - t0  # async saves: the blocking portion
            _M_SAVE_SECONDS.observe(dt)
            _M_SAVES.inc()
            nbytes = _tree_bytes(state)
            _M_SAVE_BYTES.inc(nbytes)
            _M_SAVE_SIZE.observe(nbytes)
            obs_events.record(
                "ckpt_save", step=step, seconds=round(dt, 4), bytes=nbytes
            )
        if self._replicator is not None:
            # sync saves are finalized here; async ones finalize in the
            # background — the replicator re-checks until the step dir
            # appears, so an async-save job replicates DURING training,
            # not at the one wait() the trainer issues at job end
            self._replicator.note_save(step)
        return step

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        if self._replicator is not None:
            latest = self._mngr.latest_step()
            if latest is not None:
                self._replicator.note_save(int(latest))

    def emergency_save(
        self, state, status: TrainStatus, budget_s: float, step: Optional[int] = None
    ) -> Tuple[Optional[int], bool]:
        """Best-effort checkpoint on a preemption notice, bounded by
        ``budget_s``: rides the normal (possibly async) save path, then
        waits for finalization only as long as the budget allows. Returns
        ``(step, finished)``; ``finished=False`` means the save may still
        be in flight when the process exits — a torn version is exactly
        what the restore-side quarantine absorbs, so an unfinished
        emergency save degrades to the previous periodic checkpoint, never
        to a wedged restore.

        A step already covered by the newest finalized version is skipped
        (nothing to save: the drain loses zero work) and reported as
        ``(latest, True)``.
        """
        if step is None:
            step = int(status.step)
        t0 = time.monotonic()
        latest = self.latest_step()
        if latest is not None and step <= latest:
            _M_EMERGENCY.inc(outcome="skipped")
            return latest, True
        if _FP_EMERGENCY.armed:
            _FP_EMERGENCY.fire(step=step)
        with obs_goodput.phase("ckpt_save", cause="emergency"):
            try:
                self.save(state, status, step=step)
            except Exception as exc:  # noqa: BLE001 — a failed emergency save
                # must not turn the drain into a crash: the previous periodic
                # version is still good, and DRAINED_EXIT must still happen
                logger.warning("emergency checkpoint at step %d failed: %s", step, exc)
                _M_EMERGENCY.inc(outcome="failed")
                _M_EMERGENCY_SECONDS.observe(time.monotonic() - t0)
                obs_events.record(
                    "ckpt_emergency", fsync=True, step=step, outcome="failed"
                )
                return None, False
            remaining = budget_s - (time.monotonic() - t0)
            finished = self._wait_within(max(0.0, remaining))
        dt = time.monotonic() - t0
        _M_EMERGENCY_SECONDS.observe(dt)
        _M_EMERGENCY.inc(outcome="finished" if finished else "unfinished")
        obs_trace.get_tracer().instant(
            "ckpt_emergency", step=str(step),
            finished=str(finished).lower(),
        )
        obs_events.record(
            "ckpt_emergency", fsync=True, step=step,
            outcome="finished" if finished else "unfinished",
            seconds=round(dt, 4), budget_s=budget_s,
        )
        logger.info(
            "emergency checkpoint at step %d %s in %.2fs (budget %.1fs)",
            step, "finalized" if finished else "still in flight", dt, budget_s,
        )
        return step, finished

    def _wait_within(self, timeout_s: float) -> bool:
        """``wait()`` bounded by a timeout (Orbax exposes none): run the
        wait in a daemon thread and join with the budget. On timeout the
        finalization keeps running in the background — the caller exits
        anyway, and restore-side fallback owns the torn-version case."""
        import threading

        done = threading.Event()

        def _wait():
            try:
                self._mngr.wait_until_finished()
            except Exception as exc:  # noqa: BLE001
                logger.warning("emergency checkpoint finalize failed: %s", exc)
            finally:
                done.set()

        t = threading.Thread(target=_wait, name="edl-ckpt-emergency", daemon=True)
        t.start()
        return done.wait(timeout_s)

    def emergency_replicate(self, budget_s: float) -> bool:
        """Per-pod, NON-COLLECTIVE emergency durability: push the newest
        finalized local step to peer holders inside ``budget_s``.

        This closes the multi-pod-drain gap: a single draining pod of a
        multi-pod stage cannot run :meth:`emergency_save` (Orbax saves
        are collective — its peers will never join), but it CAN make the
        checkpoints it already holds survive its departure, because a
        replica push involves nobody's cooperation but one peer's.
        Returns True when at least one peer acked a complete copy."""
        if self._replicator is None or not self._replicator.peers_armed:
            return False  # mirror-only configs have no peers to push to
        t0 = time.monotonic()
        # an async save may still be finalizing: give it a slice of the
        # budget so the NEWEST version is what survives
        if self._async:
            self._wait_within(max(0.0, budget_s * 0.5))
            latest = self._mngr.latest_step()
            if latest is not None:
                self._replicator.note_save(int(latest))
        ok = self._replicator.flush(
            max(0.5, budget_s - (time.monotonic() - t0))
        )
        _M_EMERGENCY.inc(outcome="replicated" if ok else "replicate_failed")
        obs_events.record(
            "ckpt_emergency_repl", fsync=True,
            outcome="ok" if ok else "failed",
            seconds=round(time.monotonic() - t0, 4), budget_s=budget_s,
        )
        logger.info(
            "emergency replication %s in %.2fs (budget %.1fs)",
            "complete" if ok else "FAILED",
            time.monotonic() - t0, budget_s,
        )
        return ok

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def _candidates(self, step: Optional[int]) -> list:
        """Versions to try, newest first. An explicit ``step`` pins the
        list to that one version (the caller asked for it specifically)."""
        if step is not None:
            return [step]
        return sorted(self._mngr.all_steps(), reverse=True)

    def read_status(self, step: Optional[int] = None) -> Optional[TrainStatus]:
        """Read the latest TrainStatus WITHOUT restoring model state —
        cheap (json only), for decisions that must happen before the
        optimizer/state exist (e.g. status-aware hyper-parameter
        adjustment on resume). Unreadable versions fall back like
        :meth:`restore`."""
        ocp = self._ocp
        candidates = self._candidates(step)
        if not candidates:
            return None
        last_exc: Optional[Exception] = None
        for s in candidates:
            try:
                restored = self._mngr.restore(
                    s, args=ocp.args.Composite(status=ocp.args.JsonRestore())
                )
                return TrainStatus.from_dict(restored["status"])
            except Exception as exc:  # noqa: BLE001 — any torn version falls back
                last_exc = exc
                if step is None:
                    _M_RESTORE_FALLBACKS.inc()
                    logger.warning(
                        "checkpoint status at step %d unreadable (%s); "
                        "falling back to the previous version", s, exc,
                    )
        raise last_exc

    def restore(
        self, template, step: Optional[int] = None
    ) -> Tuple[Any, Optional[TrainStatus]]:
        """Restore onto ``template``'s shardings; (template, None) when
        every tier is empty.

        A torn/corrupt newest version (crash mid-upload, bad disk) must
        not take the job down when an older good version exists: with no
        explicit ``step``, unreadable versions are skipped newest-to-
        oldest with a warning (counted in
        ``edl_ckpt_restore_fallbacks_total``).

        With a local tier armed, restore walks the TIER LADDER,
        freshness first: candidate steps are gathered from the local
        dir, the complete PEER replicas advertised in ``ckpt/replicas/``
        manifests, and the DURABLE backstop, then tried newest step
        first with ties preferring the cheapest read (local → peer →
        durable). Peer steps are assembled shard-by-shard into the local
        tier (digest-verified, atomic step-dir rename); durable steps
        are copied in; an assembled/copied version that still fails
        Orbax's restore quarantines via the ``.corrupt`` rename path
        like any torn version and the walk continues. Only when every
        tier is exhausted does the last error propagate — that is real
        data loss, not a recoverable fault. An explicit ``step`` pins
        the restore to the primary tier, as before.
        """
        candidates = self._candidates(step)
        if _FP_RESTORE.armed and (candidates or self.durable_path):
            _FP_RESTORE.fire(step=candidates[0] if candidates else -1)
        last_exc: List[Optional[Exception]] = [None]
        bad: list = []
        if step is not None:
            out = self._try_candidates(
                template, candidates, True, self._tier, last_exc, bad
            )
            if out is not None:
                return out
            raise last_exc[0]
        if self.durable_path is None:
            # classic single-tier plane: exactly the pre-ladder behavior
            out = self._try_candidates(
                template, candidates, False, self._tier, last_exc, bad
            )
            if out is not None:
                return out
            if last_exc[0] is not None:
                raise last_exc[0]
            return template, None
        return self._restore_ladder(template, candidates, last_exc, bad)

    def _restore_ladder(
        self, template, local_steps, last_exc, bad
    ) -> Tuple[Any, Optional[TrainStatus]]:
        """Freshness-FIRST tier walk: candidate steps are gathered from
        every tier and tried newest step first regardless of tier (a
        stale peer replica must never shadow a newer durable version —
        e.g. a push that failed while the background mirror landed);
        ties prefer the cheapest read: local → peer → durable."""
        from edl_tpu.checkpoint import replicate as _replicate

        # ONE store client for the whole walk: recovery is when the
        # control plane is most likely degraded, and per-attempt 5s
        # connect timeouts would eat the downtime budget reconnecting
        peer_client = None
        peer_steps: List[int] = []
        if self._peer_tier_enabled():
            try:
                from edl_tpu.store.client import connect_store

                peer_client = connect_store(
                    os.environ.get("EDL_STORE_ENDPOINT", ""), timeout=5.0
                )
                peer_steps = _replicate.peer_complete_steps(
                    client=peer_client,
                    job_id=os.environ.get("EDL_JOB_ID", ""),
                )
            except Exception as exc:  # noqa: BLE001 — a tier, not a gate
                logger.warning("peer-tier peek failed: %s", exc)
        try:
            durable_steps = _replicate.finalized_steps(self.durable_path)
            plan: List[Tuple[int, str]] = []
            for s in sorted(
                {*local_steps, *peer_steps, *durable_steps}, reverse=True
            ):
                if s in local_steps:
                    plan.append((s, self._tier))
                if s in peer_steps:
                    plan.append((s, "peer"))
                if s in durable_steps:
                    plan.append((s, "durable"))
            for s, tier in plan:
                if tier == "peer":
                    if self._assemble_peer(s, peer_client) is None:
                        continue
                    self._reload()
                elif tier == "durable":
                    if not self._copy_from_durable(s):
                        continue
                    self._reload()
                out = self._try_candidates(
                    template, [s], False, tier, last_exc, bad
                )
                if out is not None:
                    return out
                if bad:
                    # quarantine NOW: the same step may exist in the next
                    # tier, and the torn copy must not squat on its name
                    # (nor shadow it as latest_step for future saves)
                    self._purge(bad)
                    bad[:] = []
                    self._reload()
        finally:
            if peer_client is not None:
                try:
                    peer_client.close()
                except Exception:  # noqa: BLE001
                    pass
        if last_exc[0] is not None:
            raise last_exc[0]
        return template, None

    def _try_candidates(
        self, template, candidates, pinned: bool, tier: str, last_exc, bad
    ) -> Optional[Tuple[Any, Optional[TrainStatus]]]:
        """One tier's restore attempt over ``candidates`` (newest
        first); returns the restored pair or None with ``last_exc[0]``/
        ``bad`` updated for the caller's ladder bookkeeping."""
        ocp = self._ocp
        for s in candidates:
            t0 = time.monotonic()
            try:
                # child_span: stitches into a live restage/drain trace
                # (the worker-side restore hop of the critical path), or
                # roots a standalone ckpt_restore trace. A failed attempt
                # records too, so fallback laps are visible in the trace.
                with obs_trace.child_span(
                    "ckpt_restore", step=str(s), tier=tier
                ):
                    with obs_goodput.phase("ckpt_restore", cause=tier):
                        restored = self._mngr.restore(
                            s,
                            args=ocp.args.Composite(
                                state=ocp.args.StandardRestore(abstract_like(template)),
                                status=ocp.args.JsonRestore(),
                            ),
                        )
                # re-derive the manifest's numerics fingerprint: bytes
                # Orbax accepted but the trainer never saved (torn or
                # tampered state) quarantine exactly like a torn version
                fp = ((restored.get("status") or {}).get("meta") or {}).get(
                    "numerics"
                )
                fp_ok, fp_detail = obs_numerics.verify_fingerprint(
                    restored["state"], fp
                )
                if not fp_ok:
                    raise RuntimeError(
                        "numerics fingerprint mismatch: %s" % fp_detail
                    )
            except Exception as exc:  # noqa: BLE001 — any torn version falls back
                last_exc[0] = exc
                if not pinned:
                    _M_RESTORE_FALLBACKS.inc()
                    bad.append(s)
                    logger.warning(
                        "checkpoint step %d unreadable (%s); falling back "
                        "to the previous version/tier", s, exc,
                    )
                continue
            dt = time.monotonic() - t0
            _M_RESTORE_SECONDS.observe(dt)
            _M_RESTORES.inc(tier=tier)
            _M_RESTORE_BYTES.inc(_tree_bytes(restored["state"]))
            obs_events.record(
                "ckpt_restore", fsync=True, step=s, tier=tier,
                seconds=round(dt, 4), fallbacks=len(bad),
            )
            self._purge(bad)
            if tier != self._tier:
                logger.info(
                    "restored step %d from the %s tier", s, tier
                )
            return restored["state"], TrainStatus.from_dict(restored["status"])
        return None

    def _reload(self) -> None:
        reload_fn = getattr(self._mngr, "reload", None)
        if reload_fn is not None:
            reload_fn()  # a tier landed a new step dir: drop cached lists

    def _peer_tier_enabled(self) -> bool:
        from edl_tpu.checkpoint import replicate as _replicate

        return (
            self.durable_path is not None
            and _replicate.replica_count() > 0
            and bool(os.environ.get("EDL_STORE_ENDPOINT"))
            and bool(os.environ.get("EDL_JOB_ID"))
        )

    def _assemble_peer(
        self, step: Optional[int] = None, client=None
    ) -> Optional[int]:
        from edl_tpu.checkpoint import replicate as _replicate

        try:
            return _replicate.assemble_from_peers(
                self.path,
                client=client,
                endpoint=os.environ.get("EDL_STORE_ENDPOINT", ""),
                job_id=os.environ.get("EDL_JOB_ID", ""),
                step=step,
            )
        except Exception as exc:  # noqa: BLE001 — a tier, never a gate
            logger.warning("peer-tier assembly failed: %s", exc)
            return None

    def _copy_from_durable(self, s: int) -> bool:
        """Land durable version ``s`` in the local tier (tmp dir +
        atomic rename) so one Orbax manager serves every tier."""
        import shutil

        src = os.path.join(self.durable_path, str(s))
        dst = os.path.join(self.path, str(s))
        if os.path.isdir(dst):
            return True
        tmp = os.path.join(self.path, ".durable-%d-%d" % (s, os.getpid()))
        try:
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.copytree(src, tmp)
            os.replace(tmp, dst)
            return True
        except OSError as exc:
            logger.warning(
                "durable-tier copy of step %d failed: %s", s, exc
            )
            shutil.rmtree(tmp, ignore_errors=True)
            return False

    def _purge(self, bad_steps) -> None:
        """QUARANTINE versions that failed to restore (rename the step dir
        to ``<step>.corrupt``): left in place they would shadow the good
        version as ``latest_step`` and collide with post-resume re-saves
        of the same step numbers. A rename — never a delete — because the
        failure might be the READER's (template/sharding mismatch,
        transient storage error), and destroying the newest checkpoint on
        a reader-side fault would turn a recoverable incident into data
        loss. Operators can inspect or restore the quarantined dir."""
        for s in bad_steps:
            src = os.path.join(self.path, str(s))
            if not os.path.isdir(src):
                continue
            # unique destination: the SAME step can be torn again after a
            # resume re-saved it (second crash mid-save) — a taken
            # .corrupt name must not silently leave the bad version live
            dst = "%s.corrupt" % src
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = "%s.corrupt.%d" % (src, n)
            try:
                os.replace(src, dst)
                reload_fn = getattr(self._mngr, "reload", None)
                if reload_fn is not None:
                    reload_fn()  # drop any cached step list
                logger.warning(
                    "quarantined unreadable checkpoint version %d -> %s",
                    s, dst,
                )
            except OSError as exc:
                logger.warning(
                    "could not quarantine unreadable checkpoint %d: %s", s, exc
                )

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def close(self) -> None:
        if self._replicator is not None:
            self._replicator.close()
        self._mngr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
