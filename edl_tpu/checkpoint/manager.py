"""Sharded checkpoint/resume across topology change.

Capability parity with the reference's checkpoint contract — the *only*
state carried across an elastic resize (reference
example/collective/resnet50/train_with_fleet.py:422-428, 563-570:
``fleet.save_check_point/load_check_point`` with ``TrainStatus(epoch)``,
rank-0 saves per epoch, atomic write-temp-then-rename with incrementing
version per doc/fault_tolerance.md:19-28) — rebuilt on Orbax:

- arrays are saved **sharded** from every host and restored under *any*
  new mesh/sharding (the template's shardings win), so resume across a
  4→8 or 8→4 host resize needs no gather/re-scatter step — this is where
  the TPU-native design beats the reference, whose resume is
  whole-checkpoint-per-rank;
- atomicity and version counting are Orbax's finalize protocol (same
  temp-then-rename semantics the reference documents);
- ``TrainStatus`` (epoch/step/world size + free-form meta) rides along as
  JSON, exactly the role of the reference's ``TrainStatus`` + the
  step-level offsets its WIP ``DataCheckpoint`` sketches
  (python/edl/collective/data_reader.py:63-84).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace

_M_SAVE_SECONDS = obs_metrics.histogram(
    "edl_ckpt_save_seconds", "checkpoint save blocking time"
)
_M_RESTORE_SECONDS = obs_metrics.histogram(
    "edl_ckpt_restore_seconds", "checkpoint restore time"
)
_M_SAVES = obs_metrics.counter("edl_ckpt_saves_total", "checkpoints saved")
_M_RESTORES = obs_metrics.counter("edl_ckpt_restores_total", "checkpoints restored")
_M_SAVE_BYTES = obs_metrics.counter(
    "edl_ckpt_save_bytes_total", "logical array bytes written to checkpoints"
)
_M_RESTORE_BYTES = obs_metrics.counter(
    "edl_ckpt_restore_bytes_total", "logical array bytes restored from checkpoints"
)
_M_SAVE_SIZE = obs_metrics.histogram(
    "edl_ckpt_save_size_bytes", "logical size of each saved checkpoint",
    buckets=obs_metrics.SIZE_BUCKETS,
)


def _tree_bytes(tree) -> int:
    """Logical (unsharded) byte size of a state pytree; best-effort."""
    total = 0
    try:
        for leaf in jax.tree.leaves(tree):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
    except Exception:  # noqa: BLE001 — metrics must not fail a save
        pass
    return total


@dataclasses.dataclass
class TrainStatus:
    """Progress metadata carried inside every checkpoint."""

    epoch: int = -1
    step: int = 0
    world_size: int = 1
    sample_offset: int = 0  # samples consumed within the current epoch
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def next_epoch(self) -> int:
        return self.epoch + 1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainStatus":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls) if f.name in d})


def abstract_like(tree):
    """Abstract (shape/dtype/sharding) template of a live state pytree.

    Build the template from a *freshly initialized* state on the new mesh:
    its shardings describe where restored arrays should land, which is what
    makes cross-topology resume automatic.
    """

    def to_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(to_abstract, tree)


class CheckpointManager:
    """Epoch/step-versioned sharded checkpoints with retention.

    ``save`` is collective (all hosts write their shards; Orbax finalizes
    atomically); ``restore`` reshards onto the template's mesh. A missing
    or empty directory restores to ``(template-as-is, None)`` so first
    launch and resume share one code path — mirroring the reference's
    ``load_check_point`` returning a fresh ``TrainStatus`` when no
    checkpoint exists (train_with_fleet.py:428).
    """

    def __init__(
        self,
        path: str,
        max_to_keep: int = 3,
        async_save: bool = False,
    ) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.path = os.path.abspath(os.fspath(path))
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(self.path, options=options)

    # -- save --------------------------------------------------------------

    def save(self, state, status: TrainStatus, step: Optional[int] = None) -> int:
        ocp = self._ocp
        if step is None:
            step = int(status.step)
        t0 = time.monotonic()
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                status=ocp.args.JsonSave(status.to_dict()),
            ),
        )
        dt = time.monotonic() - t0  # async saves: the blocking portion
        _M_SAVE_SECONDS.observe(dt)
        _M_SAVES.inc()
        nbytes = _tree_bytes(state)
        _M_SAVE_BYTES.inc(nbytes)
        _M_SAVE_SIZE.observe(nbytes)
        obs_trace.get_tracer().record("ckpt_save", t0, dt, step=step)
        return step

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def read_status(self, step: Optional[int] = None) -> Optional[TrainStatus]:
        """Read the latest TrainStatus WITHOUT restoring model state —
        cheap (json only), for decisions that must happen before the
        optimizer/state exist (e.g. status-aware hyper-parameter
        adjustment on resume)."""
        ocp = self._ocp
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(status=ocp.args.JsonRestore())
        )
        return TrainStatus.from_dict(restored["status"])

    def restore(
        self, template, step: Optional[int] = None
    ) -> Tuple[Any, Optional[TrainStatus]]:
        """Restore onto ``template``'s shardings; (template, None) if empty."""
        ocp = self._ocp
        if step is None:
            step = self.latest_step()
        if step is None:
            return template, None
        t0 = time.monotonic()
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_like(template)),
                status=ocp.args.JsonRestore(),
            ),
        )
        dt = time.monotonic() - t0
        _M_RESTORE_SECONDS.observe(dt)
        _M_RESTORES.inc()
        _M_RESTORE_BYTES.inc(_tree_bytes(restored["state"]))
        obs_trace.get_tracer().record("ckpt_restore", t0, dt, step=step)
        return restored["state"], TrainStatus.from_dict(restored["status"])

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
