"""Leader-hosted data-dispatch service: elastic task queues with failover.

The full behavior of the reference's legacy Go master — which does not
even compile in the reference tree (SURVEY §2 C22: task queues
Todo/Pending/Done/Failed with per-task failure counts and timeouts,
pkg/master/service.go:23-35, 134-150; state snapshot/recover via the
store under a leader lock, pkg/master/etcd_client.go:99-161) — finished
and tested, speaking the edl_tpu wire protocol. The native C++ twin
(``native/master``) serves the same methods; the Python client drives
either interchangeably.

A *task* is one input file (+ resume offset). Workers pull tasks, report
record progress, and ack done/failed; a pending task whose worker goes
quiet past ``task_timeout`` is re-queued (``failure_max`` strikes → failed
list, epoch completes without it — the reference's straggler policy).

Graceful drain (health plane): a draining worker — or its launcher's
preemption notice arriving as a ``preempt/{pod_id}`` store key, when the
dispatcher was built with a registry — has its in-flight tasks re-queued
IMMEDIATELY at their reported offsets (``drain_worker``), no strike, no
``task_timeout`` wait: a notice is a fact, not a suspicion.

Wire methods:
  add_dataset(files) / new_epoch(e) / get_task(w) / task_done(w, t) /
  task_failed(w, t) / report(w, t, rec) / drain_worker(w) / state / ping
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.rpc.wire import (
    TC_FIELD,
    WireError,
    pack_frame,
    read_frame_blocking,
    server_span,
)

_TC = obs_trace.PROPAGATION
from edl_tpu.utils.exceptions import EdlError, serialize_exception
from edl_tpu.utils.log import get_logger

logger = get_logger("data.dispatcher")

_FP_TASK = _fault_point(
    "data.dispatcher.request",
    "one dispatcher RPC (get_task/report/ack): delay or drop (the worker "
    "re-pulls; a quiet task re-queues after task_timeout)",
)

TODO, PENDING, DONE, FAILED = "todo", "pending", "done", "failed"


@dataclass
class DataTask:
    task_id: int
    file_idx: int
    path: str
    start_record: int = 0
    next_record: int = 0
    failures: int = 0
    worker: str = ""
    deadline: float = 0.0

    def public(self) -> dict:
        return {
            "id": self.task_id,
            "file_idx": self.file_idx,
            "path": self.path,
            "start_record": max(self.start_record, self.next_record),
        }


class _Queues:
    def __init__(self) -> None:
        self.todo: List[DataTask] = []
        self.pending: Dict[int, DataTask] = {}
        self.done: Dict[int, DataTask] = {}
        self.failed: Dict[int, DataTask] = {}


class DataDispatcher:
    """The dispatch state machine + its TCP server.

    ``store`` (optional ``(StoreClient, job_id)``) enables failover: state
    snapshots are written under ``data_master/state`` after every mutation
    and recovered on construction — the role of the Go master's etcd
    Save/Load (etcd_client.go:100-161). Leader election among replicas is
    the launcher's job (only the leader pod hosts the dispatcher), so no
    extra lock is taken here.
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        task_timeout: float = 60.0,
        failure_max: int = 3,
        registry=None,  # Registry for snapshot/recover (optional)
        shuffle_seed: Optional[int] = None,
    ) -> None:
        self._lock = threading.Lock()
        self._q = _Queues()
        self._epoch = 0
        self._files: List[str] = []
        self._next_task_id = 0
        self._task_timeout = task_timeout
        self._failure_max = failure_max
        self._registry = registry
        # cursor-snapshot cadence: report() offsets are too hot to
        # snapshot per call (one store put per progress heartbeat), but
        # losing them across a dispatcher restart replays every pending
        # file from its start_record — so reported cursors are flushed
        # to the store on a cadence by the timeout loop instead
        try:
            self._snapshot_every = float(
                os.environ.get("EDL_DATA_SNAPSHOT_EVERY", "2")
            )
        except ValueError:
            self._snapshot_every = 2.0
        self._dirty_reports = False  # edl: guarded-by(self._lock)
        self._last_cursor_snap = 0.0
        # pass_id-as-seed parity (reference train_with_fleet.py:458-464):
        # task order is a pure function of (seed, epoch), so an epoch
        # replayed after resize/restart dispatches files identically
        self._shuffle_seed = shuffle_seed
        if registry is not None:
            self._recover()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._host = host
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        # observability: queue depths sampled at scrape time (self._q is
        # swapped atomically; len() on a stale generation is harmless),
        # counters on the mutation paths
        self._m_requests = obs_metrics.counter(
            "edl_data_requests_total", "dispatcher RPCs served, by method"
        )
        self._m_timeouts = obs_metrics.counter(
            "edl_data_task_timeouts_total", "pending tasks re-queued on worker timeout"
        )
        self._m_strikes = obs_metrics.counter(
            "edl_data_task_strikes_total", "task failure strikes (timeout or reported)"
        )
        self._m_drain_requeues = obs_metrics.counter(
            "edl_data_drain_requeues_total",
            "in-flight tasks re-queued because their worker drained",
        )
        self._preempt_watch = None
        self._drained_pods: set = set()
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_data_todo_tasks", "tasks waiting for a worker",
             lambda: len(self._q.todo)),
            ("edl_data_pending_tasks", "tasks leased to workers",
             lambda: len(self._q.pending)),
            ("edl_data_done_tasks", "tasks completed this epoch",
             lambda: len(self._q.done)),
            ("edl_data_failed_tasks", "tasks dropped after failure_max strikes",
             lambda: len(self._q.failed)),
            ("edl_data_epoch_seq", "current dispatch epoch",
             lambda: self._epoch),
        ))
        # one stable reference: bound-method attribute access mints a new
        # object each time, and release_health compares by identity
        self._health_fn = self.state
        self._obs = obs_http.start_from_env(
            "dispatcher", health_fn=self._health_fn
        )

    @property
    def endpoint(self) -> str:
        """Routable address for publication in the store: wildcard binds
        advertise this host's real IP so cross-host workers can connect."""
        from edl_tpu.utils.net import get_host_ip

        host = self._host if self._host not in ("", "0.0.0.0") else get_host_ip()
        return "%s:%d" % (host, self.port)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DataDispatcher":
        if self._obs is not None and self._registry is not None:
            # advertise the scrape target in the job's obs keyspace so
            # edl-top finds the dispatcher from the store alone
            try:
                self._registry.set_permanent(
                    obs_http.OBS_SERVICE,
                    "dispatcher.d%d" % self.port,
                    obs_http.endpoint_payload(self._obs.endpoint),
                )
            except Exception as exc:  # noqa: BLE001 — fire-and-forget
                logger.warning("dispatcher obs endpoint not registered: %s", exc)
        if self._registry is not None:
            # health plane: a launcher's preemption notice lands here as a
            # preempt/{pod_id} key — requeue that pod's in-flight tasks
            # NOW instead of letting them ride out task_timeout
            try:
                from edl_tpu.cluster.contract import PREEMPT_SERVICE

                self._preempt_watch = self._registry.watch_service(
                    PREEMPT_SERVICE, on_change=self._on_preempt
                )
            except Exception as exc:  # noqa: BLE001 — optional integration
                logger.warning("dispatcher preempt watch not armed: %s", exc)
        for target, name in (
            (self._accept_loop, "dispatch-accept"),
            (self._timeout_loop, "dispatch-timeout"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _on_preempt(self, snapshot) -> None:
        """Store-watch side of graceful drain: workers carry their pod id
        in their worker-id by convention ("worker-{rank}-{pod_id}"), so a
        noticed pod's in-flight tasks are identified by substring."""
        for pod_id in set(snapshot) - self._drained_pods:
            self._drained_pods.add(pod_id)
            n = self.drain_worker(pod_id, substring=True)
            if n:
                logger.info(
                    "preempt notice for pod %s: re-queued %d in-flight "
                    "task(s)", pod_id[:8], n,
                )

    def drain_worker(self, worker: str, substring: bool = False) -> int:
        """Re-queue a draining worker's in-flight tasks at their reported
        offsets — immediately, without a failure strike (drain is a clean
        departure, not a fault). Returns the number of tasks re-queued.
        ``substring=True`` matches any worker id containing ``worker``
        (how a pod-level notice fans out to that pod's workers)."""
        if not worker:
            return 0
        if substring:
            match = lambda t: worker in t.worker  # noqa: E731
        else:
            match = lambda t: t.worker == worker  # noqa: E731
        with self._lock:
            hits = [t for t in self._q.pending.values() if match(t)]
            for task in hits:
                del self._q.pending[task.task_id]
                self._m_drain_requeues.inc()
                task.worker, task.deadline = "", 0.0
                # resume offset survives: start_record rides next_record
                # through DataTask.public(), so the successor worker picks
                # up at the drained worker's last report
                self._q.todo.insert(0, task)
            if hits:
                logger.info(
                    "drained worker %r: re-queued %d task(s)", worker, len(hits)
                )
                # flight-record the requeue: edl-timeline orders it between
                # the preempt notice and the successor's first pull
                obs_events.record(
                    "data_drain_requeue", fsync=True,
                    worker=worker, requeued=len(hits),
                )
                self._snapshot()
            return len(hits)

    def stop(self) -> None:
        self._stop.set()
        if self._preempt_watch is not None:
            try:
                self._preempt_watch.cancel()
            except Exception:  # noqa: BLE001
                pass
        self._obs_gauges.release()  # don't pin this instance in the registry
        obs_http.release_health("dispatcher", self._health_fn)
        try:
            self._listener.close()
        except OSError:
            pass

    # -- state machine ------------------------------------------------------

    def add_dataset(self, files: List[str]) -> int:
        with self._lock:
            self._files = list(files)
            self._fill_epoch()
            self._snapshot()
            return len(self._files)

    def _fill_epoch(self) -> None:
        self._q = _Queues()
        order = list(range(len(self._files)))
        if self._shuffle_seed is not None:
            import random

            random.Random(
                self._shuffle_seed * 1_000_003 + self._epoch
            ).shuffle(order)
        for idx in order:
            self._q.todo.append(
                DataTask(
                    task_id=self._next_task_id,
                    file_idx=idx,
                    path=self._files[idx],
                )
            )
            self._next_task_id += 1

    def new_epoch(self, epoch: int) -> bool:
        """Advance to ``epoch`` and re-queue every file; requests for the
        current or an older epoch are idempotent no-ops."""
        with self._lock:
            if epoch > self._epoch:
                self._epoch = epoch
                self._fill_epoch()
                obs_events.record(
                    "data_epoch", fsync=True, epoch=epoch,
                    files=len(self._files),
                )
                self._snapshot()
                return True
            return False

    def get_task(self, worker: str) -> dict:
        with self._lock:
            if self._q.todo:
                task = self._q.todo.pop(0)
                task.worker = worker
                task.deadline = time.time() + self._task_timeout
                self._q.pending[task.task_id] = task
                self._snapshot()
                return {"task": task.public(), "epoch": self._epoch}
            if self._q.pending:
                return {"wait": True, "epoch": self._epoch}
            return {"epoch_done": True, "epoch": self._epoch}

    def task_done(self, worker: str, task_id: int) -> bool:
        with self._lock:
            task = self._q.pending.pop(task_id, None)
            if task is None or (task.worker and task.worker != worker):
                if task is not None:  # late ack from a timed-out worker
                    self._q.pending[task_id] = task
                return False
            self._q.done[task_id] = task
            self._snapshot()
            return True

    def task_failed(self, worker: str, task_id: int) -> bool:
        with self._lock:
            task = self._q.pending.pop(task_id, None)
            if task is None:
                return False
            self._strike(task, "worker %s reported failure" % worker)
            self._snapshot()
            return True

    def _strike(self, task: DataTask, why: str) -> None:
        self._m_strikes.inc()
        obs_events.record(
            "data_task_strike", task=task.task_id, path=task.path,
            failures=task.failures + 1, why=why,
        )
        task.failures += 1
        task.worker, task.deadline = "", 0.0
        if task.failures >= self._failure_max:
            logger.error(
                "task %d (%s) failed %d times, dropping: %s",
                task.task_id, task.path, task.failures, why,
            )
            self._q.failed[task.task_id] = task
        else:
            logger.warning(
                "task %d (%s) re-queued (%d strikes): %s",
                task.task_id, task.path, task.failures, why,
            )
            self._q.todo.append(task)

    def report(self, worker: str, task_id: int, next_record: int) -> bool:
        """Progress heartbeat: extends the deadline, records the offset so a
        re-queued task resumes mid-file (exact-resume semantics)."""
        with self._lock:
            task = self._q.pending.get(task_id)
            if task is None or (task.worker and task.worker != worker):
                return False
            task.next_record = max(task.next_record, next_record)
            task.deadline = time.time() + self._task_timeout
            self._dirty_reports = True  # flushed by the timeout loop
            return True

    def state(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "todo": len(self._q.todo),
                "pending": len(self._q.pending),
                "done": len(self._q.done),
                "failed": len(self._q.failed),
                "files": len(self._files),
            }

    def progress(self) -> dict:
        """Export the epoch's per-file position — the payload of an atomic
        model+data checkpoint (:class:`edl_tpu.data.DataCheckpoint`).
        Offsets are the *reported* positions, so a restore replays at most
        the records a worker consumed after its last report."""
        with self._lock:
            offsets = {}
            for t in list(self._q.pending.values()) + self._q.todo:
                pos = max(t.start_record, t.next_record)
                if pos > 0:
                    offsets[str(t.file_idx)] = pos
            return {
                "epoch": self._epoch,
                "offsets": offsets,
                "done": sorted(t.file_idx for t in self._q.done.values()),
            }

    def set_progress(self, epoch: int, offsets: Dict[str, int], done: List[int]) -> bool:
        """Restore the epoch position from a checkpoint: the inverse of
        :meth:`progress`. Rebuilds the queues so files in ``done`` are not
        re-dispatched and every other file resumes at its offset — run by
        the leader after restoring a model checkpoint, so data and model
        state roll back to the SAME instant (stop-resume exactness)."""
        with self._lock:
            self._epoch = epoch
            self._fill_epoch()
            done_set = set(done)
            todo = []
            for t in self._q.todo:
                if t.file_idx in done_set:
                    self._q.done[t.task_id] = t
                else:
                    t.start_record = int(offsets.get(str(t.file_idx), 0))
                    t.next_record = t.start_record
                    todo.append(t)
            self._q.todo = todo
            self._snapshot()
            return True

    def _timeout_loop(self) -> None:
        while not self._stop.wait(min(1.0, self._task_timeout / 4)):
            now = time.time()
            with self._lock:
                expired = [
                    t for t in self._q.pending.values() if t.deadline < now
                ]
                for task in expired:
                    del self._q.pending[task.task_id]
                    self._m_timeouts.inc()
                    self._strike(task, "worker %s timed out" % task.worker)
                # epoch shard-cursor snapshot on a cadence: a dispatcher
                # restart then resumes every pending file from its last
                # REPORTED record offset instead of replaying the epoch
                # tail from each file's start (report() itself never
                # snapshots — one store put per progress heartbeat would
                # swamp the control plane)
                flush_cursors = (
                    self._dirty_reports
                    and now - self._last_cursor_snap >= self._snapshot_every
                )
                if flush_cursors:
                    self._dirty_reports = False
                    self._last_cursor_snap = now
                if expired or flush_cursors:
                    self._snapshot()

    # -- snapshot / recover -------------------------------------------------

    _SNAP_SERVICE = "data_master"

    def _snapshot(self) -> None:
        if self._registry is None:
            return
        state = {
            "epoch": self._epoch,
            "files": self._files,
            "next_task_id": self._next_task_id,
            "todo": [vars(t) for t in self._q.todo],
            # pending tasks are deliberately saved as todo: after a master
            # restart their workers' acks won't match anyway
            "requeue": [vars(t) for t in self._q.pending.values()],
            "done": [vars(t) for t in self._q.done.values()],
            "failed": [vars(t) for t in self._q.failed.values()],
        }
        try:
            self._registry.set_permanent(
                self._SNAP_SERVICE, "state", json.dumps(state).encode()
            )
        except Exception as exc:  # noqa: BLE001 — snapshot is best-effort
            logger.warning("state snapshot failed: %s", exc)

    def _recover(self) -> None:
        meta = self._registry.get_server(self._SNAP_SERVICE, "state")
        if meta is None:
            return
        state = json.loads(meta.value.decode())

        def mk(d):
            t = DataTask(**{k: d[k] for k in (
                "task_id", "file_idx", "path", "start_record",
                "next_record", "failures")})
            return t

        self._epoch = state["epoch"]
        self._files = state["files"]
        self._next_task_id = state["next_task_id"]
        self._q = _Queues()
        self._q.todo = [mk(d) for d in state["todo"]] + [
            mk(d) for d in state["requeue"]
        ]
        self._q.done = {d["task_id"]: mk(d) for d in state["done"]}
        self._q.failed = {d["task_id"]: mk(d) for d in state["failed"]}
        logger.info(
            "recovered dispatcher state: epoch %d, %d todo, %d done",
            self._epoch, len(self._q.todo), len(self._q.done),
        )

    # -- server -------------------------------------------------------------

    _METHODS = {
        "add_dataset": lambda self, req: {"n": self.add_dataset(req["files"])},
        "new_epoch": lambda self, req: {"ok_epoch": self.new_epoch(req["epoch"])},
        "get_task": lambda self, req: self.get_task(req.get("w", "")),
        "task_done": lambda self, req: {
            "acked": self.task_done(req.get("w", ""), req["t"])
        },
        "task_failed": lambda self, req: {
            "acked": self.task_failed(req.get("w", ""), req["t"])
        },
        "report": lambda self, req: {
            "acked": self.report(req.get("w", ""), req["t"], req["rec"])
        },
        "drain_worker": lambda self, req: {
            "requeued": self.drain_worker(req.get("w", ""))
        },
        "state": lambda self, req: self.state(),
        "progress": lambda self, req: self.progress(),
        "set_progress": lambda self, req: {
            "acked": self.set_progress(
                req["epoch"], req.get("offsets", {}), req.get("done", [])
            )
        },
        "ping": lambda self, req: {},
    }

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(sock,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, sock: socket.socket) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                req = read_frame_blocking(sock)
                rid = req.get("i", 0)
                if _FP_TASK.armed:
                    _FP_TASK.fire(method=str(req.get("m")))  # ChaosDrop resets conn
                handler = self._METHODS.get(req.get("m"))
                # unknown methods share one sentinel label: the method
                # string is client data, not a bounded series key
                self._m_requests.inc(
                    method=str(req.get("m")) if handler else "<unknown>"
                )
                if handler is None:
                    resp = {
                        "i": rid, "ok": False,
                        "err": {"etype": "EdlInternalError",
                                "detail": "unknown method %r" % req.get("m")},
                    }
                else:
                    try:
                        # per-method server latency + caller-linked span
                        # when the request carried a "tc" trace context
                        with server_span(
                            str(req.get("m")), req.get(TC_FIELD),
                            server="data",
                        ):
                            resp = {"i": rid, "ok": True, **handler(self, req)}
                    except Exception as exc:  # noqa: BLE001
                        logger.exception("dispatch %s failed", req.get("m"))
                        resp = {"i": rid, "ok": False,
                                "err": serialize_exception(exc)}
                sock.sendall(pack_frame(resp))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass


class DispatcherClient:
    """Blocking client for the dispatcher (Python or native C++ server)."""

    def __init__(self, endpoint: str, worker_id: str, timeout: float = 30.0) -> None:
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.worker_id = worker_id
        self._next = 0

    def _call(self, method: str, **params) -> dict:
        self._next += 1
        payload = {"i": self._next, "m": method, "w": self.worker_id, **params}
        # trace propagation: one attr load disarmed (wire discipline)
        if _TC.armed and TC_FIELD not in payload:
            tc = obs_trace.inject()
            if tc is not None:
                payload[TC_FIELD] = tc
        self._sock.sendall(pack_frame(payload))
        resp = read_frame_blocking(self._sock)
        if not resp.get("ok"):
            raise ConnectionError(
                "dispatcher %s failed: %s" % (method, resp.get("err"))
            )
        return resp

    def add_dataset(self, files: List[str]) -> int:
        return self._call("add_dataset", files=list(files))["n"]

    def new_epoch(self, epoch: int) -> bool:
        return self._call("new_epoch", epoch=epoch)["ok_epoch"]

    def get_task(self) -> dict:
        return self._call("get_task")

    def task_done(self, task_id: int) -> bool:
        return self._call("task_done", t=task_id)["acked"]

    def task_failed(self, task_id: int) -> bool:
        return self._call("task_failed", t=task_id)["acked"]

    def report(self, task_id: int, next_record: int) -> bool:
        return self._call("report", t=task_id, rec=next_record)["acked"]

    def drain_worker(self) -> int:
        """Graceful drain: hand this worker's in-flight tasks back NOW (no
        timeout wait, no failure strike); returns how many were requeued."""
        return self._call("drain_worker")["requeued"]

    def progress(self) -> dict:
        resp = self._call("progress")
        return {
            "epoch": resp["epoch"],
            "offsets": {int(k): v for k, v in resp.get("offsets", {}).items()},
            "done": list(resp.get("done", [])),
        }

    def set_progress(self, epoch: int, offsets: Dict[int, int], done) -> bool:
        return self._call(
            "set_progress",
            epoch=epoch,
            offsets={str(k): int(v) for k, v in offsets.items()},
            done=[int(x) for x in done],
        )["acked"]

    def state(self) -> dict:
        resp = self._call("state")
        # strip protocol framing (request id / ok flag): callers get the
        # queue-state payload only, like every other client method
        return {k: v for k, v in resp.items() if k not in ("i", "ok")}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# -- discovery ---------------------------------------------------------------

DISPATCH_SERVICE = "data/dispatcher"


def publish_dispatcher(registry, endpoint: str, ttl: float = 5.0):
    """Leader-side: advertise a live dispatcher endpoint in the store.

    LEASED on purpose — a dead leader's entry expires instead of sending
    the next stage's workers to a closed port. Returns the Registration
    (keep it referenced; its keeper renews the lease)."""
    return registry.register(DISPATCH_SERVICE, endpoint, b"1", ttl=ttl)


def discover_dispatcher(
    registry, timeout: float = 60.0, probe_timeout: float = 2.0
) -> str:
    """Worker-side: find a LIVE dispatcher endpoint.

    Every advertised endpoint is liveness-probed (connect + ``state``)
    before adoption: a stage transition can leave the dead leader's
    endpoint in the registry until its lease expires, and blindly taking
    ``entries[0]`` crash-loops the new stage's workers on
    ConnectionRefused (observed under churn: rank 0 then waits out the
    full jax.distributed shutdown-barrier timeout and the job dies)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for meta in registry.get_service(DISPATCH_SERVICE):
            probe = None
            try:
                probe = DispatcherClient(
                    meta.name, "probe", timeout=probe_timeout
                )
                probe.state()
                return meta.name
            except (OSError, EdlError, WireError):
                continue
            finally:
                if probe is not None:
                    probe.close()
        time.sleep(0.1)
    raise TimeoutError(
        "no live dispatcher endpoint within %.0fs" % timeout
    )
