from edl_tpu.models.mlp import MLP, LinearRegression
from edl_tpu.models.resnet import ResNet, ResNet50_vd

__all__ = ["MLP", "LinearRegression", "ResNet", "ResNet50_vd"]
