"""Decoder-only Transformer LM — the long-context flagship.

Net-new model family versus the reference (its largest workload is
ResNet50/ERNIE fine-tune; SURVEY §5 notes long-context is absent), built
TPU-first:

- pre-norm blocks with RMSNorm, RoPE positions, SwiGLU MLP — all
  large-matmul-dominated so the MXU stays busy; bf16 compute, fp32 params;
- attention is pluggable: the Pallas flash kernel locally, or ring
  attention over the ``sp`` mesh axis for sequences longer than one
  device's HBM (``edl_tpu.parallel.ring``);
- ``remat=True`` wraps each block in ``jax.checkpoint``
  (``nn.remat``) — activation recompute, the TPU equivalent of the
  reference's recompute flag (train_with_fleet.py:104, 323-325);
- tensor-parallel sharding rules for the weights live in
  ``edl_tpu.parallel.sharding_rules`` (Megatron-style column/row splits
  expressed as PartitionSpecs; XLA inserts the tp collectives).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from edl_tpu.ops.attention import attention

AttentionFn = Callable[..., jax.Array]  # (q, k, v, causal=...) -> out


def _supports_gqa(fn) -> bool:
    """True when ``fn`` (possibly wrapped in functools.partial layers —
    the repo's standard wiring for ring attention) declares it accepts
    grouped k/v via a ``supports_gqa`` attribute."""
    while isinstance(fn, partial):
        if getattr(fn, "supports_gqa", False):
            return True
        fn = fn.func
    return getattr(fn, "supports_gqa", False)

NEG_INF_DECODE = -1e30  # mask value for cache positions past the index


class RMSNorm(nn.Module):
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon
        )
        return (norm * scale).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, base: float = 10000.0) -> jax.Array:
    """Rotary position embedding; x: [B, T, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None, None].astype(jnp.float32) * freq  # B T 1 half
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class Attention(nn.Module):
    """Multi-head / grouped-query attention.

    ``num_kv_heads`` < ``num_heads`` is GQA (Ainslie et al. 2023): K/V
    project to fewer heads, cutting KV projection params and FLOPs by
    ``num_heads/num_kv_heads``; ``num_kv_heads=1`` is MQA; ``None``
    (default) is classic MHA. The default dispatch's Pallas kernels are
    GQA-AWARE (ops/attention.py: grouped k/v read via index mapping, no
    materialized repeat, dk/dv folded back to the grouped width), so on
    the flash/flash2 routes training keeps the grouped activation bytes
    too; the dense "ref" route (below the measured flash crossover) and
    ragged fallbacks still broadcast in-graph. A custom ``attention_fn``
    sees broadcast MHA shapes UNLESS it (or the function under its
    functools.partial wrapping) declares ``supports_gqa = True`` — ring
    and ulysses attention both do, and then receive grouped k/v (the
    ring's rotating shards and ulysses' kv collectives shrink by the
    group factor).
    With tensor parallelism the grouped projections replicate when
    ``num_kv_heads`` doesn't divide ``tp`` (see ``shard_params_by_rules``)
    while q/o keep their Megatron split.
    """

    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    num_kv_heads: Optional[int] = None
    decode: bool = False       # autoregressive mode: KV cache in "cache"
    max_decode_len: int = 2048

    @nn.compact
    def __call__(self, x, positions):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        kv_heads = (
            self.num_kv_heads if self.num_kv_heads is not None
            else self.num_heads
        )
        if kv_heads < 1 or self.num_heads % kv_heads:
            raise ValueError(
                "num_kv_heads (%d) must be a positive divisor of "
                "num_heads (%d)" % (kv_heads, self.num_heads)
            )
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=self.dtype)
        q = dense(features=(self.num_heads, head_dim), name="q")(x)
        k = dense(features=(kv_heads, head_dim), name="k")(x)
        v = dense(features=(kv_heads, head_dim), name="v")(x)
        q = rope(q, positions)
        k = rope(k, positions)
        if self.decode:
            out = self._decode_step(q, k, v, kv_heads, head_dim)
        else:
            # [B, T, H, D] -> [B, H, T, D]
            q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
            if (
                kv_heads != self.num_heads
                and self.attention_fn is not None
                and not _supports_gqa(self.attention_fn)
            ):
                # custom attention fns see plain MHA shapes unless they
                # declare supports_gqa (ring attention does: grouped k/v
                # cut its ppermute volume by the group factor). The
                # DEFAULT dispatch accepts grouped k/v natively.
                group = self.num_heads // kv_heads
                k, v = (jnp.repeat(t, group, axis=1) for t in (k, v))
            attn = self.attention_fn or attention
            out = attn(q, k, v, causal=True)
            out = jnp.swapaxes(out, 1, 2)
        return nn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), use_bias=False,
            dtype=self.dtype, name="o",
        )(out)

    def _decode_step(self, q, k, v, kv_heads: int, head_dim: int):
        """Cached autoregressive attention for T >= 1 new tokens: insert
        their K/V into the cache at the running index (GROUPED width —
        the num_heads/num_kv_heads cache-byte saving is real here, and
        the cache is stored in the model dtype, bf16 for the default
        config) and attend each query against its causal prefix. T > 1
        is the PREFILL path: the whole prompt lands in one MXU-friendly
        pass. Static shapes throughout: the cache is ``max_decode_len``
        long and masked by index + offset, so generate() compiles one
        prefill program and one single-token step."""
        b, t = q.shape[0], q.shape[1]
        cache_k = self.variable(
            "cache", "cached_key",
            jnp.zeros, (b, self.max_decode_len, kv_heads, head_dim),
            self.dtype,
        )
        cache_v = self.variable(
            "cache", "cached_value",
            jnp.zeros, (b, self.max_decode_len, kv_heads, head_dim),
            self.dtype,
        )
        index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        i = index.value
        cache_k.value = jax.lax.dynamic_update_slice(
            cache_k.value, k.astype(self.dtype), (0, i, 0, 0)
        )
        cache_v.value = jax.lax.dynamic_update_slice(
            cache_v.value, v.astype(self.dtype), (0, i, 0, 0)
        )
        index.value = i + t

        group = self.num_heads // kv_heads
        # [B, T, H, D] -> [B, T, KV, G, D]; score math in fp32
        qg = q.astype(jnp.float32).reshape(b, t, kv_heads, group, head_dim)
        scores = jnp.einsum(
            "btkgd,blkd->bkgtl",
            qg * (head_dim ** -0.5),
            cache_k.value.astype(jnp.float32),
        )
        # query at offset o (position i+o) sees cache slots l <= i+o
        valid = (
            jnp.arange(self.max_decode_len)[None, :]
            <= i + jnp.arange(t)[:, None]
        )  # [T, L]
        scores = jnp.where(valid[None, None, None], scores, NEG_INF_DECODE)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bkgtl,blkd->btkgd", probs, cache_v.value.astype(jnp.float32)
        )
        return out.reshape(b, t, self.num_heads, head_dim).astype(self.dtype)


class SwiGLU(nn.Module):
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dense = partial(nn.Dense, use_bias=False, dtype=self.dtype)
        gate = nn.silu(dense(self.d_ff, name="gate")(x))
        up = dense(self.d_ff, name="up")(x)
        return dense(x.shape[-1], name="down")(gate * up)


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    num_experts: int = 0  # >0: expert-parallel MoE FFN instead of SwiGLU
    num_kv_heads: Optional[int] = None
    decode: bool = False
    max_decode_len: int = 2048

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(
            self.num_heads, self.dtype, self.attention_fn,
            num_kv_heads=self.num_kv_heads, decode=self.decode,
            max_decode_len=self.max_decode_len, name="attn",
        )(RMSNorm(name="ln1")(x), positions)
        h = RMSNorm(name="ln2")(x)
        if self.num_experts > 0:
            from edl_tpu.models.moe import SwitchMoE

            ff = SwitchMoE(
                num_experts=self.num_experts, d_ff=self.d_ff,
                dtype=self.dtype, name="moe",
            )(h)
        else:
            ff = SwiGLU(self.d_ff, self.dtype, name="mlp")(h)
        return x + ff


def _remat_policy(name: Optional[str]):
    """Resolve a TransformerLM.remat_policy string to a jax.checkpoint
    policy. ``"save_flash"`` keeps the attention kernel's forward
    products (out + lse, tagged by ``checkpoint_name`` inside the
    custom_vjp fwd — ops/attention.py::_name_residuals) so the backward
    consumes them instead of re-running the forward kernel: O(B*T*D)
    extra HBM per layer buys back a full flash forward per layer per
    step. ``"save_flash_qkv"`` additionally skips the q/k/v projection
    recompute. ``None``/"full" is classic recompute-everything."""
    if name in (None, "full"):
        return None
    if name == "save_flash":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        )
    if name == "save_flash_qkv":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "flash_qkv"
        )
    raise ValueError("unknown remat_policy %r" % (name,))


class LMHead(nn.Module):
    """Vocabulary projection with fp32 logits from input-dtype operands.

    The old ``nn.Dense(dtype=float32)`` upcast x AND the kernel to fp32
    before the matmul — on the v5e MXU that runs at a fraction of the
    bf16 rate, and at vocab 32k the head is one of the largest matmuls
    in the model. Here the multiply runs in the activation dtype (bf16
    in training) with fp32 ACCUMULATION via preferred_element_type, so
    the softmax still sees fp32 logits. Param path/shape match the old
    nn.Dense exactly (``lm_head/kernel``) — checkpoints stay loadable.
    """

    vocab_size: int

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.vocab_size),
        )
        return jax.lax.dot_general(
            x, kernel.astype(x.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    d_model: int = 512
    num_heads: int = 8
    num_layers: int = 6
    d_ff: int = 1408
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # policy under remat=True: "save_flash" (default) saves the attention
    # forward's out+lse so the backward never re-runs the kernel;
    # "save_flash_qkv" also saves q/k/v; "full"/None recomputes everything
    remat_policy: Optional[str] = "save_flash"
    attention_fn: Optional[AttentionFn] = None
    num_experts: int = 0   # with moe_every: MoE width of the routed blocks
    moe_every: int = 2     # every Nth block is MoE when num_experts > 0
    num_kv_heads: Optional[int] = None  # < num_heads = GQA; 1 = MQA
    decode: bool = False                # KV-cached autoregressive mode
    max_decode_len: int = 2048

    @nn.compact
    def __call__(self, tokens, positions=None):
        x = nn.Embed(
            self.vocab_size, self.d_model,
            dtype=self.dtype, name="embed",
        )(tokens)
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None, :], tokens.shape
            )
        block = Block
        if self.remat:
            block = nn.remat(
                Block, static_argnums=(),
                policy=_remat_policy(self.remat_policy),
            )
        for i in range(self.num_layers):
            moe = (
                self.num_experts
                if self.num_experts > 0 and (i + 1) % self.moe_every == 0
                else 0
            )
            x = block(
                self.num_heads, self.d_ff, self.dtype, self.attention_fn,
                moe, self.num_kv_heads, self.decode, self.max_decode_len,
                name="layer_%d" % i,
            )(x, positions)
        x = RMSNorm(name="ln_f")(x)
        logits = LMHead(self.vocab_size, name="lm_head")(x)
        return logits
