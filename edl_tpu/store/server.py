"""Event-loop TCP server exposing :class:`StoreState` over the wire protocol.

Single-threaded, selector-driven (the shape of the reference's epoll balance
server, python/edl/distill/redis/balance_server.py:39-216, applied to the
coordination store): every connection is nonblocking, frames are decoded
incrementally, watch events are pushed as server-initiated frames.

Run standalone as ``python -m edl_tpu.store.server --port 2379`` (the role
``scripts/download_etcd.sh`` + an external etcd daemon play for the
reference), or embedded in-process via ``StoreServer(port=0).start()`` —
the launcher embeds one in the leader pod.

Wire methods (see rpc/wire.py for framing):
  put(k, v, l?) / put_absent / cas(k, er, v, l?) / get(k) / range(p) /
  del(k) / del_range(p) / lease_grant(ttl) / lease_keepalive(l) /
  lease_revoke(l) / watch(p, r?) / unwatch(w) / ping / state /
  repl_sync(e, ep, prio) / repl_status / repl_fence(e)

Control-plane HA (see DESIGN.md "Control-plane HA"): ``follow=`` turns a
server into a **warm standby** — it bootstraps from the primary's
streamed snapshot (``repl_sync``), tails journal entries live (``rl``
push frames, replication lag exported as gauges), and on primary death
promotes itself: bump the persisted fencing epoch, reset lease clocks,
take slot 0 in the ``/store/endpoints/`` keyspace, and fence every other
known endpoint so a resurrected stale primary refuses service.
"""

from __future__ import annotations

import argparse
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple, Union

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.rpc.wire import (
    TC_FIELD,
    FrameReader,
    WireError,
    pack_frame,
    server_span,
)
from edl_tpu.store import replica as replica_mod
from edl_tpu.store.kv import Event, StoreState
from edl_tpu.utils.exceptions import (
    EdlCompactedError,
    EdlFencedError,
    EdlNotPrimaryError,
    EdlStoreError,
    serialize_exception,
)
from edl_tpu.utils.log import get_logger

logger = get_logger("store.server")

_FP_DISPATCH = _fault_point(
    "store.server.dispatch",
    "one store RPC server-side: delay (slow tail) or drop (conn reset)",
)
_FP_WAL = _fault_point(
    "store.server.wal", "journal append: delay (slow disk) before fsync"
)
_FP_REPL_SYNC = _fault_point(
    "store.replication.sync",
    "standby bootstrap dial: delay or drop (primary looks unreachable)",
)
_FP_REPL_STREAM = _fault_point(
    "store.replication.stream",
    "one replicated journal batch primary->standby: delay or drop "
    "(the standby sees a dead link and re-syncs)",
)

_LEASE_SWEEP_INTERVAL = 0.2
_COMPACT_EVERY = 10_000  # journal entries between snapshots
# semi-sync replication: how long a client ack may be held waiting for
# every live standby to apply+journal the write before the primary
# degrades that ONE commit to async (metered + alertable). <= 0 turns
# semi-sync off entirely (the pre-shard async behavior).
_REPL_SYNC_TIMEOUT = float(os.environ.get("EDL_STORE_REPL_SYNC_TIMEOUT", "0.5"))
# max replica staleness: with a replica_dir, compaction (and thus the
# replicated snapshot) is also triggered on a timer
_REPLICA_INTERVAL = float(os.environ.get("EDL_STORE_REPLICA_INTERVAL", "30"))
_REPL_HEARTBEAT = 0.25  # primary -> standby keepalive (also carries lag data)
_REPL_DIAL_INTERVAL = 0.25  # min pause between standby reconnect attempts
_FENCE_INTERVAL = 1.0  # promoted primary's fence-campaign pass interval

# the only methods a standby (or a fenced primary, minus repl_sync)
# answers: liveness probes and the replication control plane
_STANDBY_OK = ("ping", "state", "repl_status", "repl_fence")


class _Conn:
    __slots__ = (
        "sock", "reader", "out", "watches", "addr", "closed", "repl",
        "repl_tx", "repl_ack",
    )

    def __init__(self, sock: socket.socket, addr) -> None:
        self.sock = sock
        self.reader = FrameReader()
        self.out = bytearray()
        # wid -> (prefix, high-water revision): fan-out only delivers
        # events NEWER than the registration revision — the backlog push
        # already covered everything at-or-below it, so a watch
        # registered while a semi-sync commit is still held can never
        # see that commit's events twice
        self.watches: Dict[int, Tuple[str, int]] = {}
        self.addr = addr
        self.closed = False
        self.repl = False  # a replication subscriber (a standby's link)
        # async-replication loss-window accounting: cumulative journal
        # bytes streamed to this subscriber, and the highest cumulative
        # count it has echoed back (repl_ack frames)
        self.repl_tx = 0
        self.repl_ack = 0


class _SyncWait:
    """One semi-sync GROUP of commits held open: the client responses
    (and the watch fan-out of their events) release only once every
    target standby has echoed a ``repl_ack`` covering the batch — or
    the bounded degrade deadline passes. Waits release strictly FIFO so
    watchers observe events in revision order."""

    __slots__ = ("completions", "first_rev", "targets", "deadline")

    def __init__(self, completions, first_rev, targets, deadline) -> None:
        # [(conn|None, resp|None, events)] — conn None for
        # server-initiated commits (lease sweeps, endpoint publication)
        self.completions = completions
        self.first_rev = first_rev  # lowest event revision held here
        self.targets = targets  # [(subscriber _Conn, cumulative tx target)]
        self.deadline = deadline


class StoreServer:
    """``data_dir`` turns on durability (≙ the external etcd daemon's disk
    state in the reference): state is recovered from ``snapshot.bin`` +
    ``wal.bin`` at startup, every mutation is journaled (flush+fsync — the
    control plane is low-rate), and the journal is compacted into a fresh
    snapshot every ``_COMPACT_EVERY`` entries and on clean stop. A store
    killed -9 and restarted on the same ``data_dir`` loses at most nothing:
    clients reconnect, watches resume from their last revision (older
    resume points get a compaction error and resync), leases restart with
    a full fresh TTL (the store can't know how long it was down)."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        data_dir: Optional[str] = None,
        replica_dir: Optional[str] = None,
        follow: Union[str, Sequence[str], None] = None,
        priority: int = 1,
        failover_grace: float = 2.0,
        advertise: Optional[str] = None,
        repl_sync_timeout: Optional[float] = None,
        name: str = "store",
    ) -> None:
        from edl_tpu.chaos.plane import arm_from_env

        arm_from_env("store")  # no-op without EDL_CHAOS in the env
        self._host = host
        self._state = StoreState()
        self._data_dir = data_dir
        # ``name`` labels this server's RPC histograms — a sharded
        # deployment names each shard (store-0, store-1, ...) so the
        # trace plane's edl_rpc_server_seconds attributes tail latency
        # per shard, not per blurred fleet
        self.name = name
        # semi-sync replication (DESIGN.md "Sharded control plane"):
        # with a positive timeout, a mutation's ack is HELD until every
        # live replication subscriber has applied+journaled it (its
        # repl_ack covers the batch) — the async loss window the
        # edl_store_repl_unacked_bytes gauge measures drains to zero
        # before the client hears "ok". The bounded escape hatch
        # degrades one commit to async after the timeout, metered.
        self._repl_sync_timeout = (
            _REPL_SYNC_TIMEOUT if repl_sync_timeout is None
            else float(repl_sync_timeout)
        )
        self._sync_q: deque = deque()  # FIFO of held _SyncWait batches
        self._sync_last_warn = 0.0
        # group-commit pass buffer: (conn, resp, events, entries) of
        # every mutation dispatched in the current event-loop pass,
        # journaled+replicated+released together by _flush_commits().
        # EDL_STORE_GROUP_COMMIT=0 restores the per-write fsync of the
        # pre-shard store (the store_bench --baseline lane; ~5x slower
        # under pipelined write load on the CPU rig)
        self._txn_buf: List[tuple] = []
        self._group_commit = (
            os.environ.get("EDL_STORE_GROUP_COMMIT", "1") != "0"
        )
        # MVCC released-revision reads (DESIGN.md "Consistency model"):
        # get/range answer from the last RELEASED revision by default, so
        # a reader can never observe a commit still held in the semi-sync
        # window (it could die with this primary). EDL_STORE_MVCC=0
        # restores the pre-MVCC applied-state reads — the chaos plane's
        # red drill uses it to reproduce the stale-read anomaly.
        self._mvcc = os.environ.get("EDL_STORE_MVCC", "1") != "0"
        # how many revisions behind the released horizon version chains
        # retain — the budget for pinned snapshot reads and watch resume
        self._mvcc_retain = max(
            1, int(os.environ.get("EDL_STORE_MVCC_RETAIN", "4096"))
        )
        self._mvcc_last_compact = 0.0
        # standby read serving: a standby answers get/range/watch at its
        # applied (= released: it holds no commit queues) revision when
        # the client opted in ("rm": "s"), refusing — so the client falls
        # through to the primary — once its replication lag exceeds this
        self._standby_max_lag = max(
            0, int(os.environ.get("EDL_STORE_STANDBY_MAX_LAG", "1024"))
        )
        self._standby_reads_n = 0  # cumulative, exposed via repl_status
        # -- HA role (see module docstring) --------------------------------
        # ``follow`` makes this server a warm standby of the listed
        # primary endpoint(s); ``priority`` orders promotion among
        # standbys (1 = first in line); ``failover_grace`` is how long the
        # replication link must stay dead before promotion is considered.
        self._follow = replica_mod.parse_endpoints(follow)
        self.role = "standby" if self._follow else "primary"
        self.priority = 0 if self.role == "primary" else max(1, int(priority))
        self._failover_grace = max(0.1, float(failover_grace))
        self._advertise = advertise  # resolved after the bind (needs port)
        self._fenced_by: Optional[int] = None
        self._crash = False  # kill(): skip the clean-stop compaction
        self._repl_sock: Optional[socket.socket] = None
        self._repl_reader: Optional[FrameReader] = None
        self._follow_i = 0
        self._has_state = False  # a standby may only promote WITH state
        self._repl_down_since = time.monotonic()
        self._repl_last_attempt = 0.0
        self._repl_last_contact = 0.0
        self._repl_last_hb = 0.0
        # the fence-campaign thread and the serve loop race only toward
        # higher epochs; a stale read just delays fencing one tick
        self._primary_epoch = 0  # edl: lock-free(GIL-atomic int, raised monotonically via max)
        self._primary_rev = 0
        # replicated entries applied to memory but not yet journaled: the
        # standby defers its WAL fsync to the ACK boundary (a per-frame
        # fsync would stall standby-served reads while releasing nothing
        # earlier — acks only ride the primary's ~0.25s heartbeat stamps)
        self._apply_buf: List[dict] = []
        self._fence_thread: Optional[threading.Thread] = None
        # Store-HOST loss answer (the one availability asymmetry vs the
        # reference's replicable etcd): every compaction also lands the
        # snapshot in ``replica_dir`` — point it at shared storage (the
        # job's ckpt volume, a PVC) and a replacement store on a FRESH
        # host seeds itself from the replica when its own data_dir is
        # empty. Time-based compaction (below) bounds replica staleness.
        if replica_dir and not data_dir:
            raise ValueError(
                "replica_dir requires data_dir: snapshots are produced by "
                "the durability layer (an in-memory store has nothing to "
                "replicate)"
            )
        self._replica_dir = replica_dir
        self._last_compact = time.monotonic()
        self._wal_file = None
        self._wal_count = 0
        self._sel = selectors.DefaultSelector()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        # observability plane: request/fanout counters + live-state
        # gauges, scraped via /metrics when EDL_OBS_PORT opts the
        # process in (obs is a process-level plane; a replacement store
        # in the same process reuses the mounted endpoint). Created
        # before recovery — _recover() compacts, which counts — and the
        # gauges' referents (_conns) before the mount, so a scrape during
        # a long WAL replay sees a sane recovering store.
        self._conns: Dict[socket.socket, _Conn] = {}
        self._m_requests = obs_metrics.counter(
            "edl_store_requests_total", "store RPCs dispatched, by method"
        )
        self._m_fanout = obs_metrics.counter(
            "edl_store_watch_events_total", "watch events pushed to clients"
        )
        self._m_compactions = obs_metrics.counter(
            "edl_store_compactions_total", "journal compactions (snapshots written)"
        )
        self._m_failovers = obs_metrics.counter(
            "edl_store_failovers_total", "standby promotions to primary"
        )
        self._m_lease_resets = obs_metrics.counter(
            "edl_store_lease_resets_total",
            "leases restarted with a fresh TTL (recovery or promotion), by cause",
        )
        self._m_fenced = obs_metrics.counter(
            "edl_store_fenced_total",
            "times this store fenced itself on seeing a higher epoch",
        )
        self._m_sync_degraded = obs_metrics.counter(
            "edl_store_repl_sync_degraded_total",
            "semi-sync commits degraded to async (escape hatch engaged), "
            "by cause: timeout (standby too slow past "
            "EDL_STORE_REPL_SYNC_TIMEOUT) or subscriber_lost (the standby "
            "link died before acking)",
        )
        self._m_standby_reads = obs_metrics.counter(
            "edl_store_standby_reads_total",
            "reads (get/range/watch registrations) this standby served "
            "from its applied released revision instead of the primary",
        )
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_store_connections_open", "live client connections",
             lambda: len(self._conns)),
            ("edl_store_standby_lag_revs",
             "revisions this standby's applied state trails the primary "
             "by — the staleness bound on reads it serves (reads are "
             "refused past EDL_STORE_STANDBY_MAX_LAG)",
             lambda: self._repl_lag_entries()),
            ("edl_store_mvcc_versions",
             "MVCC versions retained across all per-key chains "
             "(compacted past the released horizon minus "
             "EDL_STORE_MVCC_RETAIN)",
             lambda: self._state.version_count),
            ("edl_store_revision_seq", "current store revision",
             lambda: self._state.revision),
            ("edl_store_epoch_seq", "current fencing epoch",
             lambda: self._state.epoch),
            ("edl_store_replication_lag_entries",
             "journal entries this standby trails its primary by",
             lambda: self._repl_lag_entries()),
            ("edl_store_replication_lag_seconds",
             "seconds since this standby last heard from its primary",
             lambda: self._repl_lag_seconds()),
            ("edl_store_repl_unacked_bytes",
             "journal bytes streamed to standbys but not yet standby-"
             "acked: the async-replication loss window a primary death "
             "can lose (ROADMAP item 2's semi-sync fix is judged "
             "against this)",
             lambda: self._repl_unacked_bytes()),
        ))
        self._health_fn = lambda: {
            "revision": self._state.revision,
            "conns": len(self._conns),
            "store_port": self.port,
            "role": self.role,
            "epoch": self._state.epoch,
            "fenced": self._fenced_by is not None,
        }
        self._obs = obs_http.start_from_env("store", health_fn=self._health_fn)
        if data_dir:
            # AFTER the bind on purpose: a losing "first pod on the host
            # wins" contender must fail on EADDRINUSE before it can touch
            # (compact, truncate) the live leader's snapshot/WAL. Recovery
            # faults are re-raised as RuntimeError so bind-contention
            # handlers (except OSError) never mistake them for a busy port.
            try:
                os.makedirs(data_dir, exist_ok=True)
                self._snap_path = os.path.join(data_dir, "snapshot.bin")
                self._wal_path = os.path.join(data_dir, "wal.bin")
                self._recover()
            except OSError as exc:
                self._listener.close()
                self._sel.close()
                raise RuntimeError(
                    "store data_dir %s unusable: %s" % (data_dir, exc)
                ) from exc
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wake pipe so stop() interrupts a sleeping select
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        if self._advertise is None:
            self._advertise = self.endpoint
        if self.role == "primary":
            # membership slot 0: clients refresh their ordered endpoint
            # list from here; standbys register via their repl_sync
            self._has_state = True
            self._publish_endpoint(0, self._advertise)
        else:
            # a restarted standby recovering real local state may promote
            # even if it can never re-sync (the primary died with it); a
            # blank standby must first bootstrap — promoting an empty
            # store would trade an outage for data loss
            self._has_state = self._state.revision > 0

    @property
    def endpoint(self) -> str:
        return "127.0.0.1:%d" % self.port

    # -- durability --------------------------------------------------------

    def _recover(self) -> None:
        import msgpack

        if (
            not os.path.exists(self._snap_path)
            and not os.path.exists(self._wal_path)
            and self._replica_dir
            and os.path.exists(os.path.join(self._replica_dir, "snapshot.bin"))
        ):
            # fresh host, replicated state available: seed from the
            # replica (the restore-on-new-host procedure — staleness is
            # bounded by the compaction interval; leases restart fresh
            # and watch resumes past the jump resync, both by design).
            # Copy-then-rename: a crash mid-seed must not leave a torn
            # snapshot.bin that the next boot mistakes for local state.
            import shutil

            seed_tmp = "%s.seed.%d.tmp" % (self._snap_path, os.getpid())
            shutil.copyfile(
                os.path.join(self._replica_dir, "snapshot.bin"), seed_tmp
            )
            os.replace(seed_tmp, self._snap_path)
            logger.warning(
                "store seeded from replica %s (fresh data_dir %s)",
                self._replica_dir, self._data_dir,
            )
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    self._state.load_snapshot(
                        msgpack.unpackb(f.read(), raw=False)
                    )
            except Exception as exc:
                # A torn snapshot (e.g. a non-atomic replica filesystem
                # caught mid-replace) must not crash-loop the store: set
                # it aside and continue from whatever the WAL salvages —
                # a degraded recovery beats a control plane that can
                # never come back.
                corrupt = self._snap_path + ".corrupt"
                logger.error(
                    "snapshot %s unreadable (%s); moving to %s and "
                    "recovering from the journal alone",
                    self._snap_path, exc, corrupt,
                )
                try:
                    os.replace(self._snap_path, corrupt)
                except OSError:
                    pass
                self._state = StoreState()
        replayed = 0
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                data = f.read()
            for entry in self._salvage_wal(data):
                self._state.apply_journal(entry)
                replayed += 1
        # the event history did not survive: watches resuming from any
        # pre-restart revision must resync
        self._state._mark_history_lost()
        if replayed or os.path.exists(self._snap_path):
            logger.info(
                "store recovered from %s: rev=%d, epoch=%d, %d wal entr%s "
                "replayed",
                self._data_dir, self._state.revision, self._state.epoch,
                replayed, "y" if replayed == 1 else "ies",
            )
        # recovery restarted every lease with a fresh TTL (the store
        # can't know how long it was down); say so OBSERVABLY — the chaos
        # downtime-attribution invariant reads this instead of inferring
        # lease-clock resets from expiry timing
        if self._state.lease_count:
            self._note_lease_resets(self._state.lease_count, "recovery")
        self._compact()

    @staticmethod
    def _salvage_wal(data: bytes):
        """Decode journal frames, tolerating a torn tail (crash mid-append:
        complete frames before it are all recoverable)."""
        reader = FrameReader(fault=False)  # disk replay, not network rx
        try:
            yield from reader.feed(data)
        except WireError as exc:
            logger.warning("wal tail unreadable (%s); recovered prefix", exc)

    def _compact(self) -> None:
        """Snapshot current state atomically, then truncate the journal.
        With a ``replica_dir``, the fresh snapshot is also copied there
        (best-effort: replica faults degrade availability of the
        RECOVERY path, never the live store)."""
        import msgpack

        blob = msgpack.packb(self._state.to_snapshot(), use_bin_type=True)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        if self._replica_dir:
            try:
                os.makedirs(self._replica_dir, exist_ok=True)
                # atomic publication: tmp IN the replica dir (rename never
                # crosses filesystems), pid-unique (two stores sharing one
                # replica volume must not clobber each other's tmp),
                # fsync'd file + dir (the rename itself must be durable —
                # this is the copy a REPLACEMENT host recovers from)
                rtmp = os.path.join(
                    self._replica_dir, "snapshot.bin.%d.tmp" % os.getpid()
                )
                with open(rtmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(
                    rtmp, os.path.join(self._replica_dir, "snapshot.bin")
                )
                dir_fd = os.open(self._replica_dir, os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            except OSError as exc:
                logger.warning(
                    "snapshot replica %s unwritable (%s); live store "
                    "unaffected", self._replica_dir, exc,
                )
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "wb")
        self._wal_count = 0
        self._last_compact = time.monotonic()
        self._m_compactions.inc()

    def _journal(self, entries: List[dict]) -> None:
        if self._wal_file is None or not entries:
            return
        if _FP_WAL.armed:
            _FP_WAL.fire(n=len(entries))
        # fault=False: the rpc.wire.tx point must never reach the journal
        # (a "network" fault corrupting durable state); WAL faults have
        # their own store.server.wal point above
        self._wal_file.write(
            b"".join(pack_frame(e, fault=False) for e in entries)
        )
        self._wal_file.flush()
        os.fsync(self._wal_file.fileno())
        self._wal_count += len(entries)
        if self._wal_count >= _COMPACT_EVERY or (
            self._replica_dir
            and time.monotonic() - self._last_compact >= _REPLICA_INTERVAL
        ):
            self._compact()

    def _commit(
        self,
        conn: Optional[_Conn],
        resp: Optional[dict],
        events: List[Event],
        entries: List[dict],
    ) -> None:
        """One commit: read-only commits answer immediately; mutations
        are buffered for the GROUP COMMIT that ends the current event-
        loop pass (``_flush_commits``). Grouping amortizes the WAL
        fsync — the dominant per-write cost on a durable store — across
        every request decoded in the pass: under pipelined load the
        journal syncs once per batch instead of once per write, while a
        lone write still flushes immediately (one commit = one fsync,
        exactly the old latency). The ack contract is unchanged: a
        response is only sent AFTER the batch containing its entries is
        fsynced (and, under semi-sync, standby-acked)."""
        if not entries:
            if resp is not None and conn is not None:
                self._send(conn, resp)
            self._fanout(events)
            return
        self._txn_buf.append((conn, resp, list(events), entries))
        if not self._group_commit:
            self._flush_commits()

    def _flush_applies(self) -> None:
        """Journal the standby's buffered replicated entries (one
        write+fsync for the whole buffer). Must run before any ack, any
        LOCAL commit's journal (WAL stays in apply order), and
        promotion."""
        if self._apply_buf:
            buf, self._apply_buf = self._apply_buf, []
            self._journal(buf)

    def _flush_commits(self) -> None:
        """End-of-pass group commit: journal every buffered entry with
        ONE write+fsync, stream the whole batch to subscribers as ONE
        replication frame, then release the responses and watch
        fan-out — held on the semi-sync queue when standbys must ack
        first, in FIFO order always."""
        if not self._txn_buf:
            return
        self._flush_applies()  # WAL order: replicated before local entries
        buffered, self._txn_buf = self._txn_buf, []
        all_entries: List[dict] = []
        for _conn, _resp, _events, entries in buffered:
            all_entries.extend(entries)
        self._journal(all_entries)
        targets = self._repl_broadcast(all_entries)
        completions = [
            (conn, resp, events) for conn, resp, events, _e in buffered
        ]
        if targets:
            first_rev = min(
                (evs[0].rev for _c, _r, evs in completions if evs),
                default=self._state.revision + 1,
            )
            self._sync_q.append(_SyncWait(
                completions, first_rev, targets,
                time.monotonic() + self._repl_sync_timeout,
            ))
            return
        self._release(completions)

    def _release(self, completions) -> None:
        for conn, resp, events in completions:
            if resp is not None and conn is not None:
                self._send(conn, resp)
            self._fanout(events)

    def _sync_drain(self, now: float) -> None:
        """Release held semi-sync batches, strictly FIFO (head-of-line:
        a later batch's ack never overtakes an earlier one's fanout, so
        watchers observe revision order). A batch releases when every
        target standby acked it; it DEGRADES to async — metered, the
        repl-sync-degraded rule's signal — when the deadline passes or
        the last subscriber died unacked."""
        while self._sync_q:
            wait = self._sync_q[0]
            lost = [s for s, t in wait.targets if s.closed and s.repl_ack < t]
            pending = [
                (s, t) for s, t in wait.targets
                if not s.closed and s.repl_ack < t
            ]
            if pending and now < wait.deadline:
                return
            self._sync_q.popleft()
            if pending or lost:
                cause = "timeout" if pending else "subscriber_lost"
                self._m_sync_degraded.inc(cause=cause)
                obs_trace.get_tracer().instant(
                    "store_repl_sync_degraded", cause=cause,
                    held=str(len(pending)),
                )
                if now - self._sync_last_warn >= 1.0:  # bound the log rate
                    self._sync_last_warn = now
                    logger.warning(
                        "semi-sync commit degraded to async (%s); the "
                        "replication loss window is OPEN until the "
                        "standby catches up", cause,
                    )
            self._release(wait.completions)

    def _released_rev(self) -> int:
        """The highest revision whose commit has been RELEASED to
        clients (acked / fanned out). While commits are held — buffered
        for the pass's group commit, or awaiting a semi-sync ack —
        watch registrations must not leak the held suffix through the
        history backlog: a watcher would observe a write that can
        still die with this primary alone."""
        # the sync queue holds OLDER batches than the pass buffer: the
        # earliest held event bounds what a fresh watch may be told
        for wait in self._sync_q:
            if wait.first_rev <= self._state.revision:
                return wait.first_rev - 1
        for conn_resp_events in self._txn_buf:
            events = conn_resp_events[2]
            if events:
                return events[0].rev - 1
        return self._state.revision

    def _note_lease_resets(self, count: int, cause: str) -> None:
        self._m_lease_resets.inc(count, cause=cause)
        obs_trace.get_tracer().instant(
            "store_lease_reset", cause=cause, count=str(count)
        )
        logger.warning(
            "store restarted %d lease(s) with a fresh TTL (%s)", count, cause
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self.serve_forever, name="edl-store", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def kill(self) -> None:
        """Crash simulation for failover drills: stop serving WITHOUT the
        clean-stop compaction, leaving snapshot + WAL exactly as a real
        SIGKILL would — the in-process stand-in for killing the daemon
        (every open connection sees a reset, a restart on the same
        data_dir replays the journal)."""
        self._crash = True
        self.stop()

    def serve_forever(self) -> None:  # edl: event-loop(store server: every RPC and lease sweep rides this thread)
        logger.info(
            "store serving on port %d (%s, epoch %d)",
            self.port, self.role, self._state.epoch,
        )
        last_sweep = time.monotonic()
        try:
            # commits buffered before the loop started (boot-time
            # endpoint publication) become durable on the first pass
            self._flush_commits()
            while not self._stop.is_set():
                timeout = _LEASE_SWEEP_INTERVAL
                # deadlines only matter to the acting primary: a standby's
                # replicated leases see no keepalives, and waking on their
                # (stale) deadlines would spin the loop
                deadline = (
                    self._state.next_lease_deadline()
                    if self.role == "primary" and self._fenced_by is None
                    else None
                )
                if deadline is not None:
                    timeout = min(timeout, max(0.0, deadline - time.monotonic()))
                if self._sync_q:
                    # wake by the head commit's degrade deadline: a held
                    # ack must not wait out a full sweep interval
                    timeout = min(timeout, max(
                        0.0, self._sync_q[0].deadline - time.monotonic()
                    ))
                for key, _ in self._sel.select(timeout):
                    if key.data == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    elif key.data == "repl":
                        self._on_repl_readable()
                    elif key.fileobj is self._listener:
                        self._accept()
                    else:
                        self._service(key.fileobj, key.events)
                # end of the service pass: group-commit everything the
                # pass dispatched (one WAL fsync + one repl frame for
                # the whole batch), then release/hold the responses
                self._flush_commits()
                now = time.monotonic()
                if self._sync_q:
                    self._sync_drain(now)
                self._repl_tick(now)
                # MVCC chain compaction: versions older than the released
                # horizon minus the retain budget serve no read (pinned
                # snapshots and watch resumes both live above it). Runs
                # on standbys too — their chains grow at apply time.
                if now - self._mvcc_last_compact >= 1.0:
                    self._mvcc_last_compact = now
                    self._state.compact(
                        self._released_rev() - self._mvcc_retain
                    )
                # liveness duty belongs to the serving primary alone: a
                # standby's lease deadlines tick without keepalives (they
                # land on the primary), and a fenced primary no longer
                # speaks for the cluster
                sweep_due = (
                    self.role == "primary"
                    and self._fenced_by is None
                    and (
                        now - last_sweep >= _LEASE_SWEEP_INTERVAL
                        or (deadline is not None and deadline <= now)
                    )
                )
                if sweep_due:
                    last_sweep = now
                    expired, dead_ids = self._state.expire_leases_with_ids()
                    if expired or dead_ids:
                        # server-initiated commits ride the same group-
                        # commit + semi-sync queue as client writes:
                        # expiry events reach watchers only once
                        # standby-durable, in order
                        self._commit(
                            None, None, expired,
                            [{"op": "revoke", "id": lid} for lid in dead_ids]
                            + [{"op": "ev", **ev.to_wire()} for ev in expired],
                        )
                        self._flush_commits()
                    if (
                        self._replica_dir
                        and self._wal_count > 0
                        and time.monotonic() - self._last_compact
                        >= _REPLICA_INTERVAL
                    ):
                        # a QUIET store must still honor the replica
                        # staleness bound: mutation-triggered compaction
                        # alone would strand the final pre-quiescence
                        # writes outside the replica forever
                        self._compact()
        finally:
            if self._wal_file is not None:
                if not self._crash:
                    self._compact()  # clean stop: durable snapshot, empty wal
                self._wal_file.close()
                self._wal_file = None
            self._repl_close()
            for conn in list(self._conns.values()):
                self._close(conn)
            self._sel.unregister(self._listener)
            self._listener.close()
            self._wake_r.close()
            self._wake_w.close()
            self._sel.close()
            self._obs_gauges.release()
            obs_http.release_health("store", self._health_fn)
            logger.info("store on port %d stopped", self.port)

    # -- event loop internals ---------------------------------------------

    def _accept(self) -> None:
        try:
            sock, addr = self._listener.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock, addr)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _service(self, sock: socket.socket, events: int) -> None:
        conn = self._conns.get(sock)
        if conn is None:
            return
        if events & selectors.EVENT_READ:
            self._on_readable(conn)
        if not conn.closed and events & selectors.EVENT_WRITE:
            self._flush(conn)

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(256 * 1024)
        except BlockingIOError:
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        try:
            requests = conn.reader.feed(data)
        except (WireError, ConnectionError) as exc:
            # ConnectionError: an injected rpc.wire.rx drop — one dead
            # connection, and it must not escape into (and kill) the
            # shared event loop, same as the tx guard in _send
            logger.warning("protocol error from %s: %s", conn.addr, exc)
            self._close(conn)
            return
        for req in requests:
            self._dispatch(conn, req)
            if conn.closed:
                return

    def _send(self, conn: _Conn, payload: dict) -> None:
        if conn.closed:
            return
        try:
            frame = pack_frame(payload)
        except ConnectionError:
            # an injected tx drop means THIS connection reset mid-send; it
            # must not escape into (and kill) the shared event loop
            self._close(conn)
            return
        conn.out += frame
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        try:
            while conn.out:
                sent = conn.sock.send(conn.out)
                if sent == 0:
                    break
                del conn.out[:sent]
        except BlockingIOError:
            pass
        except OSError:
            self._close(conn)
            return
        mask = selectors.EVENT_READ
        if conn.out:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _fanout(self, events: List[Event]) -> None:
        """Push events to every connection watching a matching prefix.
        Deliveries to one connection are BATCHED into a single frame
        (``wb``) when more than one of its watches matched — at 10k-pod
        scale one membership event can match hundreds of watches, and
        per-watch frames were a frame-rate multiplier on the fan-out
        path. Events at-or-below a watch's registration revision are
        skipped: the registration's backlog already delivered them."""
        if not events:
            return
        for conn in list(self._conns.values()):
            batch: List[list] = []
            for wid, (prefix, hwm) in list(conn.watches.items()):
                matched = [
                    e.to_wire() for e in events
                    if e.rev > hwm and e.key.startswith(prefix)
                ]
                if matched:
                    self._m_fanout.inc(len(matched))
                    batch.append([wid, matched])
            if not batch:
                continue
            if len(batch) == 1:
                self._send(conn, {"w": batch[0][0], "ev": batch[0][1]})
            else:
                self._send(conn, {"wb": batch})

    # -- replication (warm standby + failover) -----------------------------
    #
    # All follower-side work runs on the event-loop thread: the link to
    # the primary is just another selector-registered socket, so the
    # state machine stays single-threaded (the same invariant the client
    # connections rely on). The only extra thread is the promoted
    # primary's fence campaign, which never touches ``_state``.

    def _repl_lag_entries(self) -> float:
        if self.role != "standby":
            return 0.0
        return float(max(0, self._primary_rev - self._state.revision))

    def _repl_lag_seconds(self) -> float:
        if self.role != "standby":
            return 0.0
        anchor = self._repl_last_contact or self._repl_down_since
        return max(0.0, time.monotonic() - anchor)

    def _repl_unacked_bytes(self) -> float:
        """Journal bytes in flight toward standbys: streamed (kernel-
        buffered at best) but not yet echoed back by a ``repl_ack``.
        This is the exact measurement of the known store-failover
        async-replication window — acked writes the primary already
        answered for can still die with it while this is nonzero."""
        total = 0
        for conn in list(self._conns.values()):
            if conn.repl and not conn.closed:
                total += max(0, conn.repl_tx - conn.repl_ack)
        return float(total)

    def _known_endpoints(self) -> List[str]:
        """Every member endpoint this store has heard of: the replicated
        membership keyspace plus the configured follow list."""
        rows, _rev = self._state.range(replica_mod.ENDPOINTS_PREFIX)
        out = replica_mod.parse_endpoint_rows(rows)
        for ep in self._follow:
            if ep not in out:
                out.append(ep)
        return out

    def _publish_endpoint(
        self, slot: int, endpoint: str, role: Optional[str] = None
    ) -> None:
        ev = self._state.put(
            replica_mod.endpoint_key(slot),
            replica_mod.endpoint_value(
                endpoint, self._state.epoch, role or self.role
            ),
        )
        self._commit(None, None, [ev], [{"op": "ev", **ev.to_wire()}])

    def _retract_endpoint(self, slot: int) -> None:
        ev = self._state.delete(replica_mod.endpoint_key(slot))
        if ev is not None:
            self._commit(None, None, [ev], [{"op": "ev", **ev.to_wire()}])

    def _repl_broadcast(self, entries: List[dict]) -> List[Tuple[_Conn, int]]:
        """Stream a journal batch (or an empty heartbeat) to every
        replication subscriber. Under semi-sync, entry batches carry the
        per-subscriber cumulative byte stamp (``tb``) so the standby
        acks the moment it has applied+journaled — and the returned
        ``(subscriber, target)`` list is what the commit's release
        waits on. Async mode returns ``[]`` (stamps ride the 0.25s
        heartbeats instead, converging the loss-window gauge without
        per-write chatter)."""
        subs = [c for c in self._conns.values() if c.repl and not c.closed]
        if not subs:
            return []
        payload = {
            "rl": entries,
            "e": self._state.epoch,
            "r": self._state.revision,
        }
        if entries:
            sync = self._repl_sync_timeout > 0
            # ONE serialization per batch shared by every subscriber and
            # by the loss-window accounting; under semi-sync, the
            # per-subscriber cumulative stamp rides a tiny empty-batch
            # frame AFTER the shared one (TCP orders them, so the
            # standby's ack certifies the batch was applied+journaled)
            # instead of re-packing the whole batch per subscriber
            try:
                base = pack_frame(payload)
            except ConnectionError:
                # injected rpc.wire.tx drop: every subscriber link dies
                for conn in subs:
                    self._close(conn)
                return []
            targets: List[Tuple[_Conn, int]] = []
            for conn in subs:
                if _FP_REPL_STREAM.armed:
                    try:
                        _FP_REPL_STREAM.fire(side="tx", n=len(entries))
                    except ConnectionError:
                        self._close(conn)  # the standby sees a dead link
                        continue
                conn.repl_tx += len(base)
                conn.out += base
                if sync:
                    try:
                        conn.out += pack_frame({
                            "rl": [], "e": self._state.epoch,
                            "r": self._state.revision, "tb": conn.repl_tx,
                        })
                    except ConnectionError:
                        self._close(conn)
                        continue
                self._flush(conn)
                if sync and not conn.closed:
                    targets.append((conn, conn.repl_tx))
            return targets
        # heartbeat: per-subscriber, carrying the cumulative streamed
        # byte count; the standby echoes it back as a repl_ack, so the
        # edl_store_repl_unacked_bytes window converges at heartbeat
        # cadence without any per-write ack chatter
        for conn in subs:
            if _FP_REPL_STREAM.armed:
                try:
                    _FP_REPL_STREAM.fire(side="tx", n=0)
                except ConnectionError:
                    self._close(conn)
                    continue
            self._send(conn, dict(payload, tb=conn.repl_tx))
        return []

    def _repl_tick(self, now: float) -> None:
        if self.role == "primary":
            if self._fenced_by is None and now - self._repl_last_hb >= _REPL_HEARTBEAT:
                self._repl_last_hb = now
                self._repl_broadcast([])
            return
        if self._repl_sock is not None:
            # a silent partition gives no socket error: declare the link
            # dead once heartbeats stop arriving
            stale_after = max(self._failover_grace, 4 * _REPL_HEARTBEAT)
            if (
                self._repl_last_contact
                and now - self._repl_last_contact > stale_after
            ):
                self._repl_lost("heartbeats stopped")
            return
        if now - self._repl_down_since >= self._failover_grace * self.priority:
            self._consider_promotion(now)
            if self.role == "primary":
                return
        if now - self._repl_last_attempt >= _REPL_DIAL_INTERVAL:
            self._repl_last_attempt = now
            self._repl_connect()

    def _repl_connect(self) -> None:
        """One bootstrap attempt against the current follow target. The
        sync response (snapshot) arrives through the selector like every
        other frame."""
        if not self._follow:
            return
        target = self._follow[self._follow_i % len(self._follow)]
        if target == self._advertise:
            self._follow_i += 1
            return
        try:
            if _FP_REPL_SYNC.armed:
                _FP_REPL_SYNC.fire(endpoint=target)  # drop is an OSError
            from edl_tpu.utils.net import split_endpoint

            sock = socket.create_connection(  # edl: blocking-ok(bounded 0.5s dial, standby only: a disconnected standby's loop has no client traffic to starve)
                split_endpoint(target), timeout=0.5
            )
        except OSError:
            self._follow_i += 1  # rotate: the primary may have moved
            return
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(pack_frame({
                "i": 0,
                "m": "repl_sync",
                "e": max(self._state.epoch, self._primary_epoch),
                "ep": self._advertise,
                "prio": self.priority,
            }))
        except OSError:
            sock.close()
            self._follow_i += 1
            return
        sock.setblocking(False)
        self._repl_sock = sock
        self._repl_reader = FrameReader(fault=False)  # repl has its own points
        self._sel.register(sock, selectors.EVENT_READ, "repl")
        self._repl_last_contact = time.monotonic()
        logger.info("standby syncing from %s", target)

    def _on_repl_readable(self) -> None:
        sock = self._repl_sock
        if sock is None:
            return
        try:
            data = sock.recv(256 * 1024)
        except BlockingIOError:
            return
        except OSError as exc:
            self._repl_lost("recv failed: %s" % exc)
            return
        if not data:
            self._repl_lost("primary closed the link")
            return
        try:
            frames = self._repl_reader.feed(data)
            self._repl_last_contact = time.monotonic()
            for frame in frames:
                if "snap" in frame:
                    self._repl_bootstrap(frame)
                elif "rl" in frame:
                    self._repl_apply(frame)
                elif frame.get("ok") is False:
                    # the peer refused the sync (a standby, or fenced):
                    # rotate to the next candidate WITHOUT resetting the
                    # promotion grace clock — reaching a fellow standby
                    # is not contact with a primary, and treating it as
                    # such would keep a standby whose follow list names
                    # its peers from ever promoting
                    self._repl_lost(
                        "sync rejected: %s"
                        % frame.get("err", {}).get("detail", "?"),
                        reset_down=False,
                    )
                    self._follow_i += 1
                    return
        except (WireError, ConnectionError) as exc:
            self._repl_lost(str(exc))

    def _repl_bootstrap(self, frame: dict) -> None:
        import msgpack

        self._state.load_snapshot(msgpack.unpackb(frame["snap"], raw=False))
        # a demoted ex-primary re-syncing discards any diverged local
        # suffix here: the snapshot is authoritative, full resync by design
        self._primary_epoch = int(frame.get("e", 0))
        self._state.set_epoch(self._primary_epoch)
        self._primary_rev = int(frame.get("r", self._state.revision))
        self._has_state = True
        self._repl_down_since = time.monotonic()
        if self._data_dir:
            self._compact()  # persist the bootstrap before tailing
        logger.info(
            "standby bootstrapped from primary: rev=%d epoch=%d",
            self._state.revision, self._state.epoch,
        )

    def _repl_apply(self, frame: dict) -> None:
        entries = frame.get("rl") or ()
        if entries and _FP_REPL_STREAM.armed:
            _FP_REPL_STREAM.fire(side="rx", n=len(entries))
        for entry in entries:
            # record=True: the history ring must survive into promotion
            # so client watches resume from pre-failover revisions
            self._state.apply_journal(entry, record=True)
        if entries:
            # journaling is DEFERRED to the ack boundary (_flush_applies):
            # the ack contract — acked implies applied AND journaled —
            # holds because the flush always precedes the ack send below,
            # and an un-journaled entry is by construction un-acked (the
            # primary holds or degrades, never trusts it)
            self._apply_buf.extend(entries)
            # standby read serving: watches registered HERE fan out at
            # apply time — on a standby applied == released (it holds no
            # commit queues), and the primary only streamed this batch
            # after journaling it, so nothing pushed here can be undone
            # by the primary dying mid-window
            applied = [
                Event.from_wire(e) for e in entries if e.get("op") == "ev"
            ]
            if applied:
                self._fanout(applied)
        self._primary_epoch = max(self._primary_epoch, int(frame.get("e", 0)))
        self._primary_rev = max(self._primary_rev, int(frame.get("r", 0)))
        # ack the cumulative byte count we have APPLIED (and journaled):
        # the primary's edl_store_repl_unacked_bytes gauge is the stream
        # minus these echoes. The stamp arrives only on the primary's
        # 0.25s heartbeats, so acks are naturally throttled — an
        # in-process primary+standby pair shares the GIL, and per-write
        # ack chatter would be exactly what PR 6/8 pace out of HA rigs.
        # Best-effort on the nonblocking link: a lost ack just means the
        # next heartbeat's (cumulative) echo covers us.
        tb = frame.get("tb")
        if tb is not None and self._repl_sock is not None:
            # the ack boundary: everything applied so far must be
            # journaled BEFORE the cumulative byte echo goes out — one
            # fsync per heartbeat interval instead of one per frame
            self._flush_applies()
            try:
                ack = pack_frame(
                    {"i": 0, "m": "repl_ack", "tb": int(tb)}, fault=False
                )
                sent = self._repl_sock.send(ack)
                if sent != len(ack):
                    # a partial write on the (nearly idle) ack direction
                    # would desync the primary's frame reader: treat it
                    # as a dead link and resync rather than corrupt the
                    # stream — the ack protocol has no resume point
                    self._repl_lost("partial ack write (%d/%d)"
                                    % (sent, len(ack)))
            except BlockingIOError:
                pass  # buffer full: the next batch's cumulative ack covers
            except (OSError, TypeError, ValueError):
                pass

    def _repl_lost(self, reason: str, reset_down: bool = True) -> None:
        # the link may never stamp another ack boundary: journal what
        # was applied so the buffer cannot outlive a healthy-link window
        self._flush_applies()
        sock, self._repl_sock = self._repl_sock, None
        self._repl_reader = None
        if sock is None:
            return
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass
        if reset_down:
            self._repl_down_since = time.monotonic()
        self._repl_last_contact = 0.0
        logger.warning("replication link lost (%s)", reason)

    def _repl_close(self) -> None:
        if self._repl_sock is not None:
            self._repl_lost("server stopping")

    def _consider_promotion(self, now: float) -> None:
        """The link has been dead past this standby's share of the grace
        window. Probe the world first — promotion must lose to any live
        primary of an equal-or-newer generation (a link blip, or a
        better-placed standby that already took over)."""
        if not self._has_state:
            return  # nothing to serve: promoting an empty store loses data
        for ep in self._known_endpoints():
            if ep == self._advertise:
                continue
            status = replica_mod.probe_status(ep, timeout=0.3)
            if (
                status is not None
                and status.get("role") == "primary"
                and not status.get("fenced")
                and int(status.get("e", 0)) >= self._primary_epoch
            ):
                # someone is serving this generation (or a newer one):
                # follow them instead of splitting the brain
                self._primary_epoch = max(
                    self._primary_epoch, int(status.get("e", 0))
                )
                if ep not in self._follow:
                    self._follow.insert(0, ep)
                self._follow_i = self._follow.index(ep)
                self._repl_down_since = now  # restart the grace clock
                return
        self._promote()

    def _promote(self) -> None:
        # everything applied while standby becomes durable BEFORE this
        # store starts speaking as the primary
        self._flush_applies()
        new_epoch = max(self._state.epoch, self._primary_epoch) + 1
        self._state.set_epoch(new_epoch)
        self.role = "primary"
        fence_targets = [
            ep for ep in self._known_endpoints() if ep != self._advertise
        ]
        self._commit(None, None, [], [{"op": "epoch", "e": new_epoch}])
        resets = self._state.reset_lease_deadlines()
        if resets:
            self._note_lease_resets(resets, "promotion")
        # membership: take slot 0, clear whichever standby slot(s) hold
        # my endpoint (slot may have been bumped past my priority if it
        # collided with another standby's — never retract by number
        # alone, that could delete a peer's row)
        import json as _json

        rows, _rev = self._state.range(replica_mod.ENDPOINTS_PREFIX)
        for key, value, *_rest in rows:
            try:
                slot = int(key[len(replica_mod.ENDPOINTS_PREFIX):])
                mine = _json.loads(value).get("endpoint") == self._advertise
            except (ValueError, TypeError):
                continue
            if mine and slot != 0:
                self._retract_endpoint(slot)
        self._publish_endpoint(0, self._advertise)
        self._m_failovers.inc()
        # the epoch bump must be durable BEFORE this store serves as
        # primary: flush the group-commit buffer here, not next pass
        self._flush_commits()
        # operation root: the failover's trace id derives from the new
        # epoch, so any other process touching the op (edl-trace, a
        # future semi-sync handshake) stitches to it deterministically
        if obs_trace.PROPAGATION.armed:
            ctx = obs_trace.record_op_root(
                "store_failover", str(new_epoch), endpoint=self._advertise
            )
        else:
            ctx = None
        with obs_trace.use(ctx):
            obs_trace.get_tracer().instant(
                "store_promote", epoch=str(new_epoch),
                endpoint=self._advertise,
            )
        logger.warning(
            "standby PROMOTED to primary: epoch %d, rev %d, fencing %s",
            new_epoch, self._state.revision, fence_targets or "(nobody)",
        )
        self._start_fence_campaign(fence_targets)

    def _start_fence_campaign(self, targets: List[str]) -> None:
        if not targets:
            return
        self._fence_thread = threading.Thread(
            target=self._fence_loop, args=(list(targets),),
            name="edl-store-fence", daemon=True,
        )
        self._fence_thread.start()

    def _fence_loop(self, targets: List[str]) -> None:
        """Keep delivering our epoch to every other known endpoint while
        we are the primary — a stale primary resurrected at ANY later
        point gets fenced within one pass, before fresh clients can
        write to it."""
        while (
            not self._stop.is_set()
            and self.role == "primary"
            and self._fenced_by is None
        ):
            epoch = self._state.epoch
            for ep in targets:
                resp = replica_mod.send_fence(
                    ep, epoch, sender=self._advertise, timeout=0.5
                )
                if resp is None:
                    continue
                peer_epoch = int(resp.get("e", 0))
                if peer_epoch > epoch:
                    # a newer generation exists: WE are the stale one
                    self._fence_self(
                        peer_epoch, "fence race lost against %s" % ep
                    )
                    return
                if (
                    peer_epoch == epoch
                    and resp.get("role") == "primary"
                    and not resp.get("fenced")
                    and self._advertise > ep
                ):
                    # equal-epoch tie against a surviving primary: the
                    # lexically larger endpoint loses (mirror of the
                    # receiver-side rule in _op_repl_fence)
                    self._fence_self(
                        epoch, "equal-epoch tie lost to %s" % ep
                    )
                    return
            self._stop.wait(_FENCE_INTERVAL)

    def _fence_self(self, epoch: int, why: str) -> None:
        if self._fenced_by is not None and self._fenced_by >= epoch:
            return
        self._fenced_by = epoch
        self._m_fenced.inc()
        obs_trace.get_tracer().instant(
            "store_fenced", epoch=str(epoch), why=why
        )
        logger.error(
            "store FENCED by epoch %d (%s): refusing all client "
            "operations — a newer primary owns this cluster", epoch, why,
        )

    # -- method dispatch ---------------------------------------------------

    def _response_epoch(self) -> int:
        """The epoch stamped on every response. A fenced store reports
        the epoch that fenced it, so clients learn the NEW generation
        from the stale server itself and refuse it thereafter."""
        if self._fenced_by is not None:
            return self._fenced_by
        return self._state.epoch

    def _send_error(self, conn: _Conn, rid, exc: Exception) -> None:
        self._send(conn, {
            "i": rid,
            "ok": False,
            "e": self._response_epoch(),
            "err": serialize_exception(exc),
        })

    def _dispatch(self, conn: _Conn, req: dict) -> None:
        rid = req.get("i")
        method = req.get("m")
        if method == "repl_ack":
            # a standby echoing the replication stream's cumulative byte
            # count: pure accounting, no response frame (the subscriber
            # link is not a request/response channel), and exempt from
            # the fencing/standby gates below — acks must keep flowing
            # right up to the moment the link dies
            try:
                conn.repl_ack = max(conn.repl_ack, int(req.get("tb", 0)))
            except (TypeError, ValueError):
                pass
            if self._sync_q:
                # a fresh ack may release held semi-sync commits NOW —
                # the ack round-trip, not the next loop tick, is the
                # semi-sync latency floor
                self._sync_drain(time.monotonic())
            return
        if _FP_DISPATCH.armed:
            try:
                _FP_DISPATCH.fire(method=str(method))
            except ConnectionError:
                self._close(conn)  # the peer sees a reset mid-request
                return
        handler = getattr(self, "_op_" + str(method), None)
        # sentinel for unknown methods: the label value is client data,
        # and per-value counter series would let a fuzzing client grow
        # the registry without bound
        self._m_requests.inc(
            method=str(method) if handler is not None else "<unknown>"
        )
        if handler is None:
            self._send_error(
                conn, rid, EdlStoreError("unknown method %r" % method)
            )
            return
        # epoch fencing: a store that saw a higher epoch no longer speaks
        # for the cluster — only liveness/fence probes get through
        if self._fenced_by is not None and method not in _STANDBY_OK:
            self._send_error(conn, rid, EdlFencedError(
                "store fenced by epoch %d; a newer primary owns this "
                "cluster" % self._fenced_by
            ))
            return
        if self.role != "primary" and method not in _STANDBY_OK:
            refusal = self._standby_read_refusal(method, req)
            if refusal is not None:
                self._send_error(conn, rid, EdlNotPrimaryError(refusal))
                return
            self._standby_reads_n += 1
            self._m_standby_reads.inc()
        try:
            # per-method server-side latency + (when the caller stamped
            # a "tc" trace context into the frame) a handling span that
            # is a child of the caller's span
            with server_span(str(method), req.get(TC_FIELD), server=self.name):
                result, events = handler(conn, req)
        except Exception as exc:  # noqa: BLE001 — every fault maps to a wire error
            self._send_error(conn, rid, exc)
            return
        # journal + replicate BEFORE acking: a response implies the
        # mutation is durable AND streamed to every live standby — and
        # under semi-sync, standby-APPLIED (the commit below holds the
        # ack until the repl_ack covers it)
        entries: List[dict] = []
        if method == "lease_grant":
            entries.append(
                {"op": "grant", "id": result["lease"], "ttl": float(req["ttl"])}
            )
        elif method == "lease_revoke":
            entries.append({"op": "revoke", "id": req["lease"]})
        entries.extend({"op": "ev", **ev.to_wire()} for ev in events)
        resp = {"i": rid, "ok": True, "e": self._response_epoch()}
        resp.update(result)
        self._commit(conn, resp, list(events), entries)

    _NO_EVENTS: Tuple = ()

    # the read-only ops a standby may serve itself (applied == released
    # there: it holds no commit queues). unwatch rides along so a client
    # with a standby-registered watch can tear it down where it lives.
    _STANDBY_READS = ("get", "range", "watch", "unwatch")

    def _standby_read_refusal(self, method, req) -> Optional[str]:
        """None when this standby serves the read itself; otherwise the
        reason it must bounce to the primary. Every refusal maps to
        EdlNotPrimaryError on the wire — the exact error clients already
        redirect on, so old clients, lag fall-through and the
        read-your-writes floor all degrade the same way: a primary
        round-trip. Serving requires the client's explicit opt-in
        ("rm": "s"): a legacy client that dialed a standby by accident
        keeps getting the redirect, never silently-stale data."""
        if method not in self._STANDBY_READS or req.get("rm") != "s":
            return (
                "store at %s is a warm standby (epoch %d); retry against "
                "the primary" % (self._advertise, self._state.epoch)
            )
        if not self._has_state:
            return (
                "standby %s has no state yet (still bootstrapping)"
                % self._advertise
            )
        lag = self._repl_lag_entries()
        if lag > self._standby_max_lag:
            return (
                "standby %s lags the primary by %d revs (bound "
                "EDL_STORE_STANDBY_MAX_LAG=%d); retry against the primary"
                % (self._advertise, lag, self._standby_max_lag)
            )
        minr = req.get("minr")
        if minr is not None:
            try:
                floor = int(minr)
            except (TypeError, ValueError):
                floor = 0
            if self._state.revision < floor:
                return (
                    "standby %s applied rev %d < the session's write "
                    "floor %d (read-your-writes); retry against the "
                    "primary" % (self._advertise, self._state.revision, floor)
                )
        return None

    def _op_ping(self, conn, req):
        return {}, self._NO_EVENTS

    def _op_put(self, conn, req):
        ev = self._state.put(req["k"], req["v"], req.get("l", 0))
        return {"r": ev.rev}, [ev]

    def _op_put_absent(self, conn, req):
        created, ev, existing = self._state.put_if_absent(
            req["k"], req["v"], req.get("l", 0)
        )
        if created:
            return {"created": True, "r": ev.rev}, [ev]
        return {"created": False, "cur": existing}, self._NO_EVENTS

    def _op_cas(self, conn, req):
        ok, ev = self._state.cas(req["k"], req["er"], req["v"], req.get("l", 0))
        if ok:
            return {"swapped": True, "r": ev.rev}, [ev]
        return {"swapped": False}, self._NO_EVENTS

    def _read_rev(self, req) -> Optional[int]:
        """The revision this read answers AT: an explicit ``rev`` pin
        wins (snapshot-coherent range, MVCC history read); otherwise the
        last RELEASED revision when MVCC is on — a reader must not
        observe a commit whose semi-sync release is still held, it could
        die with this primary. None = the applied state (the fast path,
        and the whole story with EDL_STORE_MVCC=0)."""
        rev = req.get("rev")
        if rev is not None:
            return int(rev)
        if not self._mvcc:
            return None
        released = self._released_rev()
        # session floor: a standby leg may have answered at the standby's
        # applied revision a beat before OUR ack processing released it.
        # Anything the session already observed is applied+journaled on
        # the standby, so serving up to ``minr`` breaks no durability
        # promise — refusing to would make this session's history rewind.
        minr = req.get("minr")
        if minr:
            released = max(released, min(int(minr), self._state.revision))
        if released >= self._state.revision:
            return None  # nothing held: applied state IS released state
        return released

    def _op_get(self, conn, req):  # edl: protocol-ok(sent via client._read variable-method read path)
        rev = self._read_rev(req)
        try:
            got = self._state.get(req["k"], rev=rev)
        except ValueError as exc:
            raise EdlCompactedError(str(exc)) from exc
        asof = (
            self._state.revision if rev is None
            else min(rev, self._state.revision)
        )
        if got is None:
            return {"v": None, "r": asof}, self._NO_EVENTS
        value, mod_rev, lease = got
        return {"v": value, "mr": mod_rev, "l": lease, "r": asof}, self._NO_EVENTS

    def _op_range(self, conn, req):  # edl: protocol-ok(sent via client._read variable-method read path)
        try:
            items, rev = self._state.range(req["p"], rev=self._read_rev(req))
        except ValueError as exc:
            raise EdlCompactedError(str(exc)) from exc
        return {"kvs": [list(item) for item in items], "r": rev}, self._NO_EVENTS

    def _op_del(self, conn, req):
        ev = self._state.delete(req["k"])
        if ev is None:
            return {"deleted": 0}, self._NO_EVENTS
        return {"deleted": 1, "r": ev.rev}, [ev]

    def _op_del_range(self, conn, req):
        events = self._state.delete_range(req["p"])
        return {"deleted": len(events)}, events

    def _op_lease_grant(self, conn, req):
        lease = self._state.lease_grant(float(req["ttl"]))
        return {"lease": lease}, self._NO_EVENTS

    def _op_lease_keepalive(self, conn, req):
        alive = self._state.lease_keepalive(req["lease"])
        return {"alive": alive}, self._NO_EVENTS

    def _op_lease_renew_batch(self, conn, req):
        # the client-side renew coalescer's op: one RPC renews every
        # lease a connection owns this tick — at 10k pods the per-lease
        # keepalive stream was the control plane's dominant QPS
        return {
            "alive": [self._state.lease_keepalive(l) for l in req["ls"]]
        }, self._NO_EVENTS

    def _op_lease_revoke(self, conn, req):
        events = self._state.lease_revoke(req["lease"])
        return {"revoked": True}, events

    def _op_watch(self, conn, req):
        # The watch id is CLIENT-assigned (unique per connection) so the
        # client can register its handler before the first push can arrive —
        # no window where an event targets an unknown id. The backlog is
        # delivered as a push frame, written before the response and before
        # any later event, so the dispatcher sees strictly ordered history.
        wid = req["wid"]
        prefix = req["p"]
        released = self._released_rev()
        backlog = []
        if req.get("r") is not None:
            try:
                backlog = [
                    e.to_wire()
                    for e in self._state.history_since(req["r"], prefix)
                    if e.rev <= released
                ]
            except ValueError as exc:
                raise EdlCompactedError(str(exc)) from exc
        # high-water mark = the released revision: the backlog above
        # covers everything at-or-below it, the (held) fan-out covers
        # everything after — exactly once, and never before the
        # standby ack that makes the event durable beyond this primary.
        # A RESUME point past the released revision (the client's
        # range() already observed applied-but-held state) raises the
        # mark with it: re-delivering the held suffix on release would
        # double what the range reported.
        hwm = released
        if req.get("r") is not None:
            try:
                hwm = max(hwm, int(req["r"]))
            except (TypeError, ValueError):
                pass
        conn.watches[wid] = (prefix, hwm)
        if backlog:
            self._send(conn, {"w": wid, "ev": backlog})
        return {"r": released}, self._NO_EVENTS

    def _op_unwatch(self, conn, req):
        conn.watches.pop(req["wid"], None)
        return {}, self._NO_EVENTS

    def _op_state(self, conn, req):
        return {
            "rev": self._state.revision,
            "conns": len(self._conns),
            "role": self.role,
            "epoch": self._state.epoch,
        }, self._NO_EVENTS

    # -- replication control plane (see "replication" section above) -------

    def _op_repl_status(self, conn, req):
        return {
            "role": self.role,
            "e": self._state.epoch,
            "r": self._state.revision,
            "fenced": self._fenced_by is not None,
            "lag": int(self._repl_lag_entries()),
            # the per-shard health row edl-top renders: the open
            # semi-sync/async loss window and whether semi-sync is armed
            "unacked": int(self._repl_unacked_bytes()),
            "sync": self._repl_sync_timeout > 0,
            "subs": sum(
                1 for c in self._conns.values() if c.repl and not c.closed
            ),
            # read-serving posture (the edl-top STORE panel's read-mode /
            # standby-reads columns): which revision reads answer at, and
            # how many reads this member served as a standby
            "readmode": "released" if self._mvcc else "applied",
            "sreads": self._standby_reads_n,
        }, self._NO_EVENTS

    def _op_repl_sync(self, conn, req):
        """A standby bootstraps: register its endpoint in the membership
        keyspace, hand it a full snapshot, and subscribe its connection
        to the live journal stream. A sync request carrying a HIGHER
        epoch than ours is proof a newer primary exists — fence
        ourselves instead of feeding the caller stale state."""
        import msgpack

        req_epoch = int(req.get("e", 0))
        if req_epoch > self._state.epoch:
            self._fence_self(req_epoch, "repl_sync from a newer generation")
            raise EdlFencedError(
                "fenced by epoch %d carried on a sync request" % req_epoch
            )
        ep = req.get("ep")
        prio = int(req.get("prio", 1))
        if ep:
            # published (and journaled, and streamed) BEFORE the snapshot
            # is taken, so the snapshot below already carries it and the
            # new subscriber never sees its own registration twice. Two
            # standbys configured with the same priority must not
            # overwrite each other's membership row (clients and the
            # fence campaign would lose sight of one): take the first
            # slot at-or-after the requested one that is free or already
            # ours.
            slot = max(1, prio)
            while True:
                held = self._state.get(replica_mod.endpoint_key(slot))
                if held is None:
                    break
                try:
                    import json as _json

                    if _json.loads(held[0]).get("endpoint") == ep:
                        break
                except (ValueError, TypeError):
                    break  # malformed row: claim the slot
                slot += 1
            self._publish_endpoint(slot, ep, role="standby")
        blob = msgpack.packb(self._state.to_snapshot(), use_bin_type=True)
        conn.repl = True
        return {
            "snap": blob,
            "e": self._state.epoch,
            "r": self._state.revision,
        }, self._NO_EVENTS

    def _op_repl_fence(self, conn, req):
        """An epoch delivery from a promoted peer. Outcomes: we are older
        and serving → fence ourselves; we are older and standby → just
        update our horizon; we are NEWER → answer with our epoch so the
        CALLER learns it lost the race (it self-fences); EQUAL epochs
        with both sides primary (two standbys promoted concurrently) →
        tie-break on advertise endpoint, lexically larger loses — the
        same rule the caller applies, so exactly one survives."""
        epoch = int(req["e"])  # edl: protocol-ok(required field of the fence op itself, not the optional response stamp; a missing "e" maps to a wire error via the dispatch guard)
        sender = str(req.get("ep") or "")
        if epoch > self._state.epoch:
            if self.role == "primary":
                self._fence_self(epoch, "repl_fence from a promoted peer")
                return {
                    "fenced": True, "role": self.role,
                }, self._NO_EVENTS
            self._primary_epoch = max(self._primary_epoch, epoch)
            return {"fenced": False, "role": self.role}, self._NO_EVENTS
        if (
            epoch == self._state.epoch
            and self.role == "primary"
            and self._fenced_by is None
            and sender
            and sender != self._advertise
            and self._advertise > sender
        ):
            self._fence_self(epoch, "equal-epoch tie lost to %s" % sender)
            return {"fenced": True, "role": self.role}, self._NO_EVENTS
        return {"fenced": False, "role": self.role}, self._NO_EVENTS


def main() -> None:
    # invoked both as ``python -m edl_tpu.store.server`` and via edl_tpu.launch
    parser = argparse.ArgumentParser(description="edl_tpu coordination store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2379)
    parser.add_argument(
        "--data_dir",
        default=None,
        help="durable state dir (snapshot + wal); restarting on the same "
        "dir recovers every key, lease and revision",
    )
    parser.add_argument(
        "--replica_dir",
        default=None,
        help="shared-storage dir (ckpt volume / PVC) receiving a snapshot "
        "copy at every compaction: a replacement store on a FRESH host "
        "with an empty --data_dir seeds itself from here (store-host "
        "loss recovery; staleness bounded by EDL_STORE_REPLICA_INTERVAL)",
    )
    parser.add_argument(
        "--follow",
        default=None,
        help="run as a WARM STANDBY of this comma-separated primary "
        "endpoint list: bootstrap from a streamed snapshot, tail the "
        "journal live, and promote (with an epoch bump that fences the "
        "old primary) if the primary stays dead past the grace window",
    )
    parser.add_argument(
        "--priority", type=int, default=1,
        help="promotion order among standbys (1 = first in line; the "
        "grace window scales with it so lower priorities defer)",
    )
    parser.add_argument(
        "--failover_grace", type=float, default=2.0,
        help="seconds the replication link must stay dead before a "
        "standby considers promotion",
    )
    parser.add_argument(
        "--advertise", default=None,
        help="endpoint other members and clients should reach this store "
        "at (default: 127.0.0.1:<port> — set it on multi-host setups)",
    )
    parser.add_argument(
        "--repl_sync_timeout", type=float, default=None,
        help="semi-sync replication: hold each client ack until every "
        "live standby applied+journaled the write, degrading ONE commit "
        "to async (metered: edl_store_repl_sync_degraded_total) after "
        "this many seconds. <=0 disables semi-sync. Default: "
        "EDL_STORE_REPL_SYNC_TIMEOUT or 0.5",
    )
    parser.add_argument(
        "--name", default="store",
        help="server label on edl_rpc_server_seconds histograms (a "
        "sharded deployment names each shard store-0, store-1, ...)",
    )
    args = parser.parse_args()
    server = StoreServer(
        args.host, args.port, data_dir=args.data_dir,
        replica_dir=args.replica_dir, follow=args.follow,
        priority=args.priority, failover_grace=args.failover_grace,
        advertise=args.advertise, repl_sync_timeout=args.repl_sync_timeout,
        name=args.name,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
