"""Event-loop TCP server exposing :class:`StoreState` over the wire protocol.

Single-threaded, selector-driven (the shape of the reference's epoll balance
server, python/edl/distill/redis/balance_server.py:39-216, applied to the
coordination store): every connection is nonblocking, frames are decoded
incrementally, watch events are pushed as server-initiated frames.

Run standalone as ``python -m edl_tpu.store.server --port 2379`` (the role
``scripts/download_etcd.sh`` + an external etcd daemon play for the
reference), or embedded in-process via ``StoreServer(port=0).start()`` —
the launcher embeds one in the leader pod.

Wire methods (see rpc/wire.py for framing):
  put(k, v, l?) / put_absent / cas(k, er, v, l?) / get(k) / range(p) /
  del(k) / del_range(p) / lease_grant(ttl) / lease_keepalive(l) /
  lease_revoke(l) / watch(p, r?) / unwatch(w) / ping / state
"""

from __future__ import annotations

import argparse
import os
import selectors
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from edl_tpu.chaos.plane import fault_point as _fault_point
from edl_tpu.obs import http as obs_http
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.rpc.wire import FrameReader, WireError, pack_frame
from edl_tpu.store.kv import Event, StoreState
from edl_tpu.utils.exceptions import EdlCompactedError, serialize_exception
from edl_tpu.utils.log import get_logger

logger = get_logger("store.server")

_FP_DISPATCH = _fault_point(
    "store.server.dispatch",
    "one store RPC server-side: delay (slow tail) or drop (conn reset)",
)
_FP_WAL = _fault_point(
    "store.server.wal", "journal append: delay (slow disk) before fsync"
)

_LEASE_SWEEP_INTERVAL = 0.2
_COMPACT_EVERY = 10_000  # journal entries between snapshots
# max replica staleness: with a replica_dir, compaction (and thus the
# replicated snapshot) is also triggered on a timer
_REPLICA_INTERVAL = float(os.environ.get("EDL_STORE_REPLICA_INTERVAL", "30"))


class _Conn:
    __slots__ = ("sock", "reader", "out", "watches", "addr", "closed")

    def __init__(self, sock: socket.socket, addr) -> None:
        self.sock = sock
        self.reader = FrameReader()
        self.out = bytearray()
        self.watches: Dict[int, str] = {}  # wid -> prefix
        self.addr = addr
        self.closed = False


class StoreServer:
    """``data_dir`` turns on durability (≙ the external etcd daemon's disk
    state in the reference): state is recovered from ``snapshot.bin`` +
    ``wal.bin`` at startup, every mutation is journaled (flush+fsync — the
    control plane is low-rate), and the journal is compacted into a fresh
    snapshot every ``_COMPACT_EVERY`` entries and on clean stop. A store
    killed -9 and restarted on the same ``data_dir`` loses at most nothing:
    clients reconnect, watches resume from their last revision (older
    resume points get a compaction error and resync), leases restart with
    a full fresh TTL (the store can't know how long it was down)."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        data_dir: Optional[str] = None,
        replica_dir: Optional[str] = None,
    ) -> None:
        from edl_tpu.chaos.plane import arm_from_env

        arm_from_env("store")  # no-op without EDL_CHAOS in the env
        self._host = host
        self._state = StoreState()
        self._data_dir = data_dir
        # Store-HOST loss answer (the one availability asymmetry vs the
        # reference's replicable etcd): every compaction also lands the
        # snapshot in ``replica_dir`` — point it at shared storage (the
        # job's ckpt volume, a PVC) and a replacement store on a FRESH
        # host seeds itself from the replica when its own data_dir is
        # empty. Time-based compaction (below) bounds replica staleness.
        if replica_dir and not data_dir:
            raise ValueError(
                "replica_dir requires data_dir: snapshots are produced by "
                "the durability layer (an in-memory store has nothing to "
                "replicate)"
            )
        self._replica_dir = replica_dir
        self._last_compact = time.monotonic()
        self._wal_file = None
        self._wal_count = 0
        self._sel = selectors.DefaultSelector()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        # observability plane: request/fanout counters + live-state
        # gauges, scraped via /metrics when EDL_OBS_PORT opts the
        # process in (obs is a process-level plane; a replacement store
        # in the same process reuses the mounted endpoint). Created
        # before recovery — _recover() compacts, which counts — and the
        # gauges' referents (_conns) before the mount, so a scrape during
        # a long WAL replay sees a sane recovering store.
        self._conns: Dict[socket.socket, _Conn] = {}
        self._m_requests = obs_metrics.counter(
            "edl_store_requests_total", "store RPCs dispatched, by method"
        )
        self._m_fanout = obs_metrics.counter(
            "edl_store_watch_events_total", "watch events pushed to clients"
        )
        self._m_compactions = obs_metrics.counter(
            "edl_store_compactions_total", "journal compactions (snapshots written)"
        )
        self._obs_gauges = obs_metrics.bind_gauges((
            ("edl_store_connections_open", "live client connections",
             lambda: len(self._conns)),
            ("edl_store_revision_seq", "current store revision",
             lambda: self._state.revision),
        ))
        self._health_fn = lambda: {
            "revision": self._state.revision,
            "conns": len(self._conns),
            "store_port": self.port,
        }
        self._obs = obs_http.start_from_env("store", health_fn=self._health_fn)
        if data_dir:
            # AFTER the bind on purpose: a losing "first pod on the host
            # wins" contender must fail on EADDRINUSE before it can touch
            # (compact, truncate) the live leader's snapshot/WAL. Recovery
            # faults are re-raised as RuntimeError so bind-contention
            # handlers (except OSError) never mistake them for a busy port.
            try:
                os.makedirs(data_dir, exist_ok=True)
                self._snap_path = os.path.join(data_dir, "snapshot.bin")
                self._wal_path = os.path.join(data_dir, "wal.bin")
                self._recover()
            except OSError as exc:
                self._listener.close()
                self._sel.close()
                raise RuntimeError(
                    "store data_dir %s unusable: %s" % (data_dir, exc)
                ) from exc
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wake pipe so stop() interrupts a sleeping select
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")

    @property
    def endpoint(self) -> str:
        return "127.0.0.1:%d" % self.port

    # -- durability --------------------------------------------------------

    def _recover(self) -> None:
        import msgpack

        if (
            not os.path.exists(self._snap_path)
            and not os.path.exists(self._wal_path)
            and self._replica_dir
            and os.path.exists(os.path.join(self._replica_dir, "snapshot.bin"))
        ):
            # fresh host, replicated state available: seed from the
            # replica (the restore-on-new-host procedure — staleness is
            # bounded by the compaction interval; leases restart fresh
            # and watch resumes past the jump resync, both by design)
            import shutil

            shutil.copyfile(
                os.path.join(self._replica_dir, "snapshot.bin"),
                self._snap_path,
            )
            logger.warning(
                "store seeded from replica %s (fresh data_dir %s)",
                self._replica_dir, self._data_dir,
            )
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    self._state.load_snapshot(
                        msgpack.unpackb(f.read(), raw=False)
                    )
            except Exception as exc:
                # A torn snapshot (e.g. a non-atomic replica filesystem
                # caught mid-replace) must not crash-loop the store: set
                # it aside and continue from whatever the WAL salvages —
                # a degraded recovery beats a control plane that can
                # never come back.
                corrupt = self._snap_path + ".corrupt"
                logger.error(
                    "snapshot %s unreadable (%s); moving to %s and "
                    "recovering from the journal alone",
                    self._snap_path, exc, corrupt,
                )
                try:
                    os.replace(self._snap_path, corrupt)
                except OSError:
                    pass
                self._state = StoreState()
        replayed = 0
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                data = f.read()
            for entry in self._salvage_wal(data):
                self._state.apply_journal(entry)
                replayed += 1
        # the event history did not survive: watches resuming from any
        # pre-restart revision must resync
        self._state._mark_history_lost()
        if replayed or os.path.exists(self._snap_path):
            logger.info(
                "store recovered from %s: rev=%d, %d wal entr%s replayed",
                self._data_dir, self._state.revision, replayed,
                "y" if replayed == 1 else "ies",
            )
        self._compact()

    @staticmethod
    def _salvage_wal(data: bytes):
        """Decode journal frames, tolerating a torn tail (crash mid-append:
        complete frames before it are all recoverable)."""
        reader = FrameReader(fault=False)  # disk replay, not network rx
        try:
            yield from reader.feed(data)
        except WireError as exc:
            logger.warning("wal tail unreadable (%s); recovered prefix", exc)

    def _compact(self) -> None:
        """Snapshot current state atomically, then truncate the journal.
        With a ``replica_dir``, the fresh snapshot is also copied there
        (best-effort: replica faults degrade availability of the
        RECOVERY path, never the live store)."""
        import msgpack

        blob = msgpack.packb(self._state.to_snapshot(), use_bin_type=True)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        if self._replica_dir:
            try:
                os.makedirs(self._replica_dir, exist_ok=True)
                rtmp = os.path.join(self._replica_dir, "snapshot.bin.tmp")
                with open(rtmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(
                    rtmp, os.path.join(self._replica_dir, "snapshot.bin")
                )
            except OSError as exc:
                logger.warning(
                    "snapshot replica %s unwritable (%s); live store "
                    "unaffected", self._replica_dir, exc,
                )
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "wb")
        self._wal_count = 0
        self._last_compact = time.monotonic()
        self._m_compactions.inc()

    def _journal(self, entries: List[dict]) -> None:
        if self._wal_file is None or not entries:
            return
        if _FP_WAL.armed:
            _FP_WAL.fire(n=len(entries))
        # fault=False: the rpc.wire.tx point must never reach the journal
        # (a "network" fault corrupting durable state); WAL faults have
        # their own store.server.wal point above
        self._wal_file.write(
            b"".join(pack_frame(e, fault=False) for e in entries)
        )
        self._wal_file.flush()
        os.fsync(self._wal_file.fileno())
        self._wal_count += len(entries)
        if self._wal_count >= _COMPACT_EVERY or (
            self._replica_dir
            and time.monotonic() - self._last_compact >= _REPLICA_INTERVAL
        ):
            self._compact()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self.serve_forever, name="edl-store", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def serve_forever(self) -> None:
        logger.info("store serving on port %d", self.port)
        last_sweep = time.monotonic()
        try:
            while not self._stop.is_set():
                timeout = _LEASE_SWEEP_INTERVAL
                deadline = self._state.next_lease_deadline()
                if deadline is not None:
                    timeout = min(timeout, max(0.0, deadline - time.monotonic()))
                for key, _ in self._sel.select(timeout):
                    if key.data == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    elif key.fileobj is self._listener:
                        self._accept()
                    else:
                        self._service(key.fileobj, key.events)
                now = time.monotonic()
                if now - last_sweep >= _LEASE_SWEEP_INTERVAL or (
                    deadline is not None and deadline <= now
                ):
                    last_sweep = now
                    expired, dead_ids = self._state.expire_leases_with_ids()
                    self._journal(
                        [{"op": "revoke", "id": lid} for lid in dead_ids]
                        + [{"op": "ev", **ev.to_wire()} for ev in expired]
                    )
                    self._fanout(expired)
                    if (
                        self._replica_dir
                        and self._wal_count > 0
                        and time.monotonic() - self._last_compact
                        >= _REPLICA_INTERVAL
                    ):
                        # a QUIET store must still honor the replica
                        # staleness bound: mutation-triggered compaction
                        # alone would strand the final pre-quiescence
                        # writes outside the replica forever
                        self._compact()
        finally:
            if self._wal_file is not None:
                self._compact()  # clean stop: durable snapshot, empty wal
                self._wal_file.close()
                self._wal_file = None
            for conn in list(self._conns.values()):
                self._close(conn)
            self._sel.unregister(self._listener)
            self._listener.close()
            self._wake_r.close()
            self._wake_w.close()
            self._sel.close()
            self._obs_gauges.release()
            obs_http.release_health("store", self._health_fn)
            logger.info("store on port %d stopped", self.port)

    # -- event loop internals ---------------------------------------------

    def _accept(self) -> None:
        try:
            sock, addr = self._listener.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock, addr)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _service(self, sock: socket.socket, events: int) -> None:
        conn = self._conns.get(sock)
        if conn is None:
            return
        if events & selectors.EVENT_READ:
            self._on_readable(conn)
        if not conn.closed and events & selectors.EVENT_WRITE:
            self._flush(conn)

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(256 * 1024)
        except BlockingIOError:
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        try:
            requests = conn.reader.feed(data)
        except (WireError, ConnectionError) as exc:
            # ConnectionError: an injected rpc.wire.rx drop — one dead
            # connection, and it must not escape into (and kill) the
            # shared event loop, same as the tx guard in _send
            logger.warning("protocol error from %s: %s", conn.addr, exc)
            self._close(conn)
            return
        for req in requests:
            self._dispatch(conn, req)
            if conn.closed:
                return

    def _send(self, conn: _Conn, payload: dict) -> None:
        if conn.closed:
            return
        try:
            frame = pack_frame(payload)
        except ConnectionError:
            # an injected tx drop means THIS connection reset mid-send; it
            # must not escape into (and kill) the shared event loop
            self._close(conn)
            return
        conn.out += frame
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        try:
            while conn.out:
                sent = conn.sock.send(conn.out)
                if sent == 0:
                    break
                del conn.out[:sent]
        except BlockingIOError:
            pass
        except OSError:
            self._close(conn)
            return
        mask = selectors.EVENT_READ
        if conn.out:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _fanout(self, events: List[Event]) -> None:
        """Push events to every connection watching a matching prefix."""
        if not events:
            return
        for conn in list(self._conns.values()):
            for wid, prefix in list(conn.watches.items()):
                matched = [e.to_wire() for e in events if e.key.startswith(prefix)]
                if matched:
                    self._m_fanout.inc(len(matched))
                    self._send(conn, {"w": wid, "ev": matched})

    # -- method dispatch ---------------------------------------------------

    def _dispatch(self, conn: _Conn, req: dict) -> None:
        rid = req.get("i")
        method = req.get("m")
        if _FP_DISPATCH.armed:
            try:
                _FP_DISPATCH.fire(method=str(method))
            except ConnectionError:
                self._close(conn)  # the peer sees a reset mid-request
                return
        handler = getattr(self, "_op_" + str(method), None)
        # sentinel for unknown methods: the label value is client data,
        # and per-value counter series would let a fuzzing client grow
        # the registry without bound
        self._m_requests.inc(
            method=str(method) if handler is not None else "<unknown>"
        )
        if handler is None:
            self._send(
                conn,
                {
                    "i": rid,
                    "ok": False,
                    "err": {"etype": "EdlStoreError", "detail": "unknown method %r" % method},
                },
            )
            return
        try:
            result, events = handler(conn, req)
        except Exception as exc:  # noqa: BLE001 — every fault maps to a wire error
            self._send(conn, {"i": rid, "ok": False, "err": serialize_exception(exc)})
            return
        if self._wal_file is not None:
            # journal BEFORE acking: a response implies the mutation is durable
            entries: List[dict] = []
            if method == "lease_grant":
                entries.append(
                    {"op": "grant", "id": result["lease"], "ttl": float(req["ttl"])}
                )
            elif method == "lease_revoke":
                entries.append({"op": "revoke", "id": req["lease"]})
            entries.extend({"op": "ev", **ev.to_wire()} for ev in events)
            self._journal(entries)
        resp = {"i": rid, "ok": True}
        resp.update(result)
        self._send(conn, resp)
        self._fanout(events)

    _NO_EVENTS: Tuple = ()

    def _op_ping(self, conn, req):
        return {}, self._NO_EVENTS

    def _op_put(self, conn, req):
        ev = self._state.put(req["k"], req["v"], req.get("l", 0))
        return {"r": ev.rev}, [ev]

    def _op_put_absent(self, conn, req):
        created, ev, existing = self._state.put_if_absent(
            req["k"], req["v"], req.get("l", 0)
        )
        if created:
            return {"created": True, "r": ev.rev}, [ev]
        return {"created": False, "cur": existing}, self._NO_EVENTS

    def _op_cas(self, conn, req):
        ok, ev = self._state.cas(req["k"], req["er"], req["v"], req.get("l", 0))
        if ok:
            return {"swapped": True, "r": ev.rev}, [ev]
        return {"swapped": False}, self._NO_EVENTS

    def _op_get(self, conn, req):
        got = self._state.get(req["k"])
        if got is None:
            return {"v": None, "r": self._state.revision}, self._NO_EVENTS
        value, mod_rev, lease = got
        return {"v": value, "mr": mod_rev, "l": lease, "r": self._state.revision}, self._NO_EVENTS

    def _op_range(self, conn, req):
        items, rev = self._state.range(req["p"])
        return {"kvs": [list(item) for item in items], "r": rev}, self._NO_EVENTS

    def _op_del(self, conn, req):
        ev = self._state.delete(req["k"])
        if ev is None:
            return {"deleted": 0}, self._NO_EVENTS
        return {"deleted": 1, "r": ev.rev}, [ev]

    def _op_del_range(self, conn, req):
        events = self._state.delete_range(req["p"])
        return {"deleted": len(events)}, events

    def _op_lease_grant(self, conn, req):
        lease = self._state.lease_grant(float(req["ttl"]))
        return {"lease": lease}, self._NO_EVENTS

    def _op_lease_keepalive(self, conn, req):
        alive = self._state.lease_keepalive(req["lease"])
        return {"alive": alive}, self._NO_EVENTS

    def _op_lease_revoke(self, conn, req):
        events = self._state.lease_revoke(req["lease"])
        return {"revoked": True}, events

    def _op_watch(self, conn, req):
        # The watch id is CLIENT-assigned (unique per connection) so the
        # client can register its handler before the first push can arrive —
        # no window where an event targets an unknown id. The backlog is
        # delivered as a push frame, written before the response and before
        # any later event, so the dispatcher sees strictly ordered history.
        wid = req["wid"]
        prefix = req["p"]
        backlog = []
        if req.get("r") is not None:
            try:
                backlog = [
                    e.to_wire() for e in self._state.history_since(req["r"], prefix)
                ]
            except ValueError as exc:
                raise EdlCompactedError(str(exc)) from exc
        conn.watches[wid] = prefix
        if backlog:
            self._send(conn, {"w": wid, "ev": backlog})
        return {"r": self._state.revision}, self._NO_EVENTS

    def _op_unwatch(self, conn, req):
        conn.watches.pop(req["wid"], None)
        return {}, self._NO_EVENTS

    def _op_state(self, conn, req):
        return {
            "rev": self._state.revision,
            "conns": len(self._conns),
        }, self._NO_EVENTS


def main() -> None:
    # invoked both as ``python -m edl_tpu.store.server`` and via edl_tpu.launch
    parser = argparse.ArgumentParser(description="edl_tpu coordination store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2379)
    parser.add_argument(
        "--data_dir",
        default=None,
        help="durable state dir (snapshot + wal); restarting on the same "
        "dir recovers every key, lease and revision",
    )
    parser.add_argument(
        "--replica_dir",
        default=None,
        help="shared-storage dir (ckpt volume / PVC) receiving a snapshot "
        "copy at every compaction: a replacement store on a FRESH host "
        "with an empty --data_dir seeds itself from here (store-host "
        "loss recovery; staleness bounded by EDL_STORE_REPLICA_INTERVAL)",
    )
    args = parser.parse_args()
    server = StoreServer(
        args.host, args.port, data_dir=args.data_dir,
        replica_dir=args.replica_dir,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
