"""Control-plane HA plumbing shared by the store server and client.

The warm-standby design (see DESIGN.md "Control-plane HA"):

- a follower ``StoreServer`` dials the primary over the ordinary wire
  protocol, bootstraps from a streamed snapshot (``repl_sync``), then
  tails journal entries live (``rl`` push frames);
- the primary publishes every member's endpoint under the
  ``/store/endpoints/`` keyspace — replicated like any other key, so a
  promoted follower still knows the whole membership, and clients can
  refresh their ordered endpoint list from whichever member they reach;
- on primary death the best-placed follower promotes itself: it bumps
  the persisted **fencing epoch**, takes slot 0 in the endpoint
  keyspace, and runs a fence campaign (``repl_fence``) against every
  other known endpoint so a resurrected stale primary refuses service
  before a fresh client can write to it.

This module holds the pieces both sides share: endpoint-list parsing,
the endpoint keyspace layout, and the one-shot probe/fence requests.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence, Union

from edl_tpu.rpc.wire import WireError, request_once
from edl_tpu.utils.log import get_logger

logger = get_logger("store.replica")

# Root (job-independent) keyspace: the store's own membership. Slot 0 is
# the primary; standbys take their priority as the slot. Keys sort
# lexically into promotion order, so "ordered endpoint list" is one range.
ENDPOINTS_PREFIX = "/store/endpoints/"


def endpoint_key(slot: int) -> str:
    return "%s%03d" % (ENDPOINTS_PREFIX, slot)


def endpoint_value(endpoint: str, epoch: int, role: str) -> bytes:
    return json.dumps(
        {"endpoint": endpoint, "epoch": epoch, "role": role, "ts": time.time()}
    ).encode()


def parse_endpoint_rows(rows) -> List[str]:
    """``range(ENDPOINTS_PREFIX)`` rows -> ordered endpoint list (slot
    order; malformed entries skipped)."""
    out: List[str] = []
    for _key, value, *_rest in rows:
        try:
            endpoint = json.loads(value)["endpoint"]
        except (ValueError, TypeError, KeyError):
            continue
        if endpoint and endpoint not in out:
            out.append(endpoint)
    return out


def parse_endpoints(spec: Union[str, Sequence[str], None]) -> List[str]:
    """Accept ``"h:p"``, ``"h:p,h:p"`` or a sequence; ordered, deduped."""
    if spec is None:
        return []
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",")]
    else:
        parts = [str(p).strip() for p in spec]
    out: List[str] = []
    for part in parts:
        if part and part not in out:
            out.append(part)
    return out


# -- one-shot control probes --------------------------------------------------


# edl: blocking-ok(0.5s-capped one-shot dial; the event-loop caller is a standby weighing promotion — the primary it would otherwise serve behind is already dead)
def probe_status(endpoint: str, timeout: float = 0.5) -> Optional[Dict]:
    """Ask ``endpoint`` for its replication status (role, epoch,
    revision). ``None`` when unreachable or not a store."""
    try:
        resp = request_once(
            endpoint, {"i": 1, "m": "repl_status"}, timeout=timeout
        )
    except (OSError, WireError, ValueError):
        return None
    if not resp.get("ok"):
        return None
    return resp


def send_fence(
    endpoint: str, epoch: int, sender: str = "", timeout: float = 0.5
) -> Optional[Dict]:
    """Deliver a fencing epoch to ``endpoint``. The receiver compares: a
    primary seeing a HIGHER epoch fences itself (every subsequent client
    request is rejected with ``EdlFencedError``); a receiver whose own
    epoch is higher answers with it, telling the CALLER it is the stale
    one; an EQUAL-epoch primary-vs-primary contact (two standbys promoted
    concurrently) tie-breaks on ``sender`` — the lexically larger
    advertise endpoint loses, on both sides of the exchange, so exactly
    one survives. ``None`` when unreachable."""
    try:
        return request_once(
            endpoint,
            {"i": 1, "m": "repl_fence", "e": int(epoch), "ep": sender},
            timeout=timeout,
        )
    except (OSError, WireError, ValueError):
        return None
