"""Keyspace sharding for the coordination store (DESIGN.md "Sharded
control plane").

One :class:`~edl_tpu.store.server.StoreServer` (plus its warm standbys —
the PR-3 replication/failover machinery, now with semi-sync ack) is one
**shard**. The keyspace is partitioned across shards with the existing
consistent-hash ring (``edl_tpu/discovery/consistent_hash.py``), and the
topology is itself stored IN the store, the same way endpoints are:

- **Shard map.** ``/store/shards/{idx:03d}`` rows on the META shard
  (shard 0) name every shard and its ordered endpoint list (primary
  first, standbys after — the same ordered-list convention clients
  already use for ``/store/endpoints/``). Clients bootstrap by dialing
  any seed endpoint of the meta shard, reading the map, then dialing
  the rest; each per-shard client keeps refreshing its own shard's
  ``/store/endpoints/`` exactly as before, so per-shard failover needs
  no map update.
- **Routing rule.** A key routes by its *routing token*: the first two
  path components (``/{job_id}/{service}``) — the granularity every
  read-then-watch consumer (``discovery/registry.py`` ServiceWatch)
  already operates at, so a service's range+watch lands on ONE shard
  and per-shard revisions stay coherent for resume. Keys with fewer
  components route by the whole key. The ``/store/...`` system keyspace
  is pinned to the meta shard (the map must be findable before the
  ring exists).
- **Prefix routing.** A range/watch prefix maps to a single shard iff
  it pins the full routing token (contains the token-closing third
  ``/``); anything shorter fans out to every shard and merges.

Per-shard fencing epochs come for free: each shard is its own
replication group with its own persisted epoch, probes and fence
campaign.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence, Tuple

# Shard-map keyspace: rows live on the META shard (index 0). Like
# /store/endpoints/, the keys sort lexically into shard order.
SHARDS_PREFIX = "/store/shards/"
META_PREFIX = "/store/"


def shard_key(idx: int) -> str:
    return "%s%03d" % (SHARDS_PREFIX, idx)


def shard_name(idx: int) -> str:
    return "shard-%d" % idx


def shard_value(idx: int, endpoints: Sequence[str]) -> bytes:
    return json.dumps({
        "shard": int(idx),
        "name": shard_name(idx),
        "endpoints": list(endpoints),
        "ts": time.time(),
    }).encode()


def parse_shard_rows(rows) -> List[Tuple[str, List[str]]]:
    """``range(SHARDS_PREFIX)`` rows -> ordered ``(name, endpoints)``
    list (slot order; malformed rows skipped)."""
    out: List[Tuple[str, List[str]]] = []
    for _key, value, *_rest in rows:
        try:
            doc = json.loads(value)
            name = str(doc["name"])
            endpoints = [str(e) for e in doc["endpoints"] if e]
        except (ValueError, TypeError, KeyError):
            continue
        if name and endpoints:
            out.append((name, endpoints))
    return out


def publish_shard_map(client, shard_endpoints: Sequence[Sequence[str]]) -> None:
    """Write the shard map through ``client`` (which must reach the meta
    shard — any client does before the map exists, since everything is
    one shard then)."""
    for idx, endpoints in enumerate(shard_endpoints):
        client.put(shard_key(idx), shard_value(idx, endpoints))


def route_token(key: str) -> Optional[str]:
    """The routing token of ``key``: its first two path components, or
    the whole key when shorter. ``None`` pins a ``/store/...`` system
    key to the meta shard."""
    if key.startswith(META_PREFIX):
        return None
    parts = key.split("/", 3)
    if len(parts) >= 4:
        return "/".join(parts[:3])
    return key


def route_prefix(prefix: str) -> Tuple[bool, Optional[str]]:
    """``(single, token)`` for a range/watch prefix: ``single`` is True
    when the prefix maps to exactly one shard — it pins the full routing
    token (``/{job}/{service}/...``) or lives in the meta keyspace —
    else the caller must fan out to every shard and merge."""
    if prefix.startswith(META_PREFIX):
        return True, None
    parts = prefix.split("/", 3)
    if len(parts) >= 4:
        return True, "/".join(parts[:3])
    return False, None
